"""Durability discipline (v7): durable writes route through common/durable.py.

r18 landed the durable control plane (fsync'd journal, pod registry,
manifest) and an incident shape to go with it: a membership record that a
crash left in NEITHER the old journal nor the rotated one.  The root cause
class — hand-rolled publish/append sequences that each get fsync ordering
*almost* right — is exactly what a linter can retire.  One canonical home
(``common/durable.py``) now owns the two durable write shapes; these two
rules make routing through it mandatory, in the established static-pass +
runtime-sanitizer pattern (lock-order/locksan, shared-state/racesan,
jit-*/jitsan; the runtime twin here is ``common/crashsan.py``):

- ``durable-write-discipline``
    A write touching a path derived from a declared durable constant — a
    module-level string constant whose assignment line carries
    ``# durable-file`` — must route through ``common/durable.py``.
    Derivation is tracked lexically: direct references (``JOURNAL_FILENAME``
    or ``journal_mod.JOURNAL_FILENAME``), locals assigned from expressions
    containing one, and ``self.<attr>`` attributes any method of the class
    assigns from one (``self._path = os.path.join(d, METRICS_FILENAME)``
    taints ``self._path`` class-wide).  Flagged shapes:

    * builtin ``open`` in a write/append mode (or a dynamic mode) on a
      tainted path — the raw-write bypass;
    * ``os.open`` with write-flavored flags (O_WRONLY/O_RDWR/O_APPEND/
      O_CREAT/O_TRUNC) on a tainted path;
    * ``os.replace`` / ``os.rename`` with ANY path argument, tainted or
      not — a rename outside durable.py has no directory fsync, so the
      rename itself can be lost by a crash (the r18 incident's second
      half); route through ``atomic_publish`` / ``atomic_replace``;
    * a hand-rolled ``<path> + ".tmp"`` temp name anywhere — it lacks the
      thread-unique component ``durable.tmp_path`` provides, so two
      writers interleave on one temp file; also the tell of a hand-rolled
      publish sequence.

- ``recovery-read-discipline``
    A function annotated ``# recovery-path`` (def line or the contiguous
    comment-only block above — the ``# hot-path`` placement convention) is
    a crash-recovery reader: what it reads may legally end mid-line (torn
    final append) and its tolerance window is a contract.  Raw read-mode
    ``open`` inside one is a finding — route through the shared
    torn-tolerant readers ``durable.read_wal`` / ``read_json_tolerant`` so
    every recovery path shares ONE definition of "legal crash artifact".
    Conversely, a read-mode ``open`` of a tainted path in a function NOT
    annotated ``# recovery-path`` is a finding too: reading a durable file
    without declaring the recovery contract is how silent-corruption
    tolerance creeps in.

Exempt by construction: ``common/durable.py`` (the one legal home of the
primitives) and ``common/crashsan.py`` (its runtime twin must forge crash
states with raw syscalls).  ``tests/`` are outside the lint scope as ever.

Blind spots (the crashsan matrix covers them at runtime): paths that reach
a writer through function PARAMETERS (taint is per-module lexical +
class-attr), dynamic path construction (``getattr``, dict-of-paths), and
fsync *ordering* inside a compliant-looking sequence — the static rules
prove the routing, the sanitizer proves the on-disk crash states recover.

Waive with ``# graftlint: allow[<rule>] <reason>`` on the finding's line
or a comment-only line above (e.g. metrics.py's advisory flush-only
appends, whose reader is torn-tolerant by the same contract).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain

#: The canonical durable-write home and its runtime twin: the only files
#: allowed to spell the raw publish/append/rename sequences.
EXEMPT_MODULE_SUFFIXES = ("common/durable.py", "common/crashsan.py")

_DURABLE_FILE = re.compile(r"#\s*durable-file\b")
_RECOVERY_PATH = re.compile(r"#\s*recovery-path\b")

#: os.open flag names that make the fd write-flavored.
_WRITE_FLAGS = {"O_WRONLY", "O_RDWR", "O_APPEND", "O_CREAT", "O_TRUNC"}


def _is_exempt(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(p.endswith(suffix) for suffix in EXEMPT_MODULE_SUFFIXES)


def _annotated(src: SourceFile, line: int, marker: re.Pattern) -> bool:
    """Marker on ``line`` or anywhere in the contiguous block of
    comment-only lines directly above it (the ``# hot-path`` placement
    convention — markers may share the block with prose)."""
    comment = src.comments.get(line)
    if comment is not None and marker.search(comment):
        return True
    cand = line - 1
    while cand in src.comment_only_lines:
        if marker.search(src.comments[cand]):
            return True
        cand -= 1
    return False


def collect_durable_constants(
    sources: Sequence[SourceFile],
) -> Dict[str, List[Tuple[str, int, str]]]:
    """Project-wide harvest of the declared durable constants:
    ``name -> [(path, line, filename_value), ...]``.  A durable constant is
    a module-level ``NAME = "<str>"`` whose assignment line carries
    ``# durable-file``; the NAME is the taint root everywhere (constants
    are imported by name across modules — ``journal_mod.JOURNAL_FILENAME``
    taints exactly like a local reference)."""
    out: Dict[str, List[Tuple[str, int, str]]] = {}
    for src in sources:
        for node in src.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                continue
            if not _annotated(src, node.lineno, _DURABLE_FILE):
                continue
            out.setdefault(node.targets[0].id, []).append(
                (src.path, node.lineno, node.value.value)
            )
    return out


def _scope_nodes(fn_body) -> Iterable[ast.AST]:
    """Every node under ``fn_body``, PRUNING nested def/lambda scopes (the
    repo-wide traversal stance — deferred execution owns its own
    judgement)."""
    stack: List[ast.AST] = list(fn_body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class _Taint:
    """Per-file taint model over the durable-constant roots: which class
    attributes and (per function) which locals hold a durable path."""

    def __init__(self, src: SourceFile, consts: Set[str]):
        self.consts = consts
        #: "<ClassName>" -> set of tainted self-attribute names.
        self.attrs: Dict[str, Set[str]] = {}
        for node in src.tree.body:
            if isinstance(node, ast.ClassDef):
                self._class_attrs(node)

    def _class_attrs(self, cls: ast.ClassDef) -> None:
        tainted: Set[str] = set()
        # Two sweeps: self.b = f(self.a) where self.a was tainted later in
        # source order (assignment order across methods is runtime order,
        # not lexical order).
        for _ in range(2):
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for n in _scope_nodes(meth.body):
                    if not isinstance(n, ast.Assign):
                        continue
                    if not self._expr_tainted(n.value, tainted, set()):
                        continue
                    for t in n.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            tainted.add(t.attr)
        self.attrs[cls.name] = tainted

    def _expr_tainted(
        self, node: ast.AST, attr_taint: Set[str], local_taint: Set[str]
    ) -> bool:
        for s in ast.walk(node):
            if isinstance(s, ast.Name) and (
                s.id in self.consts or s.id in local_taint
            ):
                return True
            if isinstance(s, ast.Attribute):
                if s.attr in self.consts:
                    return True  # journal_mod.JOURNAL_FILENAME
                if (
                    s.attr in attr_taint
                    and isinstance(s.value, ast.Name)
                    and s.value.id == "self"
                ):
                    return True
        return False

    def function_locals(
        self, fn, cls_name: Optional[str]
    ) -> Set[str]:
        """Locals of ``fn`` assigned from a tainted expression (two sweeps
        for chained derivation: ``p = join(d, NAME); q = p + ".bak"``)."""
        attr_taint = self.attrs.get(cls_name or "", set())
        local: Set[str] = set()
        for _ in range(2):
            for n in _scope_nodes(fn.body):
                if not isinstance(n, ast.Assign):
                    continue
                if not self._expr_tainted(n.value, attr_taint, local):
                    continue
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        local.add(t.id)
        return local

    def tainted(
        self, node: ast.AST, cls_name: Optional[str], local_taint: Set[str]
    ) -> bool:
        return self._expr_tainted(
            node, self.attrs.get(cls_name or "", set()), local_taint
        )


def _open_mode(node: ast.Call) -> Optional[str]:
    """The builtin-open mode string: second positional or ``mode=``;
    ``"r"`` when absent; ``None`` when dynamic (not a string constant)."""
    mode_expr: Optional[ast.expr] = None
    if len(node.args) >= 2:
        mode_expr = node.args[1]
    else:
        for kw in node.keywords:
            if kw.arg == "mode":
                mode_expr = kw.value
    if mode_expr is None:
        return "r"
    if isinstance(mode_expr, ast.Constant) and isinstance(mode_expr.value, str):
        return mode_expr.value
    return None


def _is_write_mode(mode: Optional[str]) -> bool:
    """Dynamic modes count as writes — the conservative direction for a
    durability gate."""
    if mode is None:
        return True
    return any(c in mode for c in "wax+")


def _os_open_writes(node: ast.Call) -> bool:
    """True when an ``os.open`` call's flags reference a write flag."""
    for arg in node.args[1:]:
        for s in ast.walk(arg):
            if isinstance(s, ast.Attribute) and s.attr in _WRITE_FLAGS:
                return True
            if isinstance(s, ast.Name) and s.id in _WRITE_FLAGS:
                return True
    return False


def _iter_functions(src: SourceFile):
    """``(fn, class_name)`` for every function/method, nested defs
    included (each is its own taint scope)."""
    def walk(body, cls_name):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node, cls_name
                yield from walk(node.body, cls_name)
            elif isinstance(node, ast.ClassDef):
                yield from walk(node.body, node.name)
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For, ast.While)):
                # Functions defined under module-level control flow (the
                # compat-shim pattern) still get judged.
                yield from walk(
                    getattr(node, "body", [])
                    + getattr(node, "orelse", [])
                    + getattr(node, "finalbody", []),
                    cls_name,
                )
    yield from walk(src.tree.body, None)


class DurableWriteDisciplinePass(LintPass):
    name = "durable-write-discipline"
    description = (
        "writes to '# durable-file' paths route through common/durable.py; "
        "no raw renames or hand-rolled '.tmp' names anywhere"
    )

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        consts = set(collect_durable_constants(files))
        findings: List[Finding] = []
        for src in files:
            if _is_exempt(src.path):
                continue
            taint = _Taint(src, consts)
            # Unconditional sub-rules walk the whole module (renames and
            # hand-rolled temp names are findings at module scope too).
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Call):
                    chain = attr_chain(node.func)
                    if chain in ("os.replace", "os.rename"):
                        findings.append(Finding(
                            self.name, src.path, node.lineno,
                            f"raw {chain} publishes without the directory "
                            "fsync — a crash can lose the rename itself; "
                            "route through durable.atomic_publish / "
                            "atomic_replace",
                        ))
                elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
                    if (
                        isinstance(node.right, ast.Constant)
                        and node.right.value == ".tmp"
                    ):
                        findings.append(Finding(
                            self.name, src.path, node.lineno,
                            "hand-rolled '+ \".tmp\"' temp name lacks the "
                            "thread-unique component — two writers "
                            "interleave on one temp file; use "
                            "durable.tmp_path (or atomic_publish, which "
                            "names its own temp)",
                        ))
            # Taint-scoped sub-rules per function scope.
            if not consts:
                continue
            for fn, cls_name in _iter_functions(src):
                local = taint.function_locals(fn, cls_name)
                for node in _scope_nodes(fn.body):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    if (
                        isinstance(f, ast.Name)
                        and f.id == "open"
                        and node.args
                        and taint.tainted(node.args[0], cls_name, local)
                        and _is_write_mode(_open_mode(node))
                    ):
                        findings.append(Finding(
                            self.name, src.path, node.lineno,
                            "raw write-mode open() of a '# durable-file' "
                            "path bypasses the durable-write shapes (no "
                            "single-write guarantee, no fsync, no atomic "
                            "publish); route through durable.atomic_publish"
                            " / append_durable",
                        ))
                    elif (
                        attr_chain(f) == "os.open"
                        and node.args
                        and taint.tainted(node.args[0], cls_name, local)
                        and _os_open_writes(node)
                    ):
                        findings.append(Finding(
                            self.name, src.path, node.lineno,
                            "raw write-flavored os.open of a "
                            "'# durable-file' path bypasses "
                            "common/durable.py; use durable.open_append / "
                            "atomic_publish",
                        ))
        return findings


class RecoveryReadDisciplinePass(LintPass):
    name = "recovery-read-discipline"
    description = (
        "'# recovery-path' functions read durable files only through "
        "durable.read_wal / read_json_tolerant; durable files are read "
        "only from annotated recovery paths"
    )

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        consts = set(collect_durable_constants(files))
        findings: List[Finding] = []
        for src in files:
            if _is_exempt(src.path):
                continue
            taint = _Taint(src, consts)
            for fn, cls_name in _iter_functions(src):
                is_recovery = _annotated(src, fn.lineno, _RECOVERY_PATH)
                local = taint.function_locals(fn, cls_name) if consts else set()
                for node in _scope_nodes(fn.body):
                    if not (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "open"
                        and node.args
                    ):
                        continue
                    mode = _open_mode(node)
                    if _is_write_mode(mode):
                        continue  # the write rule's jurisdiction
                    if is_recovery:
                        findings.append(Finding(
                            self.name, src.path, node.lineno,
                            f"raw open() inside a '# recovery-path' "
                            f"function {fn.name}(): crash artifacts (torn "
                            "final line, absent file) need ONE shared "
                            "tolerance definition — read through "
                            "durable.read_wal / read_json_tolerant, or "
                            "waive with the reasoned contract",
                        ))
                    elif consts and taint.tainted(node.args[0], cls_name, local):
                        findings.append(Finding(
                            self.name, src.path, node.lineno,
                            f"{fn.name}() reads a '# durable-file' path "
                            "without the '# recovery-path' annotation — "
                            "durable files may legally hold crash "
                            "artifacts; declare the recovery contract and "
                            "read through durable.read_wal / "
                            "read_json_tolerant",
                        ))
        return findings


#: durable.py call names that WRITE (for the --durables inventory).
_DURABLE_WRITE_API = {
    "atomic_publish", "atomic_publish_json", "atomic_replace",
    "append_durable", "open_append",
}
_DURABLE_READ_API = {"read_wal", "read_json_tolerant"}


def durables_inventory(sources: Sequence[SourceFile]) -> dict:
    """The ``--durables`` dump: every declared durable constant with its
    declaration sites, the functions that write through durable.py while
    referencing it, and its ``# recovery-path`` readers.  The inventory is
    derived per-module-lexically like the taint itself, so it shows the
    same world the rules judge — plus one crediting widening the rules
    don't need: in a constant's DECLARING module, any function calling the
    durable write/read API (or annotated ``# recovery-path``) counts even
    without a lexical constant reference, because there the path typically
    arrives through a constructor parameter (``MasterJournal(path)``) the
    lexical taint cannot see."""
    consts = collect_durable_constants(sources)
    inv: Dict[str, dict] = {
        name: {
            "file": sites[0][2],
            "declared": [f"{p}:{ln}" for p, ln, _v in sites],
            "writers": [],
            "recovery_readers": [],
        }
        for name, sites in sorted(consts.items())
    }
    const_names = set(consts)
    for src in sources:
        if _is_exempt(src.path):
            continue
        taint = _Taint(src, const_names)
        for fn, cls_name in _iter_functions(src):
            refs: Set[str] = set()
            for n in _scope_nodes(fn.body):
                if isinstance(n, ast.Name) and n.id in const_names:
                    refs.add(n.id)
                elif isinstance(n, ast.Attribute) and n.attr in const_names:
                    refs.add(n.attr)
            # A method touching a tainted self-attr references whatever
            # constants tainted that attr's class; attribute: constant
            # mapping is not tracked, so attribute-only references credit
            # every constant the class derives from (coarse but honest —
            # classes here derive from exactly one).
            attr_taint = taint.attrs.get(cls_name or "", set())
            touches_attr = any(
                isinstance(n, ast.Attribute)
                and n.attr in attr_taint
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
                for n in _scope_nodes(fn.body)
            )
            if touches_attr and cls_name is not None:
                for name in const_names:
                    for p, _ln, _v in consts[name]:
                        if p == src.path:
                            refs.add(name)
            qual = f"{src.path}:{fn.lineno} {fn.name}"
            writes = reads = False
            for n in _scope_nodes(fn.body):
                if isinstance(n, ast.Call):
                    tail = attr_chain(n.func).split(".")[-1]
                    if tail in _DURABLE_WRITE_API:
                        writes = True
                    elif tail in _DURABLE_READ_API:
                        reads = True
            recovery = _annotated(src, fn.lineno, _RECOVERY_PATH)
            if writes or reads or recovery:
                # Declaring-module crediting (see docstring).
                for name in const_names:
                    if any(p == src.path for p, _ln, _v in consts[name]):
                        refs.add(name)
            if not refs:
                continue
            for name in sorted(refs):
                if name not in inv:
                    continue
                if writes:
                    inv[name]["writers"].append(qual)
                if recovery or reads:
                    inv[name]["recovery_readers"].append(qual)
    for rec in inv.values():
        rec["writers"] = sorted(set(rec["writers"]))
        rec["recovery_readers"] = sorted(set(rec["recovery_readers"]))
    return inv
