"""compat-shim: the moving jax API surface is shimmed in exactly one place.

``common/jax_compat.py`` owns every version-sensitive jax spelling
(shard_map's check_vma/check_rep rename, ``lax.axis_size``'s absence on
0.4.x, ``jax.distributed.initialize`` kwarg drift).  r6 found the last raw
``shard_map`` call site by hand (tools/ragged_smoke.py); this pass makes
the rule mechanical: outside the shim module, the following are findings —

- ``from jax.experimental.shard_map import ...`` / ``import
  jax.experimental.shard_map``
- ``jax.shard_map`` attribute use
- ``jax.distributed.initialize(...)`` call sites (route through
  ``jax_compat.distributed_initialize``)
- ``lax.axis_size`` / ``jax.lax.axis_size`` attribute use (route through
  ``jax_compat.axis_size``)
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain

#: The one module allowed to spell the raw APIs.
SHIM_MODULE_SUFFIX = "common/jax_compat.py"

_FORBIDDEN_ATTR_CHAINS = {
    "jax.shard_map": "use elasticdl_tpu.common.jax_compat.shard_map",
    "jax.distributed.initialize": (
        "use elasticdl_tpu.common.jax_compat.distributed_initialize"
    ),
    "lax.axis_size": "use elasticdl_tpu.common.jax_compat.axis_size",
    "jax.lax.axis_size": "use elasticdl_tpu.common.jax_compat.axis_size",
}


class CompatShimPass(LintPass):
    name = "compat-shim"
    description = (
        "raw shard_map / jax.distributed.initialize / lax.axis_size only "
        "inside common/jax_compat.py"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        if src.path.replace("\\", "/").endswith(SHIM_MODULE_SUFFIX):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.startswith("jax.experimental.shard_map"):
                    findings.append(Finding(
                        self.name, src.path, node.lineno,
                        "raw shard_map import bypasses the version shim — "
                        "use elasticdl_tpu.common.jax_compat.shard_map",
                    ))
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("jax.experimental.shard_map"):
                        findings.append(Finding(
                            self.name, src.path, node.lineno,
                            "raw shard_map import bypasses the version shim "
                            "— use elasticdl_tpu.common.jax_compat.shard_map",
                        ))
            elif isinstance(node, ast.Attribute):
                chain = attr_chain(node)
                fix = _FORBIDDEN_ATTR_CHAINS.get(chain)
                if fix is not None:
                    findings.append(Finding(
                        self.name, src.path, node.lineno,
                        f"raw {chain} bypasses the version shim — {fix}",
                    ))
        return findings
