"""jit-discipline (v6): compile & transfer discipline over the jit boundary.

Every perf number of record rides an unenforced contract: the jitted step
compiles ONCE per declared variant and its outputs stay on device until a
deliberate, accounted fetch.  r15 proved mask flips recompile-free and the
r11 donation story assumes stable jit identity — but nothing gated either,
and one shape drift or accidental ``np.asarray`` on a hot path quietly
halves throughput.  Three rules, in the established static-pass +
runtime-sanitizer pattern (lock-order/locksan, shared-state/racesan; the
runtime twin here is ``common/jitsan.py``):

- ``jit-shim``       raw ``jax.jit`` / ``jax.pjit`` (attribute use and
                     ``from jax import jit`` aliases) only inside
                     ``common/jax_compat.py``; every other site routes
                     through ``jax_compat.jit_compiled`` /
                     ``jit_donating`` — and those call sites must declare
                     ``name=`` (the jitsan registry, the
                     ``edl_jit_compiles_total{fn=}`` gauge label, and the
                     LINT artifact's budget table all key on it).

- ``jit-stability``  a jit created inside a per-call function body (or
                     loop) builds a FRESH compile cache on every
                     invocation — every prior compile is thrown away and
                     paid again.  Flagged shapes: the jit result invoked
                     directly (``jit_compiled(f, ...)(x)``) or through a
                     local that the same function then calls.  Clean
                     shapes: bound at module level, memoized onto
                     ``self.<attr>``, stored into a cache subscript, or
                     returned/handed out (builder pattern — the caller
                     owns the binding; the trainer's ``_structured``
                     memo is exactly this).

- ``transfer-discipline``
                     a device->host materialization — ``.item()``,
                     ``.tolist()``, ``jax.device_get``, ``np.asarray`` /
                     ``np.array``, ``int()`` / ``float()`` — applied to a
                     value flowing from a jit boundary must not be
                     reachable from a ``# hot-path`` function outside a
                     ``phases.phase(...)`` boundary.  "Flowing from a jit
                     boundary": assigned from a call to a function whose
                     ``def`` line carries ``# jit-boundary`` (or that
                     provably returns such a value — inferred as a
                     fixpoint over return statements), or from calling a
                     local bound to a jit.  Call targets resolve over the
                     v2 call graph PLUS the v5 constructor-type layer
                     (``self.trainer.train_step(...)`` edges into
                     Trainer), and materializing helpers propagate to
                     their hot callers with a witness chain, exactly like
                     ``blocking-propagation``.  Direct ``.item()`` /
                     ``device_get`` in the hot body stay
                     ``hot-path-sync`` findings too (one rule per failure
                     shape); this rule adds the dataflow- and
                     callee-chain-scoped half r7 could not express.

Blind spots (covered by the runtime twin: jitsan's per-site lowering
budget and the optional ``jax.transfer_guard`` window around worker
dispatch): values materialized through function PARAMETERS (the
jit-flow tracking is per-function lexical), dynamic dispatch, containers
of device values, and shape drift itself — the static passes prove the
binding discipline, the sanitizer proves the compile count.

Waive with ``# graftlint: allow[<rule>] <reason>`` on the finding's line;
a waived materialization does not propagate (the reason covers the call
however deep the caller sits — the blocking-propagation stance).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from elasticdl_tpu.analysis.callgraph import shared_graph
from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain
from elasticdl_tpu.analysis.hot_path import is_phase_context
from elasticdl_tpu.analysis.import_hygiene import _module_name
from elasticdl_tpu.analysis.thread_map import shared_thread_map

#: The one module allowed to spell raw jax.jit.
SHIM_MODULE_SUFFIX = "common/jax_compat.py"

#: Shim spellings whose call sites carry the name=/expected_variants=
#: declaration (the jitsan registry contract).
JIT_FAMILY = ("jit_compiled", "jit_donating")

_RAW_JIT_CHAINS = {"jax.jit", "jax.pjit"}

_JIT_BOUNDARY = re.compile(r"#\s*jit-boundary\b")

_TRANSFER_CASTS = {"int", "float"}
_TRANSFER_ARRAY_CHAINS = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
}


def _is_jit_boundary_annotated(src: SourceFile, line: int) -> bool:
    """``# jit-boundary`` on the def line or the contiguous comment-only
    block above it (the ``# hot-path`` placement convention)."""
    comment = src.comments.get(line)
    if comment is not None and _JIT_BOUNDARY.search(comment):
        return True
    cand = line - 1
    while cand in src.comment_only_lines:
        if _JIT_BOUNDARY.search(src.comments[cand]):
            return True
        cand -= 1
    return False


def _scope_nodes(fn) -> Iterable[ast.AST]:
    """Every node of ``fn``'s own body, PRUNING nested def/lambda scopes
    — deferred execution owns its own judgement (the repo-wide traversal
    stance; ast.walk would leak nested returns/calls into the enclosing
    function's model)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _jit_call_kind(node: ast.Call) -> Optional[str]:
    """``"raw"`` for jax.jit/jax.pjit spellings, ``"shim"`` for the
    jax_compat family, else None."""
    f = node.func
    chain = attr_chain(f)
    if chain in _RAW_JIT_CHAINS:
        return "raw"
    tail = chain.split(".")[-1] if chain else ""
    if tail in JIT_FAMILY or (
        isinstance(f, ast.Name) and f.id in JIT_FAMILY
    ):
        return "shim"
    # ``from jax import jit`` smuggles the raw spelling past the chain
    # check; the import itself is flagged by JitShimPass, and the bare
    # ``jit(...)`` call still counts for stability judgement.
    if isinstance(f, ast.Name) and f.id in ("jit", "pjit"):
        return "raw"
    return None


def _kwarg(node: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _param_defaults(fn) -> Dict[str, int]:
    """Int defaults of a function's parameters — the resolution table for
    a ``expected_variants=<param>`` spelling (the trainer's builders pass
    their ``variant_budget: int = 1`` through)."""
    args = fn.args
    out: Dict[str, int] = {}
    pos = args.posonlyargs + args.args
    for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        if isinstance(d, ast.Constant) and isinstance(d.value, int):
            out[a.arg] = int(d.value)
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if isinstance(d, ast.Constant) and isinstance(d.value, int):
            out[a.arg] = int(d.value)
    return out


def declared_sites(sources: Sequence[SourceFile]) -> Dict[str, dict]:
    """Static harvest of the jit_compiled/jit_donating declarations:
    ``name`` -> {"budget": <int>, "sites": [...], "dynamic": bool}.
    A non-constant ``expected_variants`` resolves through the enclosing
    function's parameter default when the spelling is a plain parameter
    name (``expected_variants=variant_budget`` with ``variant_budget:
    int = 1`` — the trainer's builder shape; recorded with
    ``dynamic: true`` since a caller may override upward, e.g. the
    serving bucket count), and falls back to ``None`` only when truly
    unresolvable.  Stamped into the LINT artifact next to the jitsan
    runtime stats so the declared contract and the measured compile
    counts live in one place (tools/bench_regress.py gates the two
    against each other)."""
    out: Dict[str, dict] = {}

    def visit_calls(body_owner, defaults: Dict[str, int]) -> None:
        for node in _scope_nodes(body_owner):
            if not (
                isinstance(node, ast.Call)
                and _jit_call_kind(node) == "shim"
            ):
                continue
            name_kw = _kwarg(node, "name")
            if not (
                isinstance(name_kw, ast.Constant)
                and isinstance(name_kw.value, str)
            ):
                continue
            budget_kw = _kwarg(node, "expected_variants")
            dynamic = False
            if budget_kw is None:
                budget: Optional[int] = 1  # the wrapper's own default
            elif isinstance(budget_kw, ast.Constant) and isinstance(
                budget_kw.value, int
            ):
                budget = int(budget_kw.value)
            elif isinstance(budget_kw, ast.Name) and (
                budget_kw.id in defaults
            ):
                budget = defaults[budget_kw.id]
                dynamic = True
            else:
                budget = None
                dynamic = True
            rec = out.setdefault(
                name_kw.value, {"budget": 0, "sites": [], "dynamic": False}
            )
            rec["sites"].append(f"{src.path}:{node.lineno}")
            rec["dynamic"] = rec["dynamic"] or dynamic
            if budget is None:
                rec["budget"] = None  # unresolvable expression
            elif rec["budget"] is not None:
                rec["budget"] = max(rec["budget"], budget)

    for src in sources:
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_calls(node, _param_defaults(node))
        # Module-level binds (no enclosing parameters to resolve against).
        mod_scope = ast.Module(body=src.tree.body, type_ignores=[])
        visit_calls(mod_scope, {})
    return {k: out[k] for k in sorted(out)}


class JitShimPass(LintPass):
    name = "jit-shim"
    description = (
        "raw jax.jit/jax.pjit only inside common/jax_compat.py; "
        "jit_compiled/jit_donating call sites declare name="
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        in_shim = src.path.replace("\\", "/").endswith(SHIM_MODULE_SUFFIX)
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom) and not in_shim:
                mod = node.module or ""
                if mod == "jax":
                    for alias in node.names:
                        if alias.name in ("jit", "pjit"):
                            findings.append(Finding(
                                self.name, src.path, node.lineno,
                                f"raw 'from jax import {alias.name}' "
                                "bypasses the compile shim — use "
                                "elasticdl_tpu.common.jax_compat."
                                "jit_compiled/jit_donating (jitsan "
                                "accounting and the declared variant "
                                "budget live there)",
                            ))
                elif mod.startswith("jax.experimental.pjit"):
                    findings.append(Finding(
                        self.name, src.path, node.lineno,
                        "raw pjit import bypasses the compile shim — use "
                        "elasticdl_tpu.common.jax_compat.jit_compiled",
                    ))
            elif isinstance(node, ast.Attribute) and not in_shim:
                chain = attr_chain(node)
                if chain in _RAW_JIT_CHAINS:
                    findings.append(Finding(
                        self.name, src.path, node.lineno,
                        f"raw {chain} bypasses the compile shim — use "
                        "elasticdl_tpu.common.jax_compat.jit_compiled/"
                        "jit_donating so the compile is named, budgeted, "
                        "and jitsan-accounted",
                    ))
            elif isinstance(node, ast.Call) and _jit_call_kind(node) == "shim":
                name_kw = _kwarg(node, "name")
                if name_kw is None:
                    findings.append(Finding(
                        self.name, src.path, node.lineno,
                        "jit_compiled/jit_donating call declares no name= "
                        "— the jitsan registry, the edl_jit_compiles_total "
                        "gauge label, and the LINT artifact's budget table "
                        "all key on it",
                    ))
        return findings


class JitStabilityPass(LintPass):
    name = "jit-stability"
    description = (
        "a jit created inside a per-call function body (or loop) and "
        "invoked there builds a fresh compile cache every invocation — "
        "bind it module-level, memoize on self.<attr>, or return it"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_scope(src, node, findings)
        return findings

    def _check_scope(self, src, fn, findings: List[Finding]) -> None:
        """One function scope (nested defs are their own scopes via the
        outer ast.walk).  Module scope is exempt by construction: a
        module-level bind runs once per process."""
        jit_locals: Dict[str, int] = {}  # local name -> jit creation line
        for node in _scope_nodes(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _jit_call_kind(node.value) is not None:
                    if len(node.targets) == 1 and isinstance(
                        node.targets[0], ast.Name
                    ):
                        jit_locals[node.targets[0].id] = node.value.lineno
                    # self.<attr> / cache[key] targets: ownership escapes
                    # the call frame (memo/bucket patterns) — clean.
            elif isinstance(node, ast.Call):
                inner = node.func
                if isinstance(inner, ast.Call) and _jit_call_kind(inner):
                    findings.append(Finding(
                        self.name, src.path, inner.lineno,
                        f"jit created and invoked in one expression inside "
                        f"{fn.name}(): every call of {fn.name} pays a "
                        "fresh trace+compile — bind the jit module-level, "
                        "memoize it on self.<attr>, or waive with a reason",
                    ))
        # Second sweep: locals bound to a jit and then CALLED in this same
        # scope — the fresh-cache-per-invocation shape one step removed.
        if not jit_locals:
            return
        reported: Set[str] = set()
        for node in _scope_nodes(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jit_locals
                and node.func.id not in reported
            ):
                reported.add(node.func.id)
                findings.append(Finding(
                    self.name, src.path, jit_locals[node.func.id],
                    f"jit bound to local {node.func.id!r} and invoked "
                    f"inside {fn.name}() (line {node.lineno}): every call "
                    f"of {fn.name} rebuilds the compile cache — bind it "
                    "module-level, memoize it on self.<attr>, or waive "
                    "with a reason",
                ))


class _FnTransferModel:
    """Per-function raw material for transfer-discipline: jit-flow locals,
    materialization sites (with exemption context), and whether the
    function's return value is jit-flow."""

    __slots__ = ("qualname", "path", "transfers", "returns_jit_flow",
                 "boundary_return_callees")

    def __init__(self, qualname: str, path: str):
        self.qualname = qualname
        self.path = path
        #: (line, reason) — non-exempt, non-waived materializations only.
        self.transfers: List[Tuple[int, str]] = []
        self.returns_jit_flow = False
        #: resolved callees whose boundary-ness makes this fn a boundary.
        self.boundary_return_callees: Set[str] = set()


class TransferDisciplinePass(LintPass):
    name = "transfer-discipline"
    description = (
        "device->host materializations of jit-boundary values must not be "
        "reachable from '# hot-path' functions outside a phases.phase(...) "
        "boundary (resolved over the v2/v5 call graph)"
    )

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        graph = shared_graph(files)
        attr_types = shared_thread_map(files).attr_types()
        models: Dict[str, _FnTransferModel] = {}
        # Annotation pre-scan: the declared '# jit-boundary' set must be
        # complete before any jit-flow judgement (extraction order across
        # files must not matter).
        boundary: Set[str] = {
            q for q, fn in graph.functions.items()
            if fn.resolvable
            and _is_jit_boundary_annotated(graph.sources[fn.path], fn.line)
        }

        for path, src in graph.sources.items():
            mod = _module_name(path) or path
            for node in src.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._extract(
                        graph, attr_types, src, mod, None, node,
                        f"{mod}:{node.name}", models, boundary,
                    )
                elif isinstance(node, ast.ClassDef):
                    for meth in node.body:
                        if isinstance(
                            meth, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            self._extract(
                                graph, attr_types, src, mod, node, meth,
                                f"{mod}:{node.name}.{meth.name}",
                                models, boundary,
                            )

        # Boundary inference fixpoint: a function returning a jit-flow
        # value, or a call into a boundary function, is itself a boundary
        # (Trainer.run_predict_step returns self.predict_step(...)).
        changed = True
        while changed:
            changed = False
            for q, m in models.items():
                if q in boundary:
                    continue
                if m.returns_jit_flow or (
                    m.boundary_return_callees & boundary
                ):
                    boundary.add(q)
                    changed = True
        # Second extraction pass: jit-flow depends on the final boundary
        # set, so transfers are re-derived once it settles (two passes
        # suffice — boundary-ness never depends on transfer sites).
        models = {}
        for path, src in graph.sources.items():
            mod = _module_name(path) or path
            for node in src.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._extract(
                        graph, attr_types, src, mod, None, node,
                        f"{mod}:{node.name}", models, set(boundary),
                        final_boundary=boundary,
                    )
                elif isinstance(node, ast.ClassDef):
                    for meth in node.body:
                        if isinstance(
                            meth, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            self._extract(
                                graph, attr_types, src, mod, node, meth,
                                f"{mod}:{node.name}.{meth.name}",
                                models, set(boundary),
                                final_boundary=boundary,
                            )

        # Witness fixpoint over the conservative v2 call edges, the
        # blocking-propagation shape: wit[q] = chain down to the
        # materializing primitive.
        wit: Dict[str, List[str]] = {}
        changed = True
        while changed:
            changed = False
            for q, fn in graph.functions.items():
                if q in wit or not fn.resolvable:
                    continue
                m = models.get(q)
                w: Optional[List[str]] = None
                if m is not None and m.transfers:
                    line, reason = m.transfers[0]
                    w = [f"{fn.path}:{line} {reason}"]
                if w is None:
                    for c in fn.calls:
                        if c.exempt:
                            continue
                        sub = wit.get(c.callee)
                        if sub is not None:
                            w = [
                                f"{fn.path}:{c.line} calls "
                                f"{c.callee.split(':')[-1]}"
                            ] + sub
                            break
                if w is not None:
                    wit[q] = w
                    changed = True

        findings: List[Finding] = []
        for q, fn in graph.functions.items():
            if not fn.hot_path:
                continue
            short = q.split(":")[-1]
            m = models.get(q)
            if m is not None:
                for line, reason in m.transfers:
                    findings.append(Finding(
                        self.name, fn.path, line,
                        f"hot-path {short}: {reason} — keep step outputs "
                        "on device, move the fetch behind a "
                        "phases.phase(...) boundary, or waive with a "
                        "reason",
                    ))
            for c in fn.calls:
                if c.exempt:
                    continue
                chain = wit.get(c.callee)
                if chain is None:
                    continue
                findings.append(Finding(
                    self.name, fn.path, c.line,
                    f"hot-path {short} calls {c.callee.split(':')[-1]}, "
                    "whose callee chain materializes a jit-boundary value "
                    "on the host: " + " -> ".join(chain)
                    + " — move the fetch behind a phases.phase(...) "
                    "boundary, off the hot path, or waive with a reason",
                ))
        return findings

    # -- per-function extraction --

    def _extract(
        self, graph, attr_types, src, mod, cls, fn, qualname, models,
        boundary: Set[str], final_boundary: Optional[Set[str]] = None,
    ) -> None:
        m = _FnTransferModel(qualname, src.path)
        models[qualname] = m
        if _is_jit_boundary_annotated(src, fn.lineno):
            boundary.add(qualname)
            if final_boundary is not None:
                final_boundary.add(qualname)
        resolved_boundary = (
            final_boundary if final_boundary is not None else boundary
        )

        # Lexically hoisted jit-flow locals (order-insensitive, the
        # thread-map local_types stance): names assigned from a call to a
        # boundary function or from invoking a jit-bound local.
        jit_bound: Set[str] = set()
        jit_flow: Set[str] = set()
        for _ in range(2):  # two sweeps: step = jit(...); out = step(x)
            for n in _scope_nodes(fn):
                if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                    names = self._target_names(n.targets)
                    if names:
                        if _jit_call_kind(n.value) is not None:
                            jit_bound |= names
                        elif self._call_is_boundary(
                            graph, attr_types, mod, cls, n.value,
                            resolved_boundary, jit_bound,
                        ):
                            jit_flow |= names

        # Return judgement (for the inference fixpoint).
        for n in _scope_nodes(fn):
            if not isinstance(n, ast.Return) or n.value is None:
                continue
            if any(
                isinstance(s, ast.Name) and s.id in jit_flow
                for s in ast.walk(n.value)
            ):
                m.returns_jit_flow = True
            if isinstance(n.value, ast.Call):
                callee = self._resolve(
                    graph, attr_types, mod, cls, n.value.func
                )
                if callee is not None:
                    m.boundary_return_callees.add(callee)
                if self._call_is_boundary(
                    graph, attr_types, mod, cls, n.value,
                    resolved_boundary, jit_bound,
                ):
                    m.returns_jit_flow = True

        # Materialization sites, with the blocking-style exemptions.
        self._walk_transfers(src, fn.body, m, jit_flow, exempt=False)

    @staticmethod
    def _target_names(targets) -> Set[str]:
        names: Set[str] = set()
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    if isinstance(el, ast.Name):
                        names.add(el.id)
        return names

    def _call_is_boundary(
        self, graph, attr_types, mod, cls, call: ast.Call,
        boundary: Set[str], jit_bound: Set[str],
    ) -> bool:
        f = call.func
        if isinstance(f, ast.Name) and f.id in jit_bound:
            return True  # out = step(x) where step = jit_compiled(...)
        callee = self._resolve(graph, attr_types, mod, cls, f)
        return callee is not None and callee in boundary

    def _resolve(self, graph, attr_types, mod, cls, f) -> Optional[str]:
        """v2 resolution plus the v5 typed-receiver layer
        (``self.<attr>.<meth>`` through constructor types)."""
        callee = graph._resolve_call(mod, cls, f)
        if callee is not None:
            return callee
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Attribute)
            and isinstance(f.value.value, ast.Name)
            and f.value.value.id == "self"
            and cls is not None
        ):
            cls_q = attr_types.get(f"{mod}:{cls.name}", {}).get(f.value.attr)
            if cls_q is not None:
                return graph.class_method(cls_q, f.attr)
        return None

    def _walk_transfers(self, src, body, m, jit_flow, exempt: bool) -> None:
        for node in body:
            self._visit_transfer(src, node, m, jit_flow, exempt)

    def _visit_transfer(self, src, node, m, jit_flow, exempt: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # deferred execution: its own scope, its own judgement
        if isinstance(node, ast.With):
            new_exempt = exempt or any(
                is_phase_context(i.context_expr) for i in node.items
            )
            self._walk_transfers(src, node.body, m, jit_flow, new_exempt)
            return
        if isinstance(node, ast.Try):
            self._walk_transfers(src, node.body, m, jit_flow, exempt)
            self._walk_transfers(src, node.orelse, m, jit_flow, exempt)
            self._walk_transfers(src, node.finalbody, m, jit_flow, exempt)
            for h in node.handlers:
                self._walk_transfers(src, h.body, m, jit_flow, True)
            return
        if isinstance(node, ast.Call):
            reason = self._transfer_reason(node, jit_flow)
            if reason is not None and not exempt and not self._waived(
                src, node.lineno
            ):
                m.transfers.append((node.lineno, reason))
        for child in ast.iter_child_nodes(node):
            self._visit_transfer(src, child, m, jit_flow, exempt)

    @staticmethod
    def _refs_flow(node: ast.AST, jit_flow) -> bool:
        return any(
            isinstance(s, ast.Name) and s.id in jit_flow
            for s in ast.walk(node)
        )

    def _transfer_reason(self, node: ast.Call, jit_flow) -> Optional[str]:
        f = node.func
        chain = attr_chain(f)
        if isinstance(f, ast.Attribute):
            if f.attr in ("item", "tolist") and not node.args:
                if self._refs_flow(f.value, jit_flow):
                    return (
                        f".{f.attr}() materializes a jit-boundary value "
                        "on the host (a blocking device->host transfer)"
                    )
            if chain == "jax.device_get" and any(
                self._refs_flow(a, jit_flow) for a in node.args
            ):
                return (
                    "jax.device_get of a jit-boundary value blocks on "
                    "the device->host transfer"
                )
            if chain in _TRANSFER_ARRAY_CHAINS and any(
                self._refs_flow(a, jit_flow) for a in node.args
            ):
                return (
                    f"{chain} over a jit-boundary value forces a "
                    "device->host copy"
                )
        elif isinstance(f, ast.Name) and f.id in _TRANSFER_CASTS:
            if any(self._refs_flow(a, jit_flow) for a in node.args):
                return (
                    f"{f.id}() over a jit-boundary value is a blocking "
                    "device read"
                )
        return None

    @staticmethod
    def _waived(src: SourceFile, line: int) -> bool:
        """A transfer-discipline waiver on the primitive's line stops it
        from propagating to callers (the blocking-propagation stance) —
        and is recorded as used so stale-waiver stays honest."""
        for cand in (line, line - 1):
            w = src.waivers.get(cand)
            if w is not None and w.rule == "transfer-discipline" and (
                cand == line or cand in src.comment_only_lines
            ):
                src.used_waiver_lines.add(cand)
                return True
        return False
