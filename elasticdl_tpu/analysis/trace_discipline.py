"""trace-discipline: hot-path trace emission uses the ring API only.

``common/trace.py`` splits its surface deliberately: ``span``/``instant``/
``add_complete`` are non-blocking ring appends (GIL-atomic deque — legal
anywhere, including ``# hot-path`` functions), while ``drain_slice``/
``export``/``chrome_events`` walk or drain the buffer and belong on
control-plane boundaries (heartbeats, checkpoint reports, dump tools).  An
export call inside a hot-path function would make TRACING the thing that
stalls the traced hot path — the exact failure mode the recorder's design
exists to rule out.  This pass keeps the split enforced: any call whose
attribute name is one of the export methods, inside a ``# hot-path``
function's steady-state body, is a finding.

Scope notes, mirroring ``hot-path-sync``'s conventions:

- ``except`` handler bodies and nested ``def``/``lambda`` bodies are
  exempt (error paths and deferred execution own their own time);
- unlike blocking calls, a ``phases.phase(...)`` boundary does NOT excuse
  an export — a drain is control-plane work, not an accountable phase of
  the hot path; waive with a reason if a hot-path drain is ever truly
  intended.

The export-method names are distinctive enough (``drain_slice``,
``chrome_events``) that receiver resolution is unnecessary — matching the
attribute name alone keeps the pass as simple as the rest of the v1 suite
(``export`` is checked with a trace-shaped receiver to avoid punishing
unrelated exporters).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain

#: Export-API attribute names that always flag in a hot-path body.
_EXPORT_ATTRS = {"drain_slice", "chrome_events"}

#: ``export`` is a common verb; only flag it when the receiver chain looks
#: like a trace recorder (``trace.default().export()`` bottoms out in a
#: call, so the chain is empty — match on the attribute one level up too).
_TRACE_RECEIVER_HINTS = ("trace", "rec", "recorder", "_REC")


def _is_export_call(node: ast.Call) -> bool:
    f = node.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr in _EXPORT_ATTRS:
        return True
    if f.attr == "export":
        chain = attr_chain(f)
        if chain:
            recv = chain.rsplit(".", 1)[0].split(".")[-1]
            return recv in _TRACE_RECEIVER_HINTS
        # Dynamic receiver (e.g. ``trace.default().export()``): the inner
        # call's own name is the hint.
        inner = f.value
        if isinstance(inner, ast.Call):
            ichain = attr_chain(inner.func)
            return any(
                part in _TRACE_RECEIVER_HINTS for part in ichain.split(".")
            )
    return False


class TraceDisciplinePass(LintPass):
    name = "trace-discipline"
    description = (
        "functions marked '# hot-path' may emit trace events only through "
        "the non-blocking ring API (span/instant/add_complete); export "
        "calls (drain_slice/export/chrome_events) are findings"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if src.is_hot_path(node.lineno):
                    self._walk(src, node.body, findings)
        return findings

    def _walk(self, src, body, findings) -> None:
        for node in body:
            self._visit(src, node, findings)

    def _visit(self, src, node, findings) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # deferred execution: not this function's hot path
        if isinstance(node, ast.Try):
            for stmt in node.body + node.orelse + node.finalbody:
                self._visit(src, stmt, findings)
            return  # handlers (error path) skipped
        if isinstance(node, ast.Call) and _is_export_call(node):
            findings.append(Finding(
                self.name, src.path, node.lineno,
                "trace export/drain inside a '# hot-path' function — ship "
                "slices from a control-plane boundary (heartbeat/report) "
                "instead, or waive with a reason",
            ))
        for child in ast.iter_child_nodes(node):
            self._visit(src, child, findings)
