"""trace-discipline: hot-path trace emission uses the ring API only.

``common/trace.py`` splits its surface deliberately: ``span``/``instant``/
``add_complete`` are non-blocking ring appends (GIL-atomic deque — legal
anywhere, including ``# hot-path`` functions), while ``drain_slice``/
``export``/``chrome_events`` walk or drain the buffer and belong on
control-plane boundaries (heartbeats, checkpoint reports, dump tools).  An
export call inside a hot-path function would make TRACING the thing that
stalls the traced hot path — the exact failure mode the recorder's design
exists to rule out.  This pass keeps the split enforced: any call whose
attribute name is one of the export methods, inside a ``# hot-path``
function's steady-state body, is a finding.

Traversal and exemption scope (handlers/nested defs exempt, no phase
excuse) are the shared ``HotPathCallDisciplinePass`` contract — one body
with ``chaos-discipline``, so the family cannot drift.

The export-method names are distinctive enough (``drain_slice``,
``chrome_events``) that receiver resolution is unnecessary — matching the
attribute name alone keeps the pass as simple as the rest of the v1 suite
(``export`` is checked with a trace-shaped receiver to avoid punishing
unrelated exporters).
"""

from __future__ import annotations

import ast

from elasticdl_tpu.analysis.core import (
    HotPathCallDisciplinePass,
    receiver_hinted,
)

#: Export-API attribute names that always flag in a hot-path body.
_EXPORT_ATTRS = {"drain_slice", "chrome_events"}

#: ``export`` is a common verb; only flag it when the receiver chain looks
#: like a trace recorder (``trace.default().export()`` bottoms out in a
#: call, so the chain is empty — match on the attribute one level up too).
_TRACE_RECEIVER_HINTS = ("trace", "rec", "recorder", "_REC")


def _is_export_call(node: ast.Call) -> bool:
    f = node.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr in _EXPORT_ATTRS:
        return True
    if f.attr == "export":
        return receiver_hinted(f, _TRACE_RECEIVER_HINTS)
    return False


class TraceDisciplinePass(HotPathCallDisciplinePass):
    name = "trace-discipline"
    description = (
        "functions marked '# hot-path' may emit trace events only through "
        "the non-blocking ring API (span/instant/add_complete); export "
        "calls (drain_slice/export/chrome_events) are findings"
    )
    message = (
        "trace export/drain inside a '# hot-path' function — ship "
        "slices from a control-plane boundary (heartbeat/report) "
        "instead, or waive with a reason"
    )

    def is_flagged_call(self, node: ast.Call) -> bool:
        return _is_export_call(node)
