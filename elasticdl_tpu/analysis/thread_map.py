"""thread-map: which functions execute on which thread ROLES (v5).

The control plane that keeps elastic training alive under churn spawns
~30 threads across dispatcher, rendezvous, pod manager, liveness beats,
checkpoint watchers and the micro-batcher — and every review round since
r6 has hand-found check-and-set races on the shared state they touch.
The lock-discipline/lock-order passes only judge state someone already
*annotated*; this module infers the concurrency structure itself, so the
shared-state pass (analysis/shared_state.py) can flag UNANNOTATED state
crossing thread boundaries.

A *role* is a named concurrency domain.  Entry points seed roles:

- ``threading.Thread(target=T, name="x")``   -> ``thread:x`` (or the
  target's name when ``name=`` is absent/dynamic);
- ``threading.Timer(delay, T)``              -> ``timer:<T>``;
- ``<pool>.submit(T, ...)``                  -> ``pool:<T>`` (executor
  worker threads — ThreadPoolExecutor and the repo's IngestPool share
  the ``submit`` shape);
- ``<future>.add_done_callback(T)``          -> ``callback:<T>`` (done
  callbacks run on executor threads, or inline on the completing one);
- gRPC servicer handler tables               -> ``grpc:<Class>`` — a
  ``method_table`` method's string constants naming methods of its own
  class (master/servicer.py), or a dict literal mapping string constants
  to ``self.<method>`` inside a class that wires grpc handlers
  (ps/service.py, serving/server.py);
- a module-level ``def main(...)``           -> ``main`` (the task loop);
- ``functools.partial(T, ...)`` (or a bare ``partial`` import) in any of
  the spawn shapes above unwraps to ``T`` (v6 — previously a documented
  blind spot: partial-wrapped targets got no role, muting shared-state
  checks on everything they touch);
- ``# thread-role: <role>`` on a ``def`` line (or the comment-only line
  above) — the explicit seed for hand-offs the resolver cannot see
  (e.g. a worker handed to the beat thread through a holder dict).

Roles then propagate over call edges: the resolved edges of
analysis/callgraph.py PLUS a constructor-type layer local to this map —
``v = ClassName(...)`` types local ``v`` (lexically visible to nested
closures), ``self._x = ClassName(...)`` types the instance attribute,
and ``v.meth(...)`` / ``self._x.meth(...)`` then edge into the class's
method.  These typed edges exist for ROLE propagation only: lock-order
and blocking-propagation keep the conservative resolved-edge set.
Nested ``def``/``lambda`` scopes inherit the enclosing function's roles
unless they are themselves a spawn target (a closure handed to a thread
runs ONLY there).

Blind spots (docs/static_analysis.md v5; the runtime twin
``common/racesan.py`` covers them from the other side): dynamic targets
(``target=self._table[k]``), ``getattr`` dispatch, callables stored in
containers, roles of code only tests invoke, and same-role concurrency
(two threads of one role racing each other — the role model treats a
role as one domain).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from elasticdl_tpu.analysis.callgraph import CallGraph, partial_target, shared_graph
from elasticdl_tpu.analysis.core import Finding, SourceFile, attr_chain as _attr_chain
from elasticdl_tpu.analysis.import_hygiene import _module_name

MAIN_ROLE = "main"

_ROLE_ANNOTATION = re.compile(r"#\s*thread-role\s*:\s*(?P<role>[^#]*)")
_ROLE_NAME = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_.:\-]*$")
_ANON = re.compile(r"^(?P<enc>.+)\.<(?P<name>[^@>]+)@\d+>$")

#: Receivers/spellings that mark a class as wiring grpc handlers — the
#: dict-literal handler-table detector only fires inside such classes, so
#: an ordinary dispatch table does not become a thread entry by accident.
_GRPC_MARKERS = (
    "grpc.server",
    "add_generic_rpc_handlers",
    "make_generic_handler",
    "unary_unary_rpc_method_handler",
    "method_handlers_generic_handler",
)


class ThreadEntry:
    """One inferred (or declared) thread entry point."""

    __slots__ = ("role", "kind", "target", "path", "line")

    def __init__(self, role: str, kind: str, target: str, path: str, line: int):
        self.role = role
        self.kind = kind  # thread|timer|pool|callback|grpc|main|annotation
        self.target = target  # qualname of the entry function
        self.path = path
        self.line = line

    def as_dict(self) -> dict:
        return {
            "role": self.role, "kind": self.kind, "target": self.target,
            "site": f"{self.path}:{self.line}",
        }


def _short_name(node: ast.expr) -> str:
    """Display name of a spawn target expression."""
    inner = partial_target(node)
    if inner is not None:
        return _short_name(inner)  # partial(T, ...): T names the role
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Lambda):
        return f"lambda@{node.lineno}"
    return "?"


def _const_str(node: Optional[ast.expr]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class ThreadMap:
    """Role assignment over a CallGraph's functions."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self.entries: List[ThreadEntry] = []
        #: Malformed/unknown '# thread-role:' annotations — reported by the
        #: shared-state pass (the map itself is not a pass).
        self.errors: List[Finding] = []
        #: qualname -> roles.  Functions absent here have UNKNOWN role and
        #: do not participate in cross-role judgements.
        self.roles: Dict[str, Set[str]] = {}
        #: (module:Class) -> {attr: "module:Class"} constructor types.
        self._attr_types: Dict[str, Dict[str, str]] = {}
        #: qualname -> extra role-propagation edges (typed receivers).
        self._typed_edges: Dict[str, Set[str]] = {}
        #: anon qualname -> enclosing qualname (from the callgraph naming).
        self._enclosing: Dict[str, str] = {}
        for q in graph.functions:
            m = _ANON.match(q)
            if m is not None:
                self._enclosing[q] = m.group("enc")
        #: (enclosing qualname, local def name) -> anon qualnames.
        self._nested: Dict[Tuple[str, str], List[str]] = {}
        for q, enc in self._enclosing.items():
            name = _ANON.match(q).group("name")
            self._nested.setdefault((enc, name), []).append(q)
        self._collect_attr_types()
        self._collect_entries_and_edges()
        self._propagate()

    # -- phase 1: constructor types of instance attributes --

    def _collect_attr_types(self) -> None:
        for path, src in self.graph.sources.items():
            mod = _module_name(path) or path
            for node in src.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                types: Dict[str, str] = {}
                for sub in ast.walk(node):
                    if not (
                        isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.value, ast.Call)
                    ):
                        continue
                    t = sub.targets[0]
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    cls_q = self.graph.resolve_class(mod, sub.value.func)
                    if cls_q is not None:
                        types[t.attr] = cls_q
                if types:
                    self._attr_types[f"{mod}:{node.name}"] = types

    # -- phase 2: entries + typed edges, per lexical scope --

    def _collect_entries_and_edges(self) -> None:
        for path, src in self.graph.sources.items():
            mod = _module_name(path) or path
            for node in src.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{mod}:{node.name}"
                    if node.name == "main":
                        self._add_entry(MAIN_ROLE, "main", q, path, node.lineno)
                    self._scan_annotation(src, mod, node, q)
                    self._scan_scope(src, mod, None, node, q, {})
                elif isinstance(node, ast.ClassDef):
                    self._scan_grpc_tables(src, mod, node)
                    for meth in node.body:
                        if isinstance(
                            meth, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            q = f"{mod}:{node.name}.{meth.name}"
                            self._scan_annotation(src, mod, meth, q)
                            self._scan_scope(src, mod, node, meth, q, {})

    def _scan_annotation(self, src: SourceFile, mod, node, q: str) -> None:
        """``# thread-role: <role>`` on the def line or anywhere in the
        contiguous comment-only block above it (the ``# hot-path``
        placement convention) seeds an explicit role."""
        cands = [node.lineno]
        above = node.lineno - 1
        while above in src.comment_only_lines:
            cands.append(above)
            above -= 1
        for cand in cands:
            comment = src.comments.get(cand)
            if comment is None:
                continue
            m = _ROLE_ANNOTATION.search(comment)
            if m is None:
                continue
            # First token only: trailing prose on the annotation line is
            # the author's rationale, not part of the role name.
            tokens = m.group("role").split()
            role = tokens[0] if tokens else ""
            if not role or not _ROLE_NAME.match(role):
                self.errors.append(Finding(
                    "shared-state", src.path, cand,
                    f"malformed thread-role annotation {role!r}: expected "
                    "'# thread-role: <role>' naming one role "
                    "(e.g. main, thread:heartbeat, grpc:MasterServicer)",
                ))
                return
            self._add_entry(role, "annotation", q, src.path, node.lineno)
            return

    def _scan_grpc_tables(self, src: SourceFile, mod, cls: ast.ClassDef):
        """gRPC handler entry points: the ``method_table`` string-constant
        form, and dict literals {str: self.<meth>} in grpc-wiring classes."""
        role = f"grpc:{cls.name}"
        methods = {
            m.name for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        wires_grpc = False
        for sub in ast.walk(cls):
            if isinstance(sub, ast.Attribute):
                chain = _attr_chain(sub)
                if any(chain.endswith(mk) for mk in _GRPC_MARKERS):
                    wires_grpc = True
            elif isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                if sub.func.id in _GRPC_MARKERS:
                    wires_grpc = True
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name == "method_table":
                for sub in ast.walk(meth):
                    name = _const_str(sub) if isinstance(sub, ast.Constant) else None
                    if name in methods:
                        self._add_entry(
                            role, "grpc", f"{mod}:{cls.name}.{name}",
                            src.path, meth.lineno,
                        )
            elif wires_grpc:
                for sub in ast.walk(meth):
                    if not isinstance(sub, ast.Dict):
                        continue
                    for key, value in zip(sub.keys, sub.values):
                        if _const_str(key) is None:
                            continue
                        if (
                            isinstance(value, ast.Attribute)
                            and isinstance(value.value, ast.Name)
                            and value.value.id == "self"
                            and value.attr in methods
                        ):
                            self._add_entry(
                                role, "grpc",
                                f"{mod}:{cls.name}.{value.attr}",
                                src.path, sub.lineno,
                            )

    def _scan_scope(self, src, mod, cls, node, q: str, outer_types: dict):
        """One lexical scope: collect local constructor types (closures see
        the enclosing scope's), spawn entries, and typed call edges.
        Recurses into nested defs under their callgraph anon names."""
        local_types = dict(outer_types)
        body = node.body if isinstance(node.body, list) else [node.body]
        stack = list(body)
        nested: List[ast.AST] = []
        # First sweep: local constructor types of THIS scope (hoisted, so a
        # spawn above the assignment still resolves — lexical, not flow).
        seen: List[ast.AST] = list(stack)
        while seen:
            n = seen.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Call)
            ):
                cls_q = self.graph.resolve_class(mod, n.value.func)
                if cls_q is not None:
                    local_types[n.targets[0].id] = cls_q
            seen.extend(ast.iter_child_nodes(n))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                nested.append(n)
                continue
            if isinstance(n, ast.Call):
                self._scan_call(src, mod, cls, q, n, local_types)
            stack.extend(ast.iter_child_nodes(n))
        for sub in nested:
            name = getattr(sub, "name", "lambda")
            anon_q = f"{q}.<{name}@{sub.lineno}>"
            self._scan_scope(src, mod, cls, sub, anon_q, local_types)

    def _scan_call(self, src, mod, cls, q, node: ast.Call, local_types):
        chain = _attr_chain(node.func)
        tail = chain.split(".")[-1] if chain else ""
        # Spawn shapes.
        if tail == "Thread" or (
            isinstance(node.func, ast.Name) and node.func.id == "Thread"
        ):
            target = next(
                (kw.value for kw in node.keywords if kw.arg == "target"), None
            )
            if target is not None:
                tq = self._resolve_target(mod, cls, q, target, local_types)
                name = next(
                    (_const_str(kw.value) for kw in node.keywords
                     if kw.arg == "name"), None,
                )
                role = f"thread:{name or _short_name(target)}"
                if tq is not None:
                    self._add_entry(role, "thread", tq, src.path, node.lineno)
            return
        if tail == "Timer" or (
            isinstance(node.func, ast.Name) and node.func.id == "Timer"
        ):
            target = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "function"),
                None,
            )
            if target is not None:
                tq = self._resolve_target(mod, cls, q, target, local_types)
                if tq is not None:
                    self._add_entry(
                        f"timer:{_short_name(target)}", "timer", tq,
                        src.path, node.lineno,
                    )
            return
        if isinstance(node.func, ast.Attribute) and node.func.attr == "submit":
            if node.args:
                tq = self._resolve_target(
                    mod, cls, q, node.args[0], local_types
                )
                if tq is not None:
                    self._add_entry(
                        f"pool:{_short_name(node.args[0])}", "pool", tq,
                        src.path, node.lineno,
                    )
            # fall through: the submit receiver may also be a typed call
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_done_callback"
            and node.args
        ):
            tq = self._resolve_target(mod, cls, q, node.args[0], local_types)
            if tq is not None:
                self._add_entry(
                    f"callback:{_short_name(node.args[0])}", "callback", tq,
                    src.path, node.lineno,
                )
            return
        # Typed call edges: v.meth(...) / self._x.meth(...).
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            cls_q: Optional[str] = None
            if isinstance(recv, ast.Name):
                cls_q = local_types.get(recv.id)
            elif (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
                and cls is not None
            ):
                cls_q = self._attr_types.get(
                    f"{mod}:{cls.name}", {}
                ).get(recv.attr)
            if cls_q is not None:
                callee = self.graph.class_method(cls_q, node.func.attr)
                if callee is not None:
                    self._typed_edges.setdefault(q, set()).add(callee)

    def _resolve_target(
        self, mod, cls, q, node: ast.expr, local_types
    ) -> Optional[str]:
        """A spawn-target expression -> qualname, or None (dynamic)."""
        inner = partial_target(node)
        if inner is not None:
            # functools.partial(T, ...): the spawned thread runs T —
            # resolve the wrapped callable (v6; previously a documented
            # blind spot that muted shared-state checks on T).
            return self._resolve_target(mod, cls, q, inner, local_types)
        if isinstance(node, ast.Lambda):
            return f"{q}.<lambda@{node.lineno}>"
        if isinstance(node, ast.Name):
            # Nested def of this scope chain first (lexical shadowing).
            scope = q
            while scope:
                anons = self._nested.get((scope, node.id))
                if anons:
                    return anons[0]
                m = _ANON.match(scope)
                scope = m.group("enc") if m else ""
            cand = f"{mod}:{node.id}"
            if cand in self.graph.functions:
                return cand
            tgt = self.graph._from_imports.get(mod, {}).get(node.id)
            if tgt is not None:
                base, leaf = tgt
                cand = f"{base}:{leaf}"
                if cand in self.graph.functions:
                    return cand
            return None
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name):
                if node.value.id == "self" and cls is not None:
                    cand = f"{mod}:{cls.name}.{node.attr}"
                    return cand if cand in self.graph.functions else None
                recv_cls = local_types.get(node.value.id)
                if recv_cls is not None:
                    return self.graph.class_method(recv_cls, node.attr)
            chain = _attr_chain(node)
            if chain and "." in chain:
                prefix, leaf = chain.rsplit(".", 1)
                target_mod = self.graph._resolve_module(mod, prefix)
                if target_mod is not None:
                    cand = f"{target_mod}:{leaf}"
                    if cand in self.graph.functions:
                        return cand
        return None

    def _add_entry(self, role, kind, target, path, line) -> None:
        self.entries.append(ThreadEntry(role, kind, target, path, line))

    # -- phase 3: propagation --

    def _propagate(self) -> None:
        entry_targets = {e.target for e in self.entries if e.kind != "main"}
        for e in self.entries:
            if e.target in self.graph.functions:
                self.roles.setdefault(e.target, set()).add(e.role)
        changed = True
        while changed:
            changed = False
            for q, fn in self.graph.functions.items():
                r = self.roles.get(q)
                if not r:
                    continue
                callees = {c.callee for c in fn.calls}
                callees |= self._typed_edges.get(q, set())
                for callee in callees:
                    if callee not in self.graph.functions:
                        continue
                    have = self.roles.setdefault(callee, set())
                    if not r <= have:
                        have |= r
                        changed = True
            # Nested scopes inherit the enclosing function's roles unless
            # they are spawn targets themselves (a closure handed to a
            # thread runs ONLY on that thread).
            for anon_q, enc_q in self._enclosing.items():
                if anon_q in entry_targets:
                    continue
                r = self.roles.get(enc_q)
                if not r:
                    continue
                have = self.roles.setdefault(anon_q, set())
                if not r <= have:
                    have |= r
                    changed = True

    # -- API --

    def roles_of(self, qualname: str) -> frozenset:
        return frozenset(self.roles.get(qualname, ()))

    def attr_types(self) -> Dict[str, Dict[str, str]]:
        """``"module:Class"`` -> {attr: constructed ``"module:Class"``} —
        the constructor-type layer, shared with the v6 transfer-discipline
        pass (it resolves ``self.trainer.train_step(...)``-shaped calls
        through the same typed receivers the role propagation uses)."""
        return self._attr_types

    def known_roles(self) -> Set[str]:
        return {e.role for e in self.entries}

    def dump(self) -> dict:
        """Machine-readable map: role -> functions, plus the entry list —
        the ``--threadmap`` CLI payload and the LINT artifact's stats."""
        by_role: Dict[str, List[str]] = {}
        for q, roles in self.roles.items():
            for r in roles:
                by_role.setdefault(r, []).append(q)
        return {
            "roles": {r: sorted(qs) for r, qs in sorted(by_role.items())},
            "entries": [e.as_dict() for e in self.entries],
            "functions_with_role": len(self.roles),
            "functions_total": len(self.graph.functions),
        }


#: One-entry memo, keyed on the (memoized) CallGraph identity — the
#: shared-state pass and the CLI --threadmap/--artifact consumers reuse
#: one map per run, like shared_graph.
_MAP_MEMO: dict = {}


def shared_thread_map(files: Sequence[SourceFile]) -> ThreadMap:
    graph = shared_graph(files)
    hit = _MAP_MEMO.get(id(graph))
    if hit is not None and hit[0] is graph:
        return hit[1]
    tmap = ThreadMap(graph)
    _MAP_MEMO.clear()
    _MAP_MEMO[id(graph)] = (graph, tmap)
    return tmap
