"""blocking-propagation: interprocedural hot-path blocking detection.

r7's ``hot-path-sync`` is per-function: a ``# hot-path`` function calling a
one-line helper that wraps ``block_until_ready`` passed clean, because the
primitive sits in the helper's body and the helper carries no marker.  This
pass closes that hole with the call graph (analysis/callgraph.py):

1. compute, for every function, whether its *callee chain* may block at
   steady state — a blocking primitive outside a ``phases.phase(...)``
   boundary / ``except`` handler that carries no ``hot-path-sync`` waiver,
   or a non-exempt call to a function that does;
2. flag every non-exempt call site in a ``# hot-path`` function whose
   callee may block, with the full witness chain down to the primitive.

Direct primitives in the hot function itself stay ``hot-path-sync``'s
findings (one rule per failure shape); this pass only reports the edges
the r7 pass is blind to.  A waived primitive does not propagate: the
waiver's reason covers the call no matter how deep the caller sits.

Waive with ``# graftlint: allow[blocking-propagation] <reason>`` on the
flagged call site.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence

from elasticdl_tpu.analysis.callgraph import shared_graph
from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile


class BlockingPropagationPass(LintPass):
    name = "blocking-propagation"
    description = (
        "'# hot-path' functions may not reach a blocking call through their "
        "callee chain outside a phases.phase(...) boundary"
    )

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        graph = shared_graph(files)
        witnesses = graph.blocking_witnesses()
        findings: List[Finding] = []
        for fn in graph.functions.values():
            if not fn.hot_path:
                continue
            for call in fn.calls:
                if call.exempt:
                    continue
                chain = witnesses.get(call.callee)
                if chain is None:
                    continue
                callee_name = call.callee.split(":")[-1]
                findings.append(Finding(
                    self.name, fn.path, call.line,
                    f"hot-path {fn.qualname.split(':')[-1]} calls "
                    f"{callee_name}, whose callee chain blocks: "
                    + " -> ".join(chain)
                    + " — move the call behind a phases.phase(...) "
                    "boundary, off the hot path, or waive with a reason",
                ))
        return findings
