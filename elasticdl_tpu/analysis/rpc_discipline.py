"""rpc-discipline: every stub call site has a deadline or a retry owner.

A blocking RPC with no deadline wedges forever on a half-dead peer — the
failure mode the death-push and PS-retry work exists to bound.  The rule:
any call spelled ``<recv>.call(...)`` / ``<recv>.call_async(...)`` (the
repo's two RPC entry-point names: JsonRpcClient / PSClient / the master
proxies) and any direct gRPC stub invocation (``self._stubs[...](...)``)
must satisfy one of:

- an explicit ``timeout=`` / ``timeout_s=`` kwarg at the call site;
- lexical containment in a designated retry/fan-out wrapper
  (``RETRY_WRAPPER_FUNCS``) — those own both deadline and backoff;
- being the body of a lambda passed to a ``_retry``-named wrapper
  (``self._retry(lambda: c.call(...))`` — the wrapper drives it);
- a receiver whose terminal name is in ``BOUNDARY_RECEIVERS`` — the master
  proxies (``self.master.call``): ``RpcMasterProxy``/``JsonRpcClient`` own
  the per-call deadline, and in-process ``DirectMasterProxy`` has no wire.

``subprocess.call`` and ``super().call`` (proxy subclass delegating to the
boundary-owning base) are out of scope by construction.

r18 adds the READINESS half: a bare ``grpc.channel_ready_future`` wait is
a reconnect loop written by hand — one hard timeout, no retry accounting,
no jitter (a thundering herd of relaunched workers re-dialing a
restarting master all at once).  The primitive is legal only inside
``common/rpc.py``, whose ``wait_channel_ready`` wraps it in the shared
backoff helper (short probes, jittered, ``edl_rpc_retry_total``
accounted); every other module routes through that helper or a
``wait_ready`` method that delegates to it.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain

#: Functions that own retry + deadline for the calls inside them.
#: ``call_with_backoff`` is the r18 shared helper every other wrapper now
#: delegates to — a lambda passed to it runs under its schedule.
RETRY_WRAPPER_FUNCS = {
    "_retry",
    "_call_shard",
    "_fan_out",
    "_retry_transient_collective",
    "call_with_backoff",
}

#: Terminal receiver names whose ``.call`` is already a managed boundary.
BOUNDARY_RECEIVERS = {"master", "subprocess"}

_TIMEOUT_KWARGS = {"timeout", "timeout_s"}

#: The one module where the raw readiness primitive is legal: it owns
#: ``wait_channel_ready``, the shared-backoff wrapper everything else
#: must route through.
READINESS_OWNER_SUFFIXES = ("common/rpc.py",)


class RpcDisciplinePass(LintPass):
    name = "rpc-discipline"
    description = (
        "stub .call/.call_async sites carry an explicit timeout or route "
        "through a retry wrapper"
    )

    def run(self, src: SourceFile) -> Iterable[Finding]:
        findings: List[Finding] = []
        self._walk(src, src.tree.body, in_wrapper=False, findings=findings)
        return findings

    def _walk(self, src, body, in_wrapper, findings) -> None:
        for node in body:
            self._visit(src, node, in_wrapper, findings)

    def _visit(self, src, node, in_wrapper, findings) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._walk(
                src, node.body,
                in_wrapper or node.name in RETRY_WRAPPER_FUNCS,
                findings,
            )
            return
        if isinstance(node, ast.Call):
            callee = node.func
            callee_chain = attr_chain(callee)
            is_retry_call = (
                callee_chain.split(".")[-1] in RETRY_WRAPPER_FUNCS
                if callee_chain else False
            )
            self._check_call(src, node, in_wrapper, findings)
            for child in ast.iter_child_nodes(node):
                if is_retry_call and isinstance(child, ast.Lambda):
                    # The lambda body executes under the wrapper's retry
                    # schedule: its calls are owned.
                    self._visit(src, child.body, True, findings)
                    continue
                self._visit(src, child, in_wrapper, findings)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(src, child, in_wrapper, findings)

    def _is_stub_invocation(self, func: ast.expr) -> bool:
        """``self._stubs[method](...)``-shaped direct stub call."""
        return (
            isinstance(func, ast.Subscript)
            and isinstance(func.value, ast.Attribute)
            and func.value.attr == "_stubs"
        )

    def _check_call(self, src, node: ast.Call, in_wrapper, findings) -> None:
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "channel_ready_future"
        ) or (
            isinstance(func, ast.Name) and func.id == "channel_ready_future"
        ):
            # Bare readiness wait (r18): the primitive belongs to
            # common/rpc.py's wait_channel_ready — a hand-rolled wait has
            # one hard timeout, no retry accounting, no jitter.
            path = src.path.replace("\\", "/")
            if not any(path.endswith(s) for s in READINESS_OWNER_SUFFIXES):
                findings.append(Finding(
                    self.name, src.path, node.lineno,
                    "bare channel_ready_future readiness wait — route "
                    "through common/rpc.wait_channel_ready (the shared "
                    "backoff helper owns probing, jitter and retry "
                    "accounting)",
                ))
            return
        is_rpc = False
        label = ""
        if isinstance(func, ast.Attribute) and func.attr in ("call", "call_async"):
            chain = attr_chain(func)
            if chain:
                recv_terminal = chain.split(".")[-2] if "." in chain else chain
                if recv_terminal in BOUNDARY_RECEIVERS:
                    return
            else:
                # Dynamic receiver, e.g. ``super().call`` (proxy subclass
                # delegating to the boundary-owning base) or
                # ``clients[i].call`` — subscripted clients ARE stubs.
                if isinstance(func.value, ast.Call):
                    return  # super().call / factory().call: base owns it
            is_rpc = True
            label = f"{chain or '<dynamic>'}"
        elif self._is_stub_invocation(func):
            is_rpc = True
            label = "direct stub invocation"
        if not is_rpc:
            return
        if in_wrapper:
            return
        if any(
            kw.arg in _TIMEOUT_KWARGS and kw.arg is not None
            for kw in node.keywords
        ):
            return
        findings.append(Finding(
            self.name, src.path, node.lineno,
            f"RPC {label} has no explicit timeout and no retry owner — "
            "pass timeout_s=/timeout=, or route through "
            f"{'/'.join(sorted(RETRY_WRAPPER_FUNCS))}",
        ))
