"""lock-order: the lock acquisition graph must stay a DAG.

The elastic control plane is a web of small locks (worker ``_ckpt_lock``,
servicer ``_lock``/``_group_lock``, dispatcher/evaluation/rendezvous
locks, PS ``_meta_lock``) touched from gRPC pool threads, watcher threads,
background checkpoint threads, and the task loop.  The r6/r7 reviews kept
the nesting acyclic BY HAND (e.g. "requeue outside the lock — holding ours
across their calls would couple lock orders"); this pass machine-checks
it, interprocedurally:

- every ``with self.<lock>:`` / ``with <module_lock>:`` of a DECLARED lock
  (``threading.Lock/RLock/Condition`` or ``locksan.lock/rlock`` assignment)
  is an acquisition; locks held at a call site propagate across resolved
  call edges (analysis/callgraph.py), so a helper that takes lock B while
  the caller holds lock A contributes the edge A -> B;
- any cycle in the resulting lock graph is a potential deadlock, reported
  with the full witness path (file:line of every hop down to the
  acquisition);
- annotations on the declaring line tighten the model:
  ``# lock-order: leaf``            nothing may be acquired while held;
  ``# lock-order: before(_other)``  this lock orders BEFORE ``self._other``
                                    (an observed reverse edge is a finding
                                    even without a full cycle);
- declarations routed through the runtime sanitizer
  (``common/locksan.py``) must AGREE with the comment annotation: a
  ``locksan.lock(...)`` whose ``leaf=``/``before=`` kwargs or name string
  diverge from the static declaration is a finding — the static model and
  the runtime assertions gate each other.

Blind spots (runtime locksan covers these): locks reached through object
attributes (``self.dispatcher.get_task()`` crosses into another class),
``acquire()``/``release()`` calls outside ``with``, and locks passed
around as values.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Sequence, Tuple

from elasticdl_tpu.analysis.callgraph import CallGraph, LockDecl, shared_graph
from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile

_ANNOTATION = re.compile(r"#\s*lock-order\s*:\s*(?P<spec>[^#]+)")
_BEFORE = re.compile(r"^before\(\s*(?P<names>[A-Za-z0-9_,\s]+)\s*\)$")


class LockOrderPass(LintPass):
    name = "lock-order"
    description = (
        "lock acquisition graph (propagated across call edges) must be "
        "acyclic and honor '# lock-order: leaf/before(...)' declarations"
    )

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        graph = shared_graph(files)
        findings: List[Finding] = []
        leaf, before = self._read_annotations(graph, findings)
        edges = graph.lock_edges()

        for (held, acquired), chain in sorted(edges.items()):
            path, line = self._witness_site(chain)
            if held == acquired and not graph.locks[held].reentrant:
                findings.append(Finding(
                    self.name, path, line,
                    f"{held} re-acquired while already held "
                    f"(non-reentrant: self-deadlock): " + " -> ".join(chain),
                ))
                continue
            if held in leaf and held != acquired:
                findings.append(Finding(
                    self.name, path, line,
                    f"{held} is declared '# lock-order: leaf' but "
                    f"{acquired} is acquired while it is held: "
                    + " -> ".join(chain),
                ))
            for b in before.get(acquired, ()):
                if b == held:
                    findings.append(Finding(
                        self.name, path, line,
                        f"{acquired} is declared '# lock-order: "
                        f"before({held.rsplit('.', 1)[-1]})' but is acquired "
                        f"while {held} is held: " + " -> ".join(chain),
                    ))

        findings.extend(self._find_cycles(graph, edges))
        return findings

    # -- annotations --

    def _read_annotations(
        self, graph: CallGraph, findings: List[Finding]
    ) -> Tuple[set, Dict[str, Tuple[str, ...]]]:
        leaf: set = set()
        before: Dict[str, Tuple[str, ...]] = {}
        for lock_id, decl in sorted(graph.locks.items()):
            src = graph.sources.get(decl.path)
            comment = src.comments.get(decl.line, "") if src else ""
            m = _ANNOTATION.search(comment)
            c_leaf, c_before = False, ()
            if m:
                spec = m.group("spec").strip()
                if spec == "leaf":
                    c_leaf = True
                else:
                    bm = _BEFORE.match(spec)
                    if bm:
                        c_before = tuple(
                            n.strip() for n in bm.group("names").split(",")
                            if n.strip()
                        )
                    else:
                        findings.append(Finding(
                            self.name, decl.path, decl.line,
                            f"malformed lock-order annotation {spec!r}: "
                            "expected 'leaf' or 'before(<attr>[, ...])'",
                        ))
                        continue
            resolved_before = []
            for attr in c_before:
                other = (
                    f"{decl.module}:{decl.cls}.{attr}" if decl.cls
                    else f"{decl.module}:{attr}"
                )
                if other not in graph.locks:
                    findings.append(Finding(
                        self.name, decl.path, decl.line,
                        f"lock-order annotation names unknown lock "
                        f"{attr!r} (no declared lock {other})",
                    ))
                    continue
                resolved_before.append(other)
            if c_leaf:
                leaf.add(lock_id)
            if resolved_before:
                before[lock_id] = tuple(resolved_before)
            findings.extend(
                self._check_runtime_agreement(decl, c_leaf, c_before)
            )
        return leaf, before

    def _check_runtime_agreement(
        self, decl: LockDecl, c_leaf: bool, c_before: Tuple[str, ...]
    ) -> Iterable[Finding]:
        """A locksan-wrapped declaration must mirror its comment annotation
        (and carry the canonical name) — the runtime sanitizer enforces
        exactly what the static model declares, or neither can be trusted."""
        if not decl.is_locksan:
            return
        expected_name = f"{decl.cls}.{decl.attr}" if decl.cls else decl.attr
        if decl.rt_name != expected_name:
            yield Finding(
                self.name, decl.path, decl.line,
                f"locksan lock name {decl.rt_name!r} does not match its "
                f"attribute (expected {expected_name!r}) — runtime order "
                "reports would mis-name the lock",
            )
        if decl.rt_leaf != c_leaf:
            yield Finding(
                self.name, decl.path, decl.line,
                f"locksan leaf={decl.rt_leaf} disagrees with the "
                f"'# lock-order:' comment ({'leaf' if c_leaf else 'no leaf'})"
                " — the static model and the runtime sanitizer must declare "
                "the same order",
            )
        if tuple(decl.rt_before) != tuple(c_before):
            yield Finding(
                self.name, decl.path, decl.line,
                f"locksan before={tuple(decl.rt_before)!r} disagrees with "
                f"the '# lock-order:' comment ({tuple(c_before)!r}) — the "
                "static model and the runtime sanitizer must declare the "
                "same order",
            )

    # -- cycles --

    @staticmethod
    def _witness_site(chain: List[str]) -> Tuple[str, int]:
        head = chain[0]
        path, _, rest = head.partition(":")
        line = rest.split(" ")[0]
        try:
            return path, int(line)
        except ValueError:
            return path, 1

    def _find_cycles(
        self, graph: CallGraph, edges: Dict[Tuple[str, str], List[str]]
    ) -> List[Finding]:
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            if a != b:
                adj.setdefault(a, []).append(b)
        findings: List[Finding] = []
        seen_cycles: set = set()
        for start in sorted(adj):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == start:
                        cycle = path + [start]
                        key = frozenset(cycle)
                        if key in seen_cycles:
                            continue
                        seen_cycles.add(key)
                        witness: List[str] = []
                        for i in range(len(cycle) - 1):
                            witness.append(
                                f"{cycle[i]} -> {cycle[i + 1]} "
                                f"[{'; '.join(edges[(cycle[i], cycle[i + 1])])}]"
                            )
                        wpath, wline = self._witness_site(
                            edges[(cycle[0], cycle[1])]
                        )
                        findings.append(Finding(
                            self.name, wpath, wline,
                            "potential deadlock: lock acquisition cycle "
                            + " ".join(witness),
                        ))
                    elif nxt not in path:
                        stack.append((nxt, path + [nxt]))
        return findings
