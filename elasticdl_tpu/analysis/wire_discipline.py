"""Wire-schema discipline (v8): both ends of every RPC match the schema.

The JSON-over-gRPC control plane's compatibility contract — "additive
optional field, no PROTOCOL_VERSION bump", the r9/r12/r14/r18 stance —
lived in comments in ``common/rpc.py`` and reviewer vigilance.  These
two rules make it machine-checked before the RPC surface grows again
(the elastic PS tier and the train-to-serve loop both will), in the
established static-pass + runtime-sanitizer lineage (v5+racesan,
v6+jitsan, v7+crashsan; the runtime twin here is ``common/wiresan.py``):

- ``wire-discipline``
    The schema index is EVALUATED from the ``MessageSchema`` literals in
    ``common/rpc.py`` — the ``*_SCHEMAS`` table assignments (request
    tables; ``*_RESPONSE_SCHEMAS`` are response tables), the type-alias
    tuples they reference, and the ``setdefault`` envelope loops that
    splice trace/phase_counts/gauge onto methods after the literals.
    Both sides of every method are then judged:

    * SENDERS — a payload dict flowing into a ``.call``/``.call_async``
      site whose method name the index knows may not carry an undeclared
      key: the receiver validates-then-ignores unknown fields (the
      additive-compat stance), so a misspelled or undeclared key is a
      silently dropped field — a latent protocol bug.  Payloads resolve
      from inline dict literals and from locals assigned a dict literal
      then grown via ``p["k"] = v`` / ``p.update({...})`` /
      ``p.setdefault("k", v)``; dynamically built payloads are skipped
      (wiresan covers them at runtime).
    * RECEIVERS — handler functions (resolved via the thread_map
      ``method_table`` machinery, plus the serving tier's
      ``{"Method": self._handler}`` dict-literal wiring) may not
      subscript-access an OPTIONAL field (``msg["gauge"]`` is a finding:
      old peers omit it — ``.get()`` required) nor read an undeclared
      one.  The message parameter's methods propagate through bare-name
      helper calls in the same file (``self._record_gauges(req)``): a
      subscript is legal only for a field REQUIRED in EVERY method
      flowing into that scope; a ``.get`` is legal for a field declared
      in AT LEAST ONE (mixed-method helpers branch on what arrived).
    * CLIENT RESPONSES — a local assigned from a ``.call`` whose method
      has a response schema is judged by the same grammar against that
      schema: subscripting an optional/undeclared response field is how
      an old master turns into a worker KeyError.

- ``wire-evolution``
    Cross-version compatibility, enforced statically against the
    committed fingerprint ``artifacts/wire_schema.lock.json``: removing
    a field, changing a field's accepted types, or adding a REQUIRED
    field to an existing method is a finding unless PROTOCOL_VERSION is
    bumped AND the lock regenerated (``tools/graftlint.py
    --update-wire-lock``) in the same diff.  Additive drift (a new
    optional field, a new method, a ``since`` stamp) just asks for the
    lock to be regenerated.  A version bump with a regenerated lock is
    clean by construction — the lock IS the reviewed record of the new
    baseline.

Blind spots (wiresan covers them at runtime): payloads built
dynamically (comprehensions, ``dict(**x)``, cross-function
construction), response dicts threaded through helper returns, and the
PS tier's binary ``call(method, meta, arrays)`` frames — their method
names are outside the index, so both rules skip them by construction.

Waive with ``# graftlint: allow[<rule>] <reason>`` on the finding's
line or a comment-only line above.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile, attr_chain
from elasticdl_tpu.analysis.import_hygiene import _module_name
from elasticdl_tpu.analysis.thread_map import shared_thread_map

#: The committed schema fingerprint the wire-evolution rule judges
#: against (regenerate with ``tools/graftlint.py --update-wire-lock``).
WIRE_LOCK_PATH = "artifacts/wire_schema.lock.json"

#: The JSON-wire type vocabulary a schema tuple may spell.
_WIRE_TYPES = {"str", "int", "float", "bool", "dict", "list", "tuple"}


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# -- schema-index evaluation -------------------------------------------------


def _eval_types(node, aliases: Dict[str, Tuple[str, ...]]) -> Optional[Tuple[str, ...]]:
    """A field's accepted-types expression -> sorted type-name tuple:
    an alias Name (``_NUM``), or an inline tuple of builtin type names
    (``(list, dict)``)."""
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    if isinstance(node, ast.Tuple):
        names: List[str] = []
        for elt in node.elts:
            if isinstance(elt, ast.Name) and elt.id in _WIRE_TYPES:
                names.append(elt.id)
            else:
                return None
        return tuple(sorted(names))
    return None


def _eval_field_dict(
    node, aliases: Dict[str, Tuple[str, ...]]
) -> Optional[Dict[str, Tuple[str, ...]]]:
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[str, Tuple[str, ...]] = {}
    for key, value in zip(node.keys, node.values):
        field = _const_str(key)
        types = _eval_types(value, aliases)
        if field is None or types is None:
            return None
        out[field] = types
    return out


def _eval_since_dict(node) -> Optional[Dict[str, int]]:
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[str, int] = {}
    for key, value in zip(node.keys, node.values):
        field = _const_str(key)
        if field is None or not (
            isinstance(value, ast.Constant) and isinstance(value.value, int)
        ):
            return None
        out[field] = value.value
    return out


class _SchemaRec:
    """One method's evaluated schema (one wire direction)."""

    def __init__(self, path: str, line: int):
        self.path = path
        self.line = line
        self.required: Dict[str, Tuple[str, ...]] = {}
        self.optional: Dict[str, Tuple[str, ...]] = {}
        self.since: Dict[str, int] = {}

    @property
    def declared(self) -> Set[str]:
        return set(self.required) | set(self.optional)

    def as_dict(self) -> dict:
        return {
            "required": {f: list(t) for f, t in sorted(self.required.items())},
            "optional": {f: list(t) for f, t in sorted(self.optional.items())},
            "since": dict(sorted(self.since.items())),
        }


class SchemaIndex:
    """The evaluated wire contract: request + response schemas per
    method, plus the declaring table locations and PROTOCOL_VERSION."""

    def __init__(self):
        self.request: Dict[str, _SchemaRec] = {}
        self.response: Dict[str, _SchemaRec] = {}
        #: (path, line) of the first schema-table assignment seen — the
        #: anchor for table-level wire-evolution findings.
        self.decl: Optional[Tuple[str, int]] = None
        self.protocol_version: Optional[int] = None

    def direction(self, name: str) -> Dict[str, _SchemaRec]:
        return self.response if "RESPONSE" in name else self.request


def _is_schema_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else ""
    )
    return name == "MessageSchema"


def _eval_schema_call(
    node: ast.Call, aliases, path: str
) -> Optional[_SchemaRec]:
    rec = _SchemaRec(path, node.lineno)
    sections = {}
    for i, arg in enumerate(node.args):
        sections[("required", "optional", "since")[i] if i < 3 else f"arg{i}"] = arg
    for kw in node.keywords:
        sections[kw.arg] = kw.value
    for name, value in sections.items():
        if name == "since":
            since = _eval_since_dict(value)
            if since is None:
                return None
            rec.since = since
        elif name in ("required", "optional"):
            fields = _eval_field_dict(value, aliases)
            if fields is None:
                return None
            setattr(rec, name, fields)
        else:
            return None
    return rec


def collect_schema_index(sources: Sequence[SourceFile]) -> SchemaIndex:
    """Evaluate every ``*_SCHEMAS`` table literal (requests;
    ``*_RESPONSE_SCHEMAS`` are responses), the type aliases they
    reference, the ``setdefault`` envelope loops that splice fields onto
    already-declared methods, and PROTOCOL_VERSION."""
    idx = SchemaIndex()
    for src in sources:
        aliases: Dict[str, Tuple[str, ...]] = {}
        tables: Dict[str, Dict[str, _SchemaRec]] = {}
        declared_any = False
        for node in src.tree.body:
            target, value = None, None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if isinstance(target, ast.Name) and value is not None:
                types = _eval_types(value, aliases)
                if types is not None:
                    aliases[target.id] = types
                    continue
                if (
                    target.id == "PROTOCOL_VERSION"
                    and isinstance(value, ast.Constant)
                    and isinstance(value.value, int)
                ):
                    idx.protocol_version = value.value
                    continue
                if target.id.endswith("_SCHEMAS") and isinstance(value, ast.Dict):
                    table = idx.direction(target.id)
                    local: Dict[str, _SchemaRec] = {}
                    for key, call in zip(value.keys, value.values):
                        method = _const_str(key)
                        if method is None or not _is_schema_call(call):
                            continue
                        rec = _eval_schema_call(call, aliases, src.path)
                        if rec is not None:
                            table[method] = rec
                            local[method] = rec
                    if local:
                        declared_any = True
                        tables[target.id] = local
                        if idx.decl is None:
                            idx.decl = (src.path, node.lineno)
                    continue
            if isinstance(node, ast.For) and declared_any:
                _apply_envelope_loop(node, tables, aliases)
    return idx


def _apply_envelope_loop(
    loop: ast.For,
    tables: Dict[str, Dict[str, _SchemaRec]],
    aliases: Dict[str, Tuple[str, ...]],
) -> None:
    """The two envelope-loop shapes ``common/rpc.py`` uses:

    ``for v in TABLE.values(): v.<section>.setdefault(key, val)``
        splices onto EVERY method of TABLE;
    ``for v in ("A", "B"): TABLE[v].<section>.setdefault(key, val)``
        splices onto the listed methods.
    """
    if not isinstance(loop.target, ast.Name):
        return
    var = loop.target.id
    targets: List[_SchemaRec] = []
    it = loop.iter
    if (
        isinstance(it, ast.Call)
        and isinstance(it.func, ast.Attribute)
        and it.func.attr == "values"
        and isinstance(it.func.value, ast.Name)
        and it.func.value.id in tables
    ):
        targets = list(tables[it.func.value.id].values())
        subscript_form = False
    elif isinstance(it, (ast.Tuple, ast.List)):
        methods = [_const_str(e) for e in it.elts]
        if any(m is None for m in methods):
            return
        subscript_form = True
    else:
        return
    for stmt in loop.body:
        if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
            continue
        call = stmt.value
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "setdefault"):
            continue
        section_attr = f.value
        if not isinstance(section_attr, ast.Attribute):
            continue
        section = section_attr.attr
        if section not in ("required", "optional", "since"):
            continue
        recv = section_attr.value
        recs: List[_SchemaRec]
        if not subscript_form:
            if not (isinstance(recv, ast.Name) and recv.id == var):
                continue
            recs = targets
        else:
            if not (
                isinstance(recv, ast.Subscript)
                and isinstance(recv.value, ast.Name)
                and recv.value.id in tables
            ):
                continue
            sl = recv.slice
            if isinstance(sl, ast.Index):  # pragma: no cover — py<3.9 shape
                sl = sl.value
            if not (isinstance(sl, ast.Name) and sl.id == var):
                continue
            table = tables[recv.value.id]
            recs = [table[m] for m in methods if m in table]
        if len(call.args) < 2:
            continue
        key = _const_str(call.args[0])
        if key is None:
            continue
        if section == "since":
            v = call.args[1]
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                for rec in recs:
                    rec.since.setdefault(key, v.value)
        else:
            types = _eval_types(call.args[1], aliases)
            if types is not None:
                for rec in recs:
                    getattr(rec, section).setdefault(key, types)


def wire_fingerprint(sources: Sequence[SourceFile]) -> dict:
    """The lock-file payload: protocol version + every method's evaluated
    schema, both directions, keyed ``"<direction>:<method>"``."""
    idx = collect_schema_index(sources)
    methods = {}
    for direction, table in (("request", idx.request), ("response", idx.response)):
        for method, rec in table.items():
            methods[f"{direction}:{method}"] = rec.as_dict()
    return {
        "protocol_version": idx.protocol_version,
        "methods": {k: methods[k] for k in sorted(methods)},
    }


# -- the sender / receiver / response model ----------------------------------


def _scope_nodes(body) -> Iterable[ast.AST]:
    """Every node under ``body``, pruning nested def scopes but KEEPING
    lambdas — ``call_with_backoff(lambda: c.call(...))`` is this
    function's wire traffic and the lambda shares its locals."""
    stack: List[ast.AST] = list(body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _iter_functions(src: SourceFile):
    """``(fn, class_name)`` for every function/method, nested included."""
    def walk(body, cls_name):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node, cls_name
                yield from walk(node.body, cls_name)
            elif isinstance(node, ast.ClassDef):
                yield from walk(node.body, node.name)
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.For, ast.While)):
                yield from walk(
                    getattr(node, "body", [])
                    + getattr(node, "orelse", [])
                    + getattr(node, "finalbody", []),
                    cls_name,
                )
    yield from walk(src.tree.body, None)


def _call_method_name(node: ast.Call) -> Optional[str]:
    """The wire method of a ``<recv>.call("M", payload)`` /
    ``.call_async`` site; None for other calls (including the PS tier's
    no-payload forms — those are judged only when a payload arg exists)."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr in ("call", "call_async")):
        return None
    if len(node.args) < 2:
        return None
    return _const_str(node.args[0])


class _PayloadTracker:
    """Per-function dict-literal payload locals: name -> (keys, live).
    A local stays judged only while every mutation stays literal; any
    dynamic growth (``p[var] = ...``, ``p.update(x)``, reassignment from
    a non-literal) drops it — skipped, never guessed."""

    def __init__(self, fn):
        self.keys: Dict[str, Set[str]] = {}
        dead: Set[str] = set()
        for n in _scope_nodes(fn.body):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(
                n.targets[0], ast.Name
            ):
                name = n.targets[0].id
                lit = self._literal_keys(n.value)
                if lit is None:
                    if name in self.keys or isinstance(n.value, ast.Dict):
                        dead.add(name)
                else:
                    if name in self.keys:
                        self.keys[name] |= lit
                    else:
                        self.keys[name] = set(lit)
            elif isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(
                n.targets[0], ast.Subscript
            ):
                sub = n.targets[0]
                if isinstance(sub.value, ast.Name) and sub.value.id in self.keys:
                    sl = sub.slice
                    if isinstance(sl, ast.Index):  # pragma: no cover
                        sl = sl.value
                    key = _const_str(sl)
                    if key is None:
                        dead.add(sub.value.id)
                    else:
                        self.keys[sub.value.id].add(key)
            elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
                recv = n.func.value
                if not (isinstance(recv, ast.Name) and recv.id in self.keys):
                    continue
                if n.func.attr == "update":
                    lit = self._literal_keys(n.args[0]) if n.args else None
                    if lit is None:
                        dead.add(recv.id)
                    else:
                        self.keys[recv.id] |= lit
                elif n.func.attr == "setdefault" and n.args:
                    key = _const_str(n.args[0])
                    if key is None:
                        dead.add(recv.id)
                    else:
                        self.keys[recv.id].add(key)
        for name in dead:
            self.keys.pop(name, None)

    @staticmethod
    def _literal_keys(node) -> Optional[Set[str]]:
        """Keys of a dict literal; None when not a fully-literal dict
        (a ``**spread`` or computed key makes the key set unknowable)."""
        if not isinstance(node, ast.Dict):
            return None
        keys: Set[str] = set()
        for k in node.keys:
            s = _const_str(k)
            if s is None:
                return None
            keys.add(s)
        return keys

    def resolve(self, node) -> Optional[Set[str]]:
        """The judged key set of a payload argument expression."""
        if isinstance(node, ast.Dict):
            # Judge the literal keys even when a **spread rides along —
            # the spread's keys are unknown, the named ones are not.
            return {
                s for s in (_const_str(k) for k in node.keys) if s is not None
            }
        if isinstance(node, ast.Name):
            return self.keys.get(node.id)
        return None


class WireModel:
    """The whole-project wire view both v8 rules and the ``--wire``
    inventory read: the schema index, every resolvable sender site,
    every receiver handler (with helper propagation), and every tracked
    client response local."""

    def __init__(self, files: Sequence[SourceFile]):
        self.files = files
        self.index = collect_schema_index(files)
        #: method -> ["path:line", ...]
        self.senders: Dict[str, List[str]] = {}
        self.receivers: Dict[str, List[str]] = {}
        self.findings: List[Finding] = []
        if self.index.request or self.index.response:
            self._judge_senders_and_responses()
            self._judge_receivers()

    # -- senders + client responses --

    def _judge_senders_and_responses(self) -> None:
        req_idx, resp_idx = self.index.request, self.index.response
        for src in self.files:
            for fn, _cls in _iter_functions(src):
                tracker = None  # built lazily — most functions have no wire calls
                resp_locals: Dict[str, Set[str]] = {}
                dead_resp: Set[str] = set()
                for n in _scope_nodes(fn.body):
                    if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(
                        n.targets[0], ast.Name
                    ):
                        name = n.targets[0].id
                        m = (
                            _call_method_name(n.value)
                            if isinstance(n.value, ast.Call) else None
                        )
                        if m is not None and m in resp_idx:
                            resp_locals.setdefault(name, set()).add(m)
                        elif name in resp_locals:
                            dead_resp.add(name)
                    if not isinstance(n, ast.Call):
                        continue
                    method = _call_method_name(n)
                    if method is None or method not in req_idx:
                        continue
                    self.senders.setdefault(method, []).append(
                        f"{src.path}:{n.lineno}"
                    )
                    if tracker is None:
                        tracker = _PayloadTracker(fn)
                    keys = tracker.resolve(n.args[1])
                    if keys is None:
                        continue
                    schema = req_idx[method]
                    undeclared = sorted(keys - schema.declared)
                    if undeclared:
                        self.findings.append(Finding(
                            "wire-discipline", src.path, n.lineno,
                            f"payload for {method} carries undeclared "
                            f"key(s) {', '.join(map(repr, undeclared))} — "
                            "the receiver ignores unknown fields "
                            "(additive-compat), so the data is silently "
                            "dropped; declare the field in the "
                            f"MessageSchema or drop it",
                        ))
                for name in dead_resp:
                    resp_locals.pop(name, None)
                if resp_locals:
                    self._judge_reads(
                        src, fn, resp_locals, resp_idx, kind="response"
                    )
                # Direct subscript on the call result itself:
                # ``c.call("M", {})["field"]``.
                for n in _scope_nodes(fn.body):
                    if not (
                        isinstance(n, ast.Subscript)
                        and isinstance(n.value, ast.Call)
                    ):
                        continue
                    m = _call_method_name(n.value)
                    if m is None or m not in resp_idx:
                        continue
                    sl = n.slice
                    if isinstance(sl, ast.Index):  # pragma: no cover
                        sl = sl.value
                    field = _const_str(sl)
                    if field is not None:
                        self._judge_subscript(
                            src, n.lineno, field, {m}, resp_idx, "response"
                        )

    # -- receivers --

    def _judge_receivers(self) -> None:
        handlers = self._resolve_handlers()
        # Per-file fixpoint: propagate each handler's message param into
        # same-file helpers called with the bare param name.
        by_path = {s.path: s for s in self.files}
        for path, fn_methods in handlers.items():
            src = by_path[path]
            marked = dict(fn_methods)  # (fn, cls) -> {param: methods}
            fn_index: Dict[Tuple[Optional[str], str], Tuple[ast.AST, Optional[str]]] = {}
            for fn, cls in _iter_functions(src):
                fn_index.setdefault((cls, fn.name), (fn, cls))
                fn_index.setdefault((None, fn.name), (fn, cls))
            changed = True
            while changed:
                changed = False
                for (fn, cls), params in list(marked.items()):
                    for n in _scope_nodes(fn.body):
                        if not isinstance(n, ast.Call):
                            continue
                        callee = self._local_callee(n, cls, fn_index)
                        if callee is None:
                            continue
                        cfn, ccls = callee
                        cparams = [a.arg for a in cfn.args.args]
                        if cparams and cparams[0] == "self":
                            cparams = cparams[1:]
                        for pos, arg in enumerate(n.args):
                            if not (
                                isinstance(arg, ast.Name)
                                and arg.id in params
                                and pos < len(cparams)
                            ):
                                continue
                            slot = marked.setdefault((cfn, ccls), {})
                            have = slot.setdefault(cparams[pos], set())
                            if not params[arg.id] <= have:
                                have |= params[arg.id]
                                changed = True
            for (fn, _cls), params in marked.items():
                self._judge_reads(src, fn, params, self.index.request,
                                  kind="request")

    def _local_callee(self, call: ast.Call, cls: Optional[str], fn_index):
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
        ):
            return fn_index.get((cls, f.attr))
        if isinstance(f, ast.Name):
            hit = fn_index.get((None, f.id))
            # Bare-name resolution must not confuse a module function
            # with a method of an unrelated class.
            if hit is not None and hit[1] is None:
                return hit
        return None

    def _resolve_handlers(self):
        """path -> {(fn_node, cls_name): {param: {methods}}} for every
        receiver handler the project wires."""
        req_idx = self.index.request
        out: Dict[str, Dict[Tuple[ast.AST, Optional[str]], Dict[str, Set[str]]]] = {}
        by_mod: Dict[str, SourceFile] = {}
        meth_nodes: Dict[Tuple[str, str, str], ast.AST] = {}
        for src in self.files:
            mod = _module_name(src.path) or src.path
            by_mod[mod] = src
            for fn, cls in _iter_functions(src):
                if cls is not None:
                    meth_nodes[(mod, cls, fn.name)] = fn

        def mark(src: SourceFile, fn, cls: Optional[str], method: str) -> None:
            params = [a.arg for a in fn.args.args]
            if params and params[0] == "self":
                params = params[1:]
            if not params:
                return
            self.receivers.setdefault(method, []).append(
                f"{src.path}:{fn.lineno} {fn.name}"
            )
            slot = out.setdefault(src.path, {}).setdefault((fn, cls), {})
            slot.setdefault(params[0], set()).add(method)

        # The method_table string-constant form, via the existing
        # thread_map machinery (wire name == handler method name there).
        for e in shared_thread_map(self.files).entries:
            if e.kind != "grpc" or ":" not in e.target:
                continue
            mod, qual = e.target.split(":", 1)
            if "." not in qual:
                continue
            cls, meth = qual.rsplit(".", 1)
            if meth not in req_idx:
                continue
            fn = meth_nodes.get((mod, cls, meth))
            src = by_mod.get(mod)
            if fn is not None and src is not None:
                mark(src, fn, cls, meth)
        # The dict-literal form ({"Predict": self._predict}) — thread_map
        # records the handler but loses the WIRE name key, so the wire
        # mapping is recovered here: a dict literal whose string keys are
        # all schema methods and whose values are methods of the class.
        for src in self.files:
            mod = _module_name(src.path) or src.path
            for node in src.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Dict) or not sub.keys:
                        continue
                    pairs = []
                    for key, value in zip(sub.keys, sub.values):
                        method = _const_str(key)
                        if method is None or method not in req_idx:
                            pairs = None
                            break
                        if not (
                            isinstance(value, ast.Attribute)
                            and isinstance(value.value, ast.Name)
                            and value.value.id == "self"
                        ):
                            pairs = None
                            break
                        fn = meth_nodes.get((mod, node.name, value.attr))
                        if fn is None:
                            pairs = None
                            break
                        pairs.append((method, fn))
                    if pairs:
                        for method, fn in pairs:
                            already = self.receivers.get(method, [])
                            tag = f"{src.path}:{fn.lineno} {fn.name}"
                            if tag not in already:
                                mark(src, fn, node.name, method)
        return out

    # -- the shared read grammar --

    def _judge_reads(
        self,
        src: SourceFile,
        fn,
        params: Dict[str, Set[str]],
        idx: Dict[str, _SchemaRec],
        kind: str,
    ) -> None:
        for n in ast.walk(fn):
            if isinstance(n, ast.Subscript) and isinstance(n.value, ast.Name):
                name = n.value.id
                if name not in params or not isinstance(n.ctx, ast.Load):
                    continue
                sl = n.slice
                if isinstance(sl, ast.Index):  # pragma: no cover
                    sl = sl.value
                field = _const_str(sl)
                if field is not None:
                    self._judge_subscript(
                        src, n.lineno, field, params[name], idx, kind
                    )
            elif (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "get"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id in params
                and n.args
            ):
                field = _const_str(n.args[0])
                if field is None:
                    continue
                methods = params[n.func.value.id]
                known = {m for m in methods if m in idx}
                if known and not any(
                    field in idx[m].declared for m in known
                ):
                    self.findings.append(Finding(
                        "wire-discipline", src.path, n.lineno,
                        f"reads undeclared {kind} field {field!r} — not in "
                        f"the schema of {self._fmt(known)}; declare it or "
                        "drop the read",
                    ))

    def _judge_subscript(
        self, src, line: int, field: str, methods: Set[str],
        idx: Dict[str, _SchemaRec], kind: str,
    ) -> None:
        known = {m for m in methods if m in idx}
        if not known:
            return
        if all(field in idx[m].required for m in known):
            return
        optional_somewhere = any(field in idx[m].declared for m in known)
        if optional_somewhere:
            self.findings.append(Finding(
                "wire-discipline", src.path, line,
                f"subscript of OPTIONAL {kind} field {field!r} "
                f"({self._fmt(known)}) — old peers omit it, so this is a "
                "version-skew KeyError; use .get()",
            ))
        else:
            self.findings.append(Finding(
                "wire-discipline", src.path, line,
                f"reads undeclared {kind} field {field!r} — not in the "
                f"schema of {self._fmt(known)}; declare it or drop the "
                "read",
            ))

    @staticmethod
    def _fmt(methods: Set[str]) -> str:
        return "/".join(sorted(methods))


class WireDisciplinePass(LintPass):
    name = "wire-discipline"
    description = (
        "sender payloads carry only declared fields; receiver handlers "
        "and client response reads never subscript optional fields"
    )

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        return WireModel(files).findings


class WireEvolutionPass(LintPass):
    name = "wire-evolution"
    description = (
        "schema changes against artifacts/wire_schema.lock.json: breaking "
        "drift needs a PROTOCOL_VERSION bump + regenerated lock"
    )

    def __init__(
        self,
        lock_path: str = WIRE_LOCK_PATH,
        lock_data: Optional[dict] = None,
    ):
        self._lock_path = lock_path
        self._lock_data = lock_data

    def _load_lock(self) -> Optional[dict]:
        if self._lock_data is not None:
            return self._lock_data
        path = self._lock_path
        if not os.path.isabs(path) and not os.path.exists(path):
            # The default path is repo-relative; the linter may run from
            # any CWD (pytest, an IDE) — fall back to the repo root this
            # package lives in.
            repo = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            )))
            path = os.path.join(repo, self._lock_path)
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        current = wire_fingerprint(files)
        if not current["methods"]:
            return ()  # no wire surface in this file set — nothing to judge
        idx = collect_schema_index(files)
        anchor_path, anchor_line = idx.decl
        lock = self._load_lock()
        findings: List[Finding] = []
        if lock is None:
            return [Finding(
                self.name, anchor_path, anchor_line,
                "no readable wire-schema lock at "
                f"{self._lock_path} — commit one via tools/graftlint.py "
                "--update-wire-lock",
            )]
        if lock == current:
            return ()
        if lock.get("protocol_version") != current["protocol_version"]:
            # A bump declares a new baseline; the only requirement left
            # is that the lock records it (regenerated in the same diff).
            return [Finding(
                self.name, anchor_path, anchor_line,
                f"PROTOCOL_VERSION is {current['protocol_version']} but "
                f"the lock records {lock.get('protocol_version')} — "
                "regenerate artifacts/wire_schema.lock.json "
                "(--update-wire-lock) in the same diff as the bump",
            )]

        def rec_anchor(key: str) -> Tuple[str, int]:
            direction, _, method = key.partition(":")
            table = idx.response if direction == "response" else idx.request
            rec = table.get(method)
            return (rec.path, rec.line) if rec else (anchor_path, anchor_line)

        breaking: List[Tuple[str, Tuple[str, int]]] = []
        additive: List[str] = []
        lock_methods = lock.get("methods", {})
        for key, lrec in sorted(lock_methods.items()):
            crec = current["methods"].get(key)
            if crec is None:
                breaking.append((
                    f"method {key} was removed from the wire", rec_anchor(key)
                ))
                continue
            lfields = {**lrec.get("required", {}), **lrec.get("optional", {})}
            cfields = {**crec["required"], **crec["optional"]}
            for f, types in sorted(lfields.items()):
                if f not in cfields:
                    breaking.append((
                        f"{key} removed field {f!r} — old peers still "
                        "send/expect it", rec_anchor(key),
                    ))
                elif sorted(cfields[f]) != sorted(types):
                    breaking.append((
                        f"{key} changed accepted types of {f!r} "
                        f"({sorted(types)} -> {sorted(cfields[f])})",
                        rec_anchor(key),
                    ))
                elif f in lrec.get("optional", {}) and f in crec["required"]:
                    breaking.append((
                        f"{key} promoted optional field {f!r} to REQUIRED "
                        "— old peers legally omit it", rec_anchor(key),
                    ))
            for f in sorted(crec["required"]):
                if f not in lfields:
                    breaking.append((
                        f"{key} added REQUIRED field {f!r} to an existing "
                        "method — old peers cannot send it",
                        rec_anchor(key),
                    ))
            for f in sorted(crec["optional"]):
                if f not in lfields:
                    additive.append(f"{key} +optional {f!r}")
            if crec.get("since", {}) != lrec.get("since", {}):
                additive.append(f"{key} since-map changed")
        for key in sorted(set(current["methods"]) - set(lock_methods)):
            additive.append(f"new method {key}")
        for msg, (path, line) in breaking:
            findings.append(Finding(
                self.name, path, line,
                f"BREAKING wire change without a PROTOCOL_VERSION bump: "
                f"{msg}; bump PROTOCOL_VERSION and regenerate the lock "
                "(--update-wire-lock) in the same diff",
            ))
        if not breaking and additive:
            findings.append(Finding(
                self.name, anchor_path, anchor_line,
                "additive wire-schema drift ("
                + "; ".join(additive[:6])
                + ("; …" if len(additive) > 6 else "")
                + ") — regenerate artifacts/wire_schema.lock.json "
                "(--update-wire-lock) in this diff",
            ))
        return findings


def wire_inventory(sources: Sequence[SourceFile]) -> dict:
    """The ``--wire`` dump: per method, both schemas plus every resolved
    sender and receiver site — the reviewable map of the control plane."""
    model = WireModel(sources)
    idx = model.index
    out: Dict[str, dict] = {}
    for method in sorted(set(idx.request) | set(idx.response)):
        req = idx.request.get(method)
        resp = idx.response.get(method)
        out[method] = {
            "request": req.as_dict() if req else None,
            "response": resp.as_dict() if resp else None,
            "senders": sorted(set(model.senders.get(method, []))),
            "receivers": sorted(set(model.receivers.get(method, []))),
        }
    return {
        "protocol_version": idx.protocol_version,
        "methods": out,
    }
