"""graftlint — repo-native static analysis.

The r6 review rounds caught latent races and hot-path blockers *by hand*
(rank-asymmetric checkpoint hooks, a raw shard_map call site bypassing
``common/jax_compat.py``, blocking device reads at task boundaries).  This
package encodes those invariants as AST passes so every future change is
gated, not reviewed, into compliance:

- ``lock-discipline``   attributes annotated ``# guarded-by: <lock>`` may
                        only be touched inside ``with self.<lock>:``
- ``hot-path-sync``     functions annotated ``# hot-path`` may not block
                        (device syncs, sleeps, master RPCs) outside a
                        ``phases.phase(...)`` accounting boundary
- ``compat-shim``       raw ``shard_map`` / ``jax.distributed.initialize``
                        / ``lax.axis_size`` only in ``common/jax_compat.py``
- ``collective-shim``   raw ``lax.psum`` / ``lax.pmean`` /
                        ``lax.psum_scatter`` only in
                        ``parallel/collectives.py`` (graftreduce, r15) and
                        ``common/jax_compat.py`` — reductions must route
                        through the layer that owns topology routing and
                        subgroup renormalization
- ``rpc-discipline``    stub call sites carry a timeout or route through a
                        retry wrapper
- ``thread-hygiene``    every ``threading.Thread`` is daemonized or joined
- ``import-hygiene``    master/bench-process modules stay jax-free at
                        import time (transitive)
- ``trace-discipline``  ``# hot-path`` functions emit trace events only via
                        the non-blocking ring API (``common/trace.py``
                        span/instant); export/drain calls are findings
- ``chaos-discipline``  ``# hot-path`` functions cross fault-injection
                        points only via the no-op-when-disabled
                        ``chaos.hook`` API (``chaos/inject.py``);
                        fire/configure/set_context/parse_plan and direct
                        ChaosInjector construction are findings
- ``gauge-discipline``  ``# hot-path`` functions update metrics only via
                        the O(1) counter/gauge/histogram API
                        (``common/gauge.py`` inc/set/add/observe);
                        scrape/aggregation calls (snapshot/
                        render_prometheus/merge_snapshots/...) are
                        findings

v2 adds the interprocedural layer (``analysis/callgraph.py``: resolved
self-method and module-function call edges across the repo):

- ``blocking-propagation``  a ``# hot-path`` function may not reach a
                            blocking call through its CALLEE CHAIN outside
                            a ``phases.phase(...)`` boundary — the helper
                            wrapping ``block_until_ready`` that
                            ``hot-path-sync`` cannot see
- ``lock-order``            the lock acquisition graph (which locks are
                            held when another is acquired, propagated
                            across call edges) must be acyclic and honor
                            ``# lock-order: leaf`` / ``before(...)``
                            declarations; locksan-wrapped locks must agree
                            with their static annotation
- ``stale-waiver``          a waiver that suppresses no finding is itself
                            a finding (the inventory cannot rot)

v5 adds thread-role inference (``analysis/thread_map.py``: Thread/Timer
targets, executor ``submit``/``add_done_callback`` callables, gRPC
handler tables, ``main``, and ``# thread-role:`` declarations, propagated
over call edges plus a constructor-type layer) and on top of it:

- ``shared-state``          a ``self.<attr>`` written on one thread role
                            and touched on another must share a common
                            lexically-held lock, or carry a checked
                            escape hatch: ``# single-writer: <role>``
                            (writes elsewhere are findings) or
                            ``# gil-atomic`` (illegal on read-modify-
                            write sites)

v6 adds compile & transfer discipline (``analysis/jit_discipline.py``):

- ``jit-shim``          raw ``jax.jit``/``jax.pjit`` only in
                        ``common/jax_compat.py``; ``jit_compiled``/
                        ``jit_donating`` call sites declare ``name=``
                        (the jitsan registry / gauge-label key)
- ``jit-stability``     a jit created inside a per-call function body or
                        loop and invoked there rebuilds its compile
                        cache every invocation — bind module-level,
                        memoize on ``self.<attr>``, or return it
                        (builder pattern)
- ``transfer-discipline``  device->host materializations (``.item()``,
                        ``.tolist()``, ``jax.device_get``,
                        ``np.asarray``, ``int()``/``float()``) of values
                        flowing from a ``# jit-boundary`` function must
                        not be reachable from ``# hot-path`` functions
                        outside a ``phases.phase(...)`` boundary —
                        resolved over the v2/v5 call graph, with
                        materializing helpers propagating to hot
                        callers like ``blocking-propagation``

v7 adds durability discipline (``analysis/durability.py``):

- ``durable-write-discipline``  a write touching a path derived from a
                        ``# durable-file`` constant must route through
                        ``common/durable.py`` (atomic publish / fsync'd
                        append); raw ``os.replace``/``os.rename`` (no
                        directory fsync) and hand-rolled ``+ ".tmp"``
                        temp names (no thread-unique component) are
                        findings anywhere outside durable.py/crashsan.py
- ``recovery-read-discipline``  a ``# recovery-path`` function reads
                        durable files only through the shared
                        torn-tolerant readers (``durable.read_wal`` /
                        ``read_json_tolerant``); reading a durable path
                        WITHOUT the annotation is a finding too — the
                        tolerance window is a declared contract, not an
                        accident

v8 adds wire-schema discipline (``analysis/wire_discipline.py``), built
on a schema index EVALUATED from the ``MessageSchema`` literals in
``common/rpc.py`` (the ``*_SCHEMAS`` / ``*_RESPONSE_SCHEMAS`` tables,
their type-alias tuples, and the ``setdefault`` envelope loops):

- ``wire-discipline``   sender payload dicts flowing into ``.call`` /
                        ``.call_async`` sites may not carry undeclared
                        keys (the receiver drops unknown fields —
                        additive-compat — so the data silently
                        vanishes); receiver handlers (resolved via the
                        thread_map ``method_table`` machinery plus the
                        serving tier's dict-literal wiring, with
                        same-file helper propagation) and client
                        response locals may not subscript an OPTIONAL
                        field (old peers omit it; ``.get()`` required)
                        nor read undeclared fields
- ``wire-evolution``    removing a field, changing its accepted types,
                        or adding a REQUIRED field to an existing
                        method is a finding against the committed
                        fingerprint ``artifacts/wire_schema.lock.json``
                        unless PROTOCOL_VERSION is bumped and the lock
                        regenerated (``--update-wire-lock``) in the
                        same diff; additive drift just regenerates

The runtime twin of ``lock-order`` is ``common/locksan.py``: a debug lock
wrapper that records actual acquisition orders under ``GRAFT_LOCKSAN=1``
(on for tier-1 via tests/conftest.py) and raises on inversions or
leaf-order violations — the static model and the runtime behavior gate
each other.  ``shared-state``'s runtime twin is ``common/racesan.py``
(``GRAFT_RACESAN=1``, also tier-1-wide): opted-in classes record
per-attribute (thread-role, held-locks) observations and raise on a
cross-role unguarded write.  The durability rules' runtime twin is
``common/crashsan.py`` (``GRAFT_CRASHSAN=1``, tier-1-wide): every
durable-write crossing is indexed, and ``crash_at(op, mode)`` forges the
exact on-disk state a crash at that point leaves so the recovery readers
are driven through every injectable crash point.  The wire rules' twin is
``common/wiresan.py`` (``GRAFT_WIRESAN=1``, tier-1-wide): every request
AND response crossing ``JsonRpcClient.call`` / ``make_generic_handler``
is validated against its schema, unknown fields are counted per method
(``edl_wire_unknown_fields_total``), and ``GRAFT_WIRESAN_MASK=<rev>``
emulates an old peer by stripping newer-than-``rev`` fields — the
version-skew roundtrip ``tools/wire_skew.py`` stamps into the artifact.

Inline waivers: ``# graftlint: allow[<rule>] <reason>`` — the reason is
mandatory; malformed waivers are themselves findings (``waiver-syntax``).
CLI driver: ``python tools/graftlint.py [paths...]``.  Pure stdlib — the
linter must never pay (or hang on) a jax import.
"""

from elasticdl_tpu.analysis.blocking import BlockingPropagationPass
from elasticdl_tpu.analysis.chaos_discipline import ChaosDisciplinePass
from elasticdl_tpu.analysis.collective_shim import CollectiveShimPass
from elasticdl_tpu.analysis.compat_shim import CompatShimPass
from elasticdl_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintPass,
    SourceFile,
    collect_waivers,
    lint_text,
    run_lint,
    run_lint_full,
)
from elasticdl_tpu.analysis.durability import (
    DurableWriteDisciplinePass,
    RecoveryReadDisciplinePass,
)
from elasticdl_tpu.analysis.gauge_discipline import GaugeDisciplinePass
from elasticdl_tpu.analysis.hot_path import HotPathSyncPass
from elasticdl_tpu.analysis.import_hygiene import ImportHygienePass
from elasticdl_tpu.analysis.jit_discipline import (
    JitShimPass,
    JitStabilityPass,
    TransferDisciplinePass,
)
from elasticdl_tpu.analysis.lock_discipline import LockDisciplinePass
from elasticdl_tpu.analysis.lock_order import LockOrderPass
from elasticdl_tpu.analysis.rpc_discipline import RpcDisciplinePass
from elasticdl_tpu.analysis.shared_state import SharedStatePass
from elasticdl_tpu.analysis.thread_hygiene import ThreadHygienePass
from elasticdl_tpu.analysis.trace_discipline import TraceDisciplinePass
from elasticdl_tpu.analysis.wire_discipline import (
    WireDisciplinePass,
    WireEvolutionPass,
)


def all_passes() -> list:
    """One fresh instance of every pass (passes are stateless between runs,
    but a fresh list keeps callers from accidentally sharing config)."""
    return [
        LockDisciplinePass(),
        HotPathSyncPass(),
        BlockingPropagationPass(),
        CompatShimPass(),
        CollectiveShimPass(),
        RpcDisciplinePass(),
        ThreadHygienePass(),
        ImportHygienePass(),
        LockOrderPass(),
        SharedStatePass(),
        TraceDisciplinePass(),
        ChaosDisciplinePass(),
        GaugeDisciplinePass(),
        JitShimPass(),
        JitStabilityPass(),
        TransferDisciplinePass(),
        DurableWriteDisciplinePass(),
        RecoveryReadDisciplinePass(),
        WireDisciplinePass(),
        WireEvolutionPass(),
    ]
