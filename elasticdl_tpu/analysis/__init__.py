"""graftlint — repo-native static analysis.

The r6 review rounds caught latent races and hot-path blockers *by hand*
(rank-asymmetric checkpoint hooks, a raw shard_map call site bypassing
``common/jax_compat.py``, blocking device reads at task boundaries).  This
package encodes those invariants as AST passes so every future change is
gated, not reviewed, into compliance:

- ``lock-discipline``   attributes annotated ``# guarded-by: <lock>`` may
                        only be touched inside ``with self.<lock>:``
- ``hot-path-sync``     functions annotated ``# hot-path`` may not block
                        (device syncs, sleeps, master RPCs) outside a
                        ``phases.phase(...)`` accounting boundary
- ``compat-shim``       raw ``shard_map`` / ``jax.distributed.initialize``
                        / ``lax.axis_size`` only in ``common/jax_compat.py``
- ``rpc-discipline``    stub call sites carry a timeout or route through a
                        retry wrapper
- ``thread-hygiene``    every ``threading.Thread`` is daemonized or joined
- ``import-hygiene``    master/bench-process modules stay jax-free at
                        import time (transitive)

Inline waivers: ``# graftlint: allow[<rule>] <reason>`` — the reason is
mandatory; malformed waivers are themselves findings (``waiver-syntax``).
CLI driver: ``python tools/graftlint.py [paths...]``.  Pure stdlib — the
linter must never pay (or hang on) a jax import.
"""

from elasticdl_tpu.analysis.compat_shim import CompatShimPass
from elasticdl_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintPass,
    SourceFile,
    lint_text,
    run_lint,
)
from elasticdl_tpu.analysis.hot_path import HotPathSyncPass
from elasticdl_tpu.analysis.import_hygiene import ImportHygienePass
from elasticdl_tpu.analysis.lock_discipline import LockDisciplinePass
from elasticdl_tpu.analysis.rpc_discipline import RpcDisciplinePass
from elasticdl_tpu.analysis.thread_hygiene import ThreadHygienePass


def all_passes() -> list:
    """One fresh instance of every pass (passes are stateless between runs,
    but a fresh list keeps callers from accidentally sharing config)."""
    return [
        LockDisciplinePass(),
        HotPathSyncPass(),
        CompatShimPass(),
        RpcDisciplinePass(),
        ThreadHygienePass(),
        ImportHygienePass(),
    ]
