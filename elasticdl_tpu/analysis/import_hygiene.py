"""import-hygiene: control-plane modules stay jax-free at import time.

The master, bench drivers, and test harness processes deliberately never
import jax: a jax import in this image can register the out-of-process TPU
PJRT plugin and hang on (or fight for) the chip, and it costs ~13 s of the
relaunch path (docs/perf.md).  r6 hoisted ``free_port`` into the jax-free
``common/platform.py`` for exactly this reason; this pass locks the
property in *transitively*: for each root module below, walk module-level
imports (function-local imports are deferred by definition and do not
count) across the repo's own modules — importing a module also executes
its ancestor packages' ``__init__`` — and flag any path that reaches a
top-level ``import jax``.

The finding is reported at the root's offending import line with the full
chain, so the fix site is obvious.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from elasticdl_tpu.analysis.core import Finding, LintPass, SourceFile

#: Modules that must import without pulling jax into the process.  Keyed by
#: dotted module name (derived from repo-relative paths).
DEFAULT_JAX_FREE_ROOTS = (
    "elasticdl_tpu.common.platform",
    "elasticdl_tpu.common.config",
    "elasticdl_tpu.common.log_utils",
    "elasticdl_tpu.common.metrics",
    "elasticdl_tpu.common.rpc",
    # r13: the fault injector rides in the master control plane (rpc.py
    # imports it) and in the jax-free bench tools — its own root keeps the
    # contract explicit even if the rpc edge ever moves.
    "elasticdl_tpu.chaos.inject",
    "elasticdl_tpu.master.main",
    "elasticdl_tpu.master.servicer",
    "elasticdl_tpu.master.pod_manager",
    "elasticdl_tpu.master.task_dispatcher",
    "elasticdl_tpu.master.rendezvous",
    "elasticdl_tpu.master.evaluation_service",
    "elasticdl_tpu.analysis",
    "tools.artifact",
    "tools.graftlint",
)

_BANNED_TOP = "jax"

#: common/platform.py helpers that import jax INSIDE their body: a deferred
#: import the graph walk cannot see — unless the module CALLS one at module
#: level, which executes the import right there.  (This is exactly how
#: master/main.py leaked jax into the control plane: a module-level
#: ``apply_platform_env()`` call, found by the runtime twin test.)
JAX_IMPORTING_CALLS = frozenset(
    {"apply_platform_env", "enable_compile_cache", "probe_devices"}
)


def _module_name(path: str) -> Optional[str]:
    """Repo-relative ``a/b/c.py`` -> ``a.b.c``; ``a/b/__init__.py`` ->
    ``a.b``.  Absolute/outside paths return None."""
    p = path.replace("\\", "/")
    if not p.endswith(".py") or p.startswith("/"):
        return None
    parts = p[:-3].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts or any(not seg.isidentifier() for seg in parts):
        return None
    return ".".join(parts)


def _top_level_imports(tree: ast.Module) -> List[Tuple[str, int]]:
    """(dotted module, line) pairs imported when the module is imported:
    module-body imports, including those under top-level ``if``/``try``
    (conditional top-level imports still execute at import time on some
    path, so they count).  A module-level CALL to a known jax-importing
    helper (``JAX_IMPORTING_CALLS``) records a direct jax edge."""
    out: List[Tuple[str, int]] = []

    def scan_calls(node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                f = sub.func
                name = (
                    f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute)
                    else ""
                )
                if name in JAX_IMPORTING_CALLS:
                    out.append((_BANNED_TOP, sub.lineno))

    def visit(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # deferred: bodies run later, not at import
            if isinstance(node, ast.ClassDef):
                visit(node.body)  # class bodies DO execute at import
                continue
            if isinstance(node, (ast.Expr, ast.Assign, ast.AnnAssign)):
                scan_calls(node)
            if isinstance(node, ast.Import):
                for alias in node.names:
                    out.append((alias.name, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                if node.level:  # relative import — not used in this repo
                    continue
                mod = node.module or ""
                if mod:
                    out.append((mod, node.lineno))
                    for alias in node.names:
                        # ``from pkg import submodule`` imports pkg.submodule
                        # when it is a module; recorded speculatively — the
                        # graph only keeps edges that resolve to real files.
                        out.append((f"{mod}.{alias.name}", node.lineno))
            elif isinstance(node, ast.If):
                visit(node.body)
                visit(node.orelse)
            elif isinstance(node, ast.Try):
                visit(node.body)
                for h in node.handlers:
                    visit(h.body)
                visit(node.orelse)
                visit(node.finalbody)
            elif isinstance(node, (ast.With, ast.For, ast.While)):
                # Any compound statement at module level runs at import
                # time — a loop body can smuggle an import just as an
                # if-branch can.
                visit(node.body)
                visit(getattr(node, "orelse", []) or [])

    visit(tree.body)
    return out


def module_dependents(
    files: Sequence[SourceFile], changed_paths: set
) -> set:
    """Display paths of modules that (transitively) import any CHANGED
    module at module level — the re-lint scope ``--changed`` adds so the
    project-wide passes (import-hygiene, lock-order) judge every root a
    change can affect, not just the changed files themselves.  Importing
    ``a.b.c`` executes ``a`` and ``a.b`` too, so a changed package
    ``__init__`` pulls in every importer underneath it."""
    mod_path: Dict[str, str] = {}
    for src in files:
        name = _module_name(src.path)
        if name is not None:
            mod_path[name] = src.path
    rev: Dict[str, set] = {}
    for src in files:
        name = _module_name(src.path)
        if name is None:
            continue
        for target, _line in _top_level_imports(src.tree):
            parts = target.split(".")
            for i in range(1, len(parts) + 1):
                cand = ".".join(parts[:i])
                if cand in mod_path:
                    rev.setdefault(cand, set()).add(name)
    changed_mods = [m for m, p in mod_path.items() if p in changed_paths]
    seen = set(changed_mods)
    queue = list(changed_mods)
    while queue:
        cur = queue.pop()
        for dep in rev.get(cur, ()):
            if dep not in seen:
                seen.add(dep)
                queue.append(dep)
    return {mod_path[m] for m in seen}


class ImportHygienePass(LintPass):
    name = "import-hygiene"
    description = (
        "designated control-plane modules must not transitively import jax "
        "at module level"
    )

    def __init__(self, roots: Sequence[str] = DEFAULT_JAX_FREE_ROOTS):
        self.roots = tuple(roots)

    def run_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        modules: Dict[str, SourceFile] = {}
        for src in files:
            name = _module_name(src.path)
            if name is not None:
                modules[name] = src
        imports: Dict[str, List[Tuple[str, int]]] = {
            name: _top_level_imports(src.tree)
            for name, src in modules.items()
        }
        findings: List[Finding] = []
        for root in self.roots:
            if root not in modules:
                continue
            chain = self._find_jax_chain(root, modules, imports)
            if chain is not None:
                path_str, line = chain
                findings.append(Finding(
                    self.name, modules[root].path, line,
                    f"{root} must stay jax-free but reaches a module-level "
                    f"'import jax' via: {path_str} — defer the import into "
                    "the function that needs it",
                ))
        return findings

    def _ancestors(self, name: str) -> List[str]:
        parts = name.split(".")
        return [".".join(parts[:i]) for i in range(1, len(parts))]

    def _find_jax_chain(self, root, modules, imports):
        """BFS from ``root``; returns (chain string, root's offending import
        line) on the first path to jax, else None."""
        seen = set()
        # queue entries: (module, chain-so-far, root_line)
        queue: List[Tuple[str, List[str], Optional[int]]] = [(root, [root], None)]
        while queue:
            mod, chain, root_line = queue.pop(0)
            if mod in seen:
                continue
            seen.add(mod)
            for target, line in imports.get(mod, ()):
                at_root = mod == root
                eff_line = line if at_root else root_line
                if target == _BANNED_TOP or target.startswith(_BANNED_TOP + "."):
                    return (
                        " -> ".join(chain + ["jax"]),
                        eff_line if eff_line is not None else 1,
                    )
                # An import of a.b.c executes packages a and a.b too.
                for cand in self._ancestors(target) + [target]:
                    if cand in modules and cand not in seen:
                        queue.append((cand, chain + [cand], eff_line))
        return None
