"""Whole-repo call graph: the interprocedural layer under graftlint v2.

r7's passes were strictly intra-procedural — a blocking call or a lock
acquisition hidden ONE call deep was invisible (`hot-path-sync` could not
see a one-line helper wrapping ``block_until_ready``; lock nesting through
a ``self._helper()`` call was not an edge).  This module builds the shared
function index + call-edge resolution both v2 passes (blocking-propagation,
lock-order) consume:

Resolved edges (deliberately conservative — every edge is real):

- ``self.method(...)``      -> a method of the lexically enclosing class;
- ``func(...)``             -> a module-level function of the same module,
                               or one bound by ``from mod import func``;
- ``mod.func(...)``         -> a module-level function of an imported repo
                               module (``import mod`` / ``import pkg.mod`` /
                               ``from pkg import mod`` / aliases).

Known blind spots (documented in docs/static_analysis.md and covered by
the runtime sanitizer instead): dynamic dispatch through object attributes
(``self.dispatcher.get_task(...)`` — the receiver's type is not tracked),
``getattr`` / method tables, callbacks/lambdas handed across objects,
class constructors, and ``super()``.

Per function the graph also records the facts the v2 passes need at each
site:

- *call sites* with the blocking-exemption context (inside a
  ``phases.phase(...)`` boundary / an ``except`` handler) and the set of
  locks lexically held;
- *blocking primitives* (shared detector with hot-path-sync) with the same
  context plus whether the line carries a ``hot-path-sync`` waiver — a
  reasoned waiver covers the transitive concern too, so waived blocking
  does not propagate to callers;
- *lock acquisitions* (``with self.<lock>:`` / ``with <module_lock>:`` of
  a lock DECLARED in scope) with the locks already held.

Nested ``def``/``lambda`` bodies are separate anonymous scopes: their
execution is deferred (background threads own their own time and their own
lock stacks), so their blocking never propagates to the enclosing function
and their acquisitions start from an empty held set.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from elasticdl_tpu.analysis.core import SourceFile
from elasticdl_tpu.analysis.hot_path import blocking_reason, is_phase_context
from elasticdl_tpu.analysis.import_hygiene import _module_name

#: Constructors that declare a lock attribute (the runtime wrapper spellings
#: come first: common/locksan.py is the sanitizer the declarations feed).
_LOCK_CTOR_CHAINS = {
    "threading.Lock": False,
    "threading.RLock": True,
    # Condition() defaults to wrapping an RLock: same-thread nested entry
    # is legal, so it must not produce self-deadlock findings.
    "threading.Condition": True,
    "locksan.lock": False,
    "locksan.rlock": True,
}


@dataclasses.dataclass
class CallSite:
    callee: str  # qualified "module:Class.method" / "module:func"
    line: int
    exempt: bool  # inside a phase boundary or except handler
    held: Tuple[str, ...]  # lock ids lexically held at the site


@dataclasses.dataclass
class BlockingCall:
    line: int
    reason: str
    exempt: bool
    waived: bool  # carries a hot-path-sync waiver: accounted by a human


@dataclasses.dataclass
class LockAcquire:
    lock: str  # qualified lock id "module:Class.attr" / "module:attr"
    line: int
    held: Tuple[str, ...]


@dataclasses.dataclass
class AttrAccess:
    """One ``self.<attr>`` touch inside a class-scoped function, with the
    locks lexically held at the site — the raw material of the v5
    shared-state pass (analysis/shared_state.py)."""

    attr: str
    line: int
    write: bool
    rmw: bool  # read-modify-write in ONE site (augmented assignment)
    held: Tuple[str, ...]


@dataclasses.dataclass
class FunctionInfo:
    qualname: str  # "module:Class.method" / "module:func" / anon scopes
    path: str
    line: int
    hot_path: bool
    resolvable: bool  # False for nested/anonymous scopes
    cls_name: str = ""  # lexically enclosing class ("" for module funcs)
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    blocking: List[BlockingCall] = dataclasses.field(default_factory=list)
    acquires: List[LockAcquire] = dataclasses.field(default_factory=list)
    attr_accesses: List[AttrAccess] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class LockDecl:
    lock_id: str  # "module:Class.attr" / "module:attr"
    attr: str
    cls: str  # "" for module-level locks
    module: str
    path: str
    line: int
    reentrant: bool
    is_locksan: bool
    rt_name: Optional[str]  # locksan.lock("<name>") first argument
    rt_leaf: bool  # locksan leaf= kwarg
    rt_before: Tuple[str, ...]  # locksan before= kwarg (attr names)


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _chain(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def partial_target(node: ast.AST) -> Optional[ast.expr]:
    """``functools.partial(T, ...)`` / ``partial(T, ...)`` -> the wrapped
    callable expression ``T``, else None.  Shared by the thread map (v6):
    a partial handed to ``Thread(target=...)`` / ``pool.submit(...)``
    executes its wrapped callable on the spawned thread, so the role
    resolver must see through it — before v6, partial-wrapped targets got
    no role, silently muting shared-state checks on everything they
    touch.  Only the two canonical spellings match (``functools.partial``
    and a bare ``partial`` import); an arbitrary ``obj.partial(...)``
    method stays dynamic."""
    if not isinstance(node, ast.Call):
        return None
    chain = _chain(node.func)
    if chain in ("partial", "functools.partial") and node.args:
        return node.args[0]
    return None


def _lock_ctor(node: ast.AST) -> Optional[Tuple[bool, bool]]:
    """(is_lock, reentrant) when ``node`` is a lock-constructor call."""
    if not isinstance(node, ast.Call):
        return None
    chain = _chain(node.func)
    tail = ".".join(chain.split(".")[-2:]) if "." in chain else chain
    if tail in _LOCK_CTOR_CHAINS:
        return True, _LOCK_CTOR_CHAINS[tail]
    return None


def _locksan_meta(node: ast.Call) -> Tuple[Optional[str], bool, Tuple[str, ...]]:
    """(rt_name, leaf, before) from a ``locksan.lock(...)`` call."""
    rt_name: Optional[str] = None
    if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
        node.args[0].value, str
    ):
        rt_name = node.args[0].value
    leaf = False
    before: Tuple[str, ...] = ()
    for kw in node.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
            rt_name = str(kw.value.value)
        elif kw.arg == "leaf" and isinstance(kw.value, ast.Constant):
            leaf = kw.value.value is True
        elif kw.arg == "before" and isinstance(kw.value, (ast.Tuple, ast.List)):
            before = tuple(
                e.value
                for e in kw.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
    return rt_name, leaf, before


#: One-entry memo for :func:`shared_graph`: both v2 passes (and the CLI's
#: --callgraph/--artifact stats) consume the SAME parsed file set within a
#: run; rebuilding the graph per consumer tripled the pre-commit cost.
#: Keyed by the identity of every SourceFile (the cached entry keeps a
#: strong reference to them, so the ids stay valid while it lives).
_GRAPH_MEMO: dict = {}


def shared_graph(files: Sequence[SourceFile]) -> "CallGraph":
    """The CallGraph for ``files``, built at most once per file set."""
    key = tuple(id(s) for s in files)
    hit = _GRAPH_MEMO.get(key)
    if hit is not None:
        return hit[1]
    graph = CallGraph(files)
    _GRAPH_MEMO.clear()  # one entry: the current run's file set
    _GRAPH_MEMO[key] = (list(files), graph)
    return graph


class CallGraph:
    """Function index + resolved call edges over a set of SourceFiles."""

    def __init__(self, files: Sequence[SourceFile]):
        self.functions: Dict[str, FunctionInfo] = {}
        self.locks: Dict[str, LockDecl] = {}
        self.sources: Dict[str, SourceFile] = {s.path: s for s in files}
        self._blocking_memo: Optional[Dict[str, List[str]]] = None
        self._edges_memo: Optional[Dict[Tuple[str, str], List[str]]] = None
        #: module -> {local name -> qualified target}; filled in two passes
        #: (the index must be complete before edges resolve).
        self._modules: Dict[str, SourceFile] = {}
        self._mod_funcs: Dict[str, set] = {}
        self._mod_classes: Dict[str, Dict[str, set]] = {}
        self._imports: Dict[str, Dict[str, str]] = {}  # alias -> module
        self._from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        for src in files:
            mod = _module_name(src.path) or src.path
            self._modules[mod] = src
        for mod, src in self._modules.items():
            self._index_module(mod, src)
        for mod, src in self._modules.items():
            self._extract_module(mod, src)

    # -- pass 1: symbol + import index --

    def _index_module(self, mod: str, src: SourceFile) -> None:
        funcs: set = set()
        classes: Dict[str, set] = {}
        imports: Dict[str, str] = {}
        from_imports: Dict[str, Tuple[str, str]] = {}
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.add(node.name)
            elif isinstance(node, ast.ClassDef):
                classes[node.name] = {
                    n.name
                    for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    # ``import a.b`` binds ``a``; ``import a.b as ab`` binds
                    # ``ab`` to a.b directly.
                    imports[bound] = alias.name if alias.asname else (
                        alias.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and not node.level:
                base = node.module or ""
                for alias in node.names:
                    bound = alias.asname or alias.name
                    from_imports[bound] = (base, alias.name)
        self._mod_funcs[mod] = funcs
        self._mod_classes[mod] = classes
        self._imports[mod] = imports
        self._from_imports[mod] = from_imports

    # -- pass 2: per-function extraction --

    def _extract_module(self, mod: str, src: SourceFile) -> None:
        # Module-level lock declarations: ``_lib_lock = threading.Lock()``.
        for node in src.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Name)
            ):
                self._maybe_declare_lock(
                    mod, src, "", node.targets[0].id, node.value, node.lineno
                )
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._extract_function(mod, src, None, node, f"{mod}:{node.name}")
            elif isinstance(node, ast.ClassDef):
                # Class-scoped lock declarations live in ANY method (almost
                # always __init__) as ``self.<attr> = threading.Lock()``.
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                        attr = _self_attr(sub.targets[0])
                        if attr is not None:
                            self._maybe_declare_lock(
                                mod, src, node.name, attr, sub.value, sub.lineno
                            )
                for meth in node.body:
                    if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._extract_function(
                            mod, src, node, meth,
                            f"{mod}:{node.name}.{meth.name}",
                        )

    def _maybe_declare_lock(
        self, mod, src, cls: str, attr: str, value: ast.AST, line: int
    ) -> None:
        ctor = _lock_ctor(value)
        if ctor is None:
            return
        lock_id = f"{mod}:{cls}.{attr}" if cls else f"{mod}:{attr}"
        chain = _chain(value.func)
        is_locksan = chain.split(".")[-2:-1] == ["locksan"] or chain.startswith(
            "locksan."
        )
        rt_name, rt_leaf, rt_before = (
            _locksan_meta(value) if is_locksan else (None, False, ())
        )
        self.locks[lock_id] = LockDecl(
            lock_id=lock_id, attr=attr, cls=cls, module=mod,
            path=src.path, line=line, reentrant=ctor[1],
            is_locksan=is_locksan, rt_name=rt_name, rt_leaf=rt_leaf,
            rt_before=rt_before,
        )

    def _extract_function(self, mod, src, cls, node, qualname) -> None:
        info = FunctionInfo(
            qualname=qualname,
            path=src.path,
            line=node.lineno,
            hot_path=src.is_hot_path(node.lineno),
            resolvable=True,
            cls_name=cls.name if cls is not None else "",
        )
        self.functions[qualname] = info
        self._walk(mod, src, cls, info, node.body, exempt=False, held=())

    def _walk(self, mod, src, cls, info, body, exempt, held) -> None:
        for node in body:
            self._visit(mod, src, cls, info, node, exempt, held)

    def _visit(self, mod, src, cls, info, node, exempt, held) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # Deferred scope: fresh anonymous FunctionInfo, empty held set,
            # not resolvable as a call target.  Its lock nesting still
            # counts (a closure IS eventually some thread's code).
            anon = FunctionInfo(
                qualname=f"{info.qualname}.<{getattr(node, 'name', 'lambda')}"
                f"@{node.lineno}>",
                path=src.path, line=node.lineno, hot_path=False,
                resolvable=False, cls_name=info.cls_name,
            )
            self.functions[anon.qualname] = anon
            body = node.body if isinstance(node.body, list) else [node.body]
            self._walk(mod, src, cls, anon, body, exempt=False, held=())
            return
        if isinstance(node, ast.With):
            new_held = held
            new_exempt = exempt
            for item in node.items:
                ctx = item.context_expr
                if is_phase_context(ctx):
                    new_exempt = True
                    continue
                lock = self._lock_of_ctx(mod, cls, ctx)
                if lock is not None:
                    info.acquires.append(
                        LockAcquire(lock=lock, line=node.lineno, held=new_held)
                    )
                    new_held = new_held + (lock,)
                else:
                    self._visit(mod, src, cls, info, ctx, exempt, held)
            self._walk(mod, src, cls, info, node.body, new_exempt, new_held)
            return
        if isinstance(node, ast.Try):
            self._walk(mod, src, cls, info, node.body, exempt, held)
            self._walk(mod, src, cls, info, node.orelse, exempt, held)
            self._walk(mod, src, cls, info, node.finalbody, exempt, held)
            for h in node.handlers:
                # Error path: exempt for blocking, NOT for locks (a lock
                # taken while recovering still nests for real).
                self._walk(mod, src, cls, info, h.body, True, held)
            return
        if isinstance(node, ast.AugAssign):
            # ``self.x += 1`` is a read AND a write at one site — the
            # check-and-set shape the shared-state pass must see as a
            # read-modify-write (never legal under '# gil-atomic').
            # ``self.d[k] += 1`` mutates the SHARED CONTAINER through the
            # attribute: same read-modify-write judgement on the attr.
            attr = _self_attr(node.target)
            if attr is None and isinstance(node.target, ast.Subscript):
                attr = _self_attr(node.target.value)
                if attr is not None and cls is not None:
                    info.attr_accesses.append(AttrAccess(
                        attr=attr, line=node.lineno, write=True, rmw=True,
                        held=held,
                    ))
                    self._visit(
                        mod, src, cls, info, node.target.slice, exempt, held
                    )
                    self._visit(mod, src, cls, info, node.value, exempt, held)
                    return
                self._visit(mod, src, cls, info, node.target, exempt, held)
            elif attr is not None and cls is not None:
                info.attr_accesses.append(AttrAccess(
                    attr=attr, line=node.lineno, write=True, rmw=True,
                    held=held,
                ))
            self._visit(mod, src, cls, info, node.value, exempt, held)
            return
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            # ``self.d[k] = v`` / ``del self.d[k]`` mutate the shared
            # container: a WRITE of the attribute (single-op, not rmw —
            # dict/list item set is one GIL-atomic op).  The generic
            # recursion below still records the receiver's Load, which is
            # harmless (same line, same held set).
            attr = _self_attr(node.value)
            if attr is not None and cls is not None:
                info.attr_accesses.append(AttrAccess(
                    attr=attr, line=node.lineno, write=True, rmw=False,
                    held=held,
                ))
        if isinstance(node, ast.Attribute):
            # Innermost ``self.<attr>`` only: for ``self.a.b`` the chain
            # recurses down to the ``self.a`` load (the shared slot) —
            # ``b`` lives on another object.
            attr = _self_attr(node)
            if attr is not None and cls is not None:
                info.attr_accesses.append(AttrAccess(
                    attr=attr, line=node.lineno,
                    write=isinstance(node.ctx, (ast.Store, ast.Del)),
                    rmw=False, held=held,
                ))
        if isinstance(node, ast.Call):
            reason = blocking_reason(node)
            if reason is not None:
                info.blocking.append(BlockingCall(
                    line=node.lineno, reason=reason, exempt=exempt,
                    waived=self._line_waives(src, node.lineno, "hot-path-sync"),
                ))
            callee = self._resolve_call(mod, cls, node.func)
            if callee is not None:
                info.calls.append(CallSite(
                    callee=callee, line=node.lineno, exempt=exempt, held=held,
                ))
        for child in ast.iter_child_nodes(node):
            self._visit(mod, src, cls, info, child, exempt, held)

    @staticmethod
    def _line_waives(src: SourceFile, line: int, rule: str) -> bool:
        for cand in (line, line - 1):
            w = src.waivers.get(cand)
            if w is not None and w.rule == rule and (
                cand == line or cand in src.comment_only_lines
            ):
                # A waiver consumed HERE is load-bearing even when its
                # function is not hot-path-marked (it stops the primitive
                # from propagating to hot callers) — record usage or the
                # stale-waiver pass would tell the user to delete it.
                src.used_waiver_lines.add(cand)
                return True
        return False

    def _lock_of_ctx(self, mod, cls, ctx: ast.expr) -> Optional[str]:
        attr = _self_attr(ctx)
        if attr is not None and cls is not None:
            lock_id = f"{mod}:{cls.name}.{attr}"
            return lock_id if lock_id in self.locks else None
        if isinstance(ctx, ast.Name):
            lock_id = f"{mod}:{ctx.id}"
            return lock_id if lock_id in self.locks else None
        return None

    def _resolve_call(self, mod, cls, func: ast.expr) -> Optional[str]:
        if isinstance(func, ast.Name):
            name = func.id
            if name in self._mod_funcs.get(mod, ()):
                return f"{mod}:{name}"
            tgt = self._from_imports.get(mod, {}).get(name)
            if tgt is not None:
                base, leaf = tgt
                if leaf in self._mod_funcs.get(base, ()):
                    return f"{base}:{leaf}"
            return None
        if isinstance(func, ast.Attribute):
            attr = _self_attr(func)
            if attr is not None:
                if cls is not None and attr in self._mod_classes.get(mod, {}).get(
                    cls.name, ()
                ):
                    return f"{mod}:{cls.name}.{attr}"
                return None
            chain = _chain(func)
            if not chain or "." not in chain:
                return None
            prefix, leaf = chain.rsplit(".", 1)
            target_mod = self._resolve_module(mod, prefix)
            if target_mod is not None and leaf in self._mod_funcs.get(
                target_mod, ()
            ):
                return f"{target_mod}:{leaf}"
        return None

    def _resolve_module(self, mod: str, prefix: str) -> Optional[str]:
        """Dotted receiver prefix -> repo module name, via this module's
        import bindings (``import a.b`` binds ``a``; dotted access walks
        down from there)."""
        head, _, rest = prefix.partition(".")
        from_tgt = self._from_imports.get(mod, {}).get(head)
        if from_tgt is not None:
            base, leaf = from_tgt
            cand = f"{base}.{leaf}" if base else leaf
            cand = f"{cand}.{rest}" if rest else cand
            return cand if cand in self._modules else None
        bound = self._imports.get(mod, {}).get(head)
        if bound is None:
            return None
        cand = bound if bound.split(".")[0] != head or bound == head else head
        cand = f"{cand}.{rest}" if rest else cand
        if cand in self._modules:
            return cand
        # ``import a.b`` bound ``a``: the chain ``a.b.f`` walks a.b.
        cand2 = f"{head}.{rest}" if rest else head
        return cand2 if cand2 in self._modules else None

    # -- class resolution (the thread-map's constructor-type layer) --

    def resolve_class(self, mod: str, func: ast.expr) -> Optional[str]:
        """``ClassName(...)``'s class as ``"module:Class"`` when it is a
        repo class visible from ``mod`` (local, ``from m import Class``,
        or ``m.Class``); None otherwise."""
        if isinstance(func, ast.Name):
            name = func.id
            if name in self._mod_classes.get(mod, {}):
                return f"{mod}:{name}"
            tgt = self._from_imports.get(mod, {}).get(name)
            if tgt is not None:
                base, leaf = tgt
                if leaf in self._mod_classes.get(base, {}):
                    return f"{base}:{leaf}"
            return None
        if isinstance(func, ast.Attribute):
            chain = _chain(func)
            if not chain or "." not in chain:
                return None
            prefix, leaf = chain.rsplit(".", 1)
            target_mod = self._resolve_module(mod, prefix)
            if target_mod is not None and leaf in self._mod_classes.get(
                target_mod, {}
            ):
                return f"{target_mod}:{leaf}"
        return None

    def class_method(self, cls_q: str, meth: str) -> Optional[str]:
        """``("module:Class", "meth")`` -> the method's qualname when the
        class declares it."""
        mod, _, cls = cls_q.partition(":")
        if meth in self._mod_classes.get(mod, {}).get(cls, ()):
            return f"{mod}:{cls}.{meth}"
        return None

    # -- derived: transitive blocking --

    def blocking_witnesses(self) -> Dict[str, List[str]]:
        """qualname -> witness chain (site strings down to a primitive) for
        every function that may block at steady state.  Waived primitives
        and phase-boundary/except-handler sites do not count."""
        if self._blocking_memo is not None:
            return self._blocking_memo
        wit: Dict[str, List[str]] = {}
        changed = True
        while changed:
            changed = False
            for q, fn in self.functions.items():
                if q in wit or not fn.resolvable:
                    continue
                w: Optional[List[str]] = None
                for b in fn.blocking:
                    if not b.exempt and not b.waived:
                        w = [f"{fn.path}:{b.line} {b.reason}"]
                        break
                if w is None:
                    for c in fn.calls:
                        if c.exempt:
                            continue
                        sub = wit.get(c.callee)
                        if sub is not None:
                            w = [
                                f"{fn.path}:{c.line} calls "
                                f"{c.callee.split(':')[-1]}"
                            ] + sub
                            break
                if w is not None:
                    wit[q] = w
                    changed = True
        self._blocking_memo = wit
        return wit

    def blocking_roots(self) -> List[str]:
        """Functions that DIRECTLY block (non-exempt, non-waived primitive)
        — the propagation roots the artifact counts."""
        return sorted(
            q for q, fn in self.functions.items()
            if fn.resolvable
            and any(not b.exempt and not b.waived for b in fn.blocking)
        )

    # -- derived: lock acquisition graph --

    def lock_closures(self) -> Dict[str, Dict[str, List[str]]]:
        """qualname -> {lock_id: witness chain of sites acquiring it},
        including locks acquired by transitive callees."""
        clo: Dict[str, Dict[str, List[str]]] = {
            q: {} for q in self.functions
        }
        for q, fn in self.functions.items():
            for a in fn.acquires:
                clo[q].setdefault(
                    a.lock, [f"{fn.path}:{a.line} acquires {a.lock}"]
                )
        changed = True
        while changed:
            changed = False
            for q, fn in self.functions.items():
                for c in fn.calls:
                    sub = clo.get(c.callee)
                    if not sub:
                        continue
                    for lock, chain in sub.items():
                        if lock not in clo[q]:
                            clo[q][lock] = [
                                f"{fn.path}:{c.line} calls "
                                f"{c.callee.split(':')[-1]}"
                            ] + chain
                            changed = True
        return clo

    def lock_edges(self) -> Dict[Tuple[str, str], List[str]]:
        """(held, acquired) -> first witness chain observed.  Direct
        acquisitions under a held lock, plus call sites whose callee's
        closure acquires locks."""
        if self._edges_memo is not None:
            return self._edges_memo
        clo = self.lock_closures()
        edges: Dict[Tuple[str, str], List[str]] = {}
        for q, fn in self.functions.items():
            for a in fn.acquires:
                for h in a.held:
                    edges.setdefault(
                        (h, a.lock),
                        [f"{fn.path}:{a.line} {q.split(':')[-1]} acquires "
                         f"{a.lock} while holding {h}"],
                    )
            for c in fn.calls:
                sub = clo.get(c.callee)
                if not sub:
                    continue
                for h in c.held:
                    for lock, chain in sub.items():
                        edges.setdefault(
                            (h, lock),
                            [f"{fn.path}:{c.line} {q.split(':')[-1]} calls "
                             f"{c.callee.split(':')[-1]} while holding {h}"]
                            + chain,
                        )
        self._edges_memo = edges
        return edges
