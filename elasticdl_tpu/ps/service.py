"""Parameter-server service tier: the native host store behind gRPC.

Reference parity (SURVEY.md §2 #10, §3.4 [U — mount empty at survey time;
existence of a gRPC parameter server is [D]: BASELINE.json names "gRPC
parameter server" / pull_embedding_vectors / push_gradients): the reference
runs dedicated PS pods — a gRPC service over a KV embedding store that
applies gradients server-side — which every worker dials.  Here the same
tier is ``PSServer``: a gRPC wrapper around the native C++
``HostEmbeddingStore`` (ps/native/edl_native.cc), launched as PS pods by the
master when ``--num_ps_pods > 0``, serving ``Pull`` / ``PushGrad`` /
``Save`` / ``Load`` / ``Stats``.

This tier exists for tables too large for the device mesh (the normal
ParameterServer strategy shards tables over HBM — ops/embedding.py — which
beats any RPC hop; see models/spec.HostTableIO).  Putting the host tier
behind gRPC is what makes host-tier tables work on MULTI-PROCESS meshes: the
store must be one shared service, not a per-worker-process sidecar, or each
process would train a divergent copy of the rows.

Sharding: ``--num_ps_pods = n`` partitions every table by ``id mod n`` (the
reference partitions its embedding KV the same way across PS pods [U]).
Row init is deterministic per id (splitmix64 in the native store), so the
row a fresh id materializes as is identical no matter which shard serves it
or how many shards exist.

Wire format: tensors ride as raw little-endian buffers after a JSON header
(``encode_frame``/``decode_frame``) — NOT JSON-encoded floats; a Pull of
8192x26 dim-8 rows is ~6.8 MB of f32, which JSON would inflate ~4x and
dominate the RPC cost.  The frame schema is validated at both ends like the
master's MASTER_SCHEMAS contract (common/rpc.py).

Failure/durability model (async-PS semantics, as the reference's):

- PS pods outlive worker restarts: an elastic worker re-join does NOT roll
  the host tier back to the checkpoint step (workers' dense params restore
  to step S while PS rows stay live).  The reference's PS behaves the same
  way — pushed gradients are never un-applied.
- ``Save`` makes each shard dump its own slice atomically
  (``{key}.shard{i}of{n}.bin``), mirroring "PS shards each dump their
  slice" (SURVEY.md §5 checkpoint row); the worker that hits a checkpoint
  step fans the Save out to every shard.
- A relaunched PS pod restores its slice from the newest complete snapshot
  at startup (``ps/main.py``); rows pushed after that snapshot are lost —
  exactly the reference's PS-pod-crash semantics.
"""

from __future__ import annotations

import contextlib
import json
import os
import struct
import threading
import time
from concurrent import futures
from typing import Any, Dict, List, Optional, Sequence, Tuple

import grpc
import numpy as np

from elasticdl_tpu import chaos
from elasticdl_tpu.common import durable
from elasticdl_tpu.common import gauge as gaugelib
from elasticdl_tpu.common import locksan, trace
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.rpc import (
    BackoffPolicy,
    call_with_backoff,
    wait_channel_ready,
)

logger = get_logger("ps.service")

PS_SERVICE_NAME = "elasticdl.PS"

#: Methods -> (required meta fields -> types).  Arrays are declared
#: separately per method; unknown meta fields pass through (forward compat).
PS_METHODS: Dict[str, Dict[str, tuple]] = {
    "Pull": {"table": (str,)},
    "PushGrad": {"table": (str,)},
    "Save": {"directory": (str,), "step": (int,)},
    "Load": {"directory": (str,), "step": (int,), "strict": (bool,)},
    "Stats": {},
}

_HEADER = struct.Struct("<I")  # u32 header length prefix

#: gRPC message cap for BOTH PSServer and PSClient — one constant so the two
#: sides cannot drift into the asymmetric-cap RESOURCE_EXHAUSTED failure
#: (a production push is ~8.5 MB of frame, over gRPC's 4 MB default).
GRPC_MAX_MESSAGE_BYTES = 256 << 20


class PSFrameError(ValueError):
    """A frame violated the PS wire contract (boundary error, never a
    KeyError deep in a handler — same principle as common/rpc.MessageSchema)."""


def encode_frame(meta: Dict[str, Any], arrays: Dict[str, np.ndarray]) -> bytes:
    """``u32 header_len | header JSON | concatenated raw buffers``.

    The header carries ``meta`` plus each array's name/dtype/shape in payload
    order; buffers are C-contiguous little-endian.
    """
    descs = []
    bufs = []
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype.byteorder == ">":  # big-endian never happens on our
            arr = arr.astype(arr.dtype.newbyteorder("<"))  # targets, but be exact
        descs.append(
            {"name": name, "dtype": arr.dtype.str, "shape": list(arr.shape)}
        )
        bufs.append(arr.tobytes())
    header = json.dumps({"meta": meta, "arrays": descs}).encode()
    return _HEADER.pack(len(header)) + header + b"".join(bufs)


def decode_frame(payload: bytes) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    if len(payload) < _HEADER.size:
        raise PSFrameError(f"frame too short ({len(payload)} bytes)")
    (hlen,) = _HEADER.unpack_from(payload)
    if _HEADER.size + hlen > len(payload):
        raise PSFrameError("frame header runs past the payload")
    try:
        header = json.loads(payload[_HEADER.size : _HEADER.size + hlen].decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise PSFrameError(f"malformed frame header: {e}") from e
    if not isinstance(header, dict) or "meta" not in header or "arrays" not in header:
        raise PSFrameError("frame header must carry 'meta' and 'arrays'")
    arrays: Dict[str, np.ndarray] = {}
    off = _HEADER.size + hlen
    for desc in header["arrays"]:
        try:
            dtype = np.dtype(desc["dtype"])
            shape = tuple(int(d) for d in desc["shape"])
            name = desc["name"]
        except (KeyError, TypeError, ValueError) as e:
            raise PSFrameError(f"malformed array descriptor {desc!r}") from e
        nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if off + nbytes > len(payload):
            raise PSFrameError(
                f"array {name!r} ({nbytes} bytes) runs past the frame"
            )
        arrays[name] = np.frombuffer(
            payload[off : off + nbytes], dtype=dtype
        ).reshape(shape)
        off += nbytes
    return header["meta"], arrays


def validate_meta(method: str, meta: Dict[str, Any]) -> None:
    spec = PS_METHODS.get(method)
    if spec is None:
        raise PSFrameError(f"unknown PS method {method!r}")
    problems = []
    for field, types in spec.items():
        if field not in meta:
            problems.append(f"missing required field {field!r}")
        elif not isinstance(meta[field], types) or (
            isinstance(meta[field], bool) and bool not in types
        ):
            problems.append(
                f"field {field!r} must be "
                f"{'/'.join(t.__name__ for t in types)}, "
                f"got {type(meta[field]).__name__}"
            )
    if problems:
        raise PSFrameError(f"{method}: " + "; ".join(problems))


def shard_of(ids: np.ndarray, num_shards: int) -> np.ndarray:
    """Owning shard per id: ``id mod n``, non-negative for any int64 id."""
    return (ids % num_shards + num_shards) % num_shards


def snapshot_filename(key: str, shard: int, num_shards: int) -> str:
    return f"{key}.shard{shard}of{num_shards}.bin"


class _RWLock:
    """Writer-preferring readers-writer lock.

    PS traffic is read-mostly in steady state (pulls of existing rows);
    a single mutex serialized the whole 16-thread executor (VERDICT r3
    Weak #3).  Readers share; writers (row materialization, optimizer
    pushes, save/load) exclude everyone.  Writer preference keeps a pull
    storm from starving pushes — training stalls otherwise."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    @contextlib.contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextlib.contextmanager
    def write(self):
        self.acquire_write()
        try:
            yield
        finally:
            self.release_write()


class PSServer:
    """One PS shard: gRPC service over per-table native stores.

    ``table_specs`` maps table key -> HostTableIO-like objects carrying
    ``dim`` / ``optimizer`` / ``learning_rate`` / ``init_scale`` (usually a
    ModelSpec's ``host_io``).  Tables materialize rows lazily on first pull,
    so a shard's memory is proportional to the ids it has actually served.
    """

    def __init__(
        self,
        table_specs: Dict[str, Any],
        shard: int = 0,
        num_shards: int = 1,
        port: int = 0,
        max_workers: int = 16,
        gauges: Optional[gaugelib.Registry] = None,
    ):
        from elasticdl_tpu.ps.host_store import HostEmbeddingStore

        if not 0 <= shard < num_shards:
            raise ValueError(f"shard {shard} out of range for {num_shards}")
        self.shard = shard
        self.num_shards = num_shards
        self._stores = {
            key: HostEmbeddingStore(
                dim=io.dim,
                optimizer=io.optimizer,
                learning_rate=io.learning_rate,
                init_scale=io.init_scale,
            )
            for key, io in table_specs.items()
        }
        # Per-table reader-writer locks: tables are independent stores, and
        # within a table read-only pulls (the steady-state hot path) run
        # concurrently via the native try_pull; only row materialization,
        # optimizer pushes, and save/load take the write side.  Save/Load
        # span every table — they acquire all write locks in sorted key
        # order (deadlock-free).
        self._locks = {key: _RWLock() for key in self._stores}
        # Step this shard restored at (re)start, or None: surfaced in Stats
        # so workers can verify the whole fleet restored the SAME step (a
        # shard-divergent restore silently mixes model versions).  Written
        # by a Load handler thread, read by concurrent Stats handlers — a
        # leaf lock makes the hand-off explicit (graftlint lock-discipline).
        self._meta_lock = locksan.lock("PSServer._meta_lock", leaf=True)  # lock-order: leaf
        self.restored_step: Optional[int] = None  # guarded-by: _meta_lock
        # graftgauge (r14): pull/push rates + latency tails, live.  The
        # shard's own registry defaults to the process-default one so the
        # PS pod's /metrics endpoint (ps/main.py) serves everything the
        # process records; in-process fleets (tests, serving_bench) pass
        # their own instance to keep shards' families apart.  Updates are
        # O(1) counter/histogram ops — legal in the # hot-path handlers
        # (gauge-discipline); table row counts are a scrape-time collector.
        self.gauges = gauges if gauges is not None else gaugelib.default()
        shard_label = {"shard": str(shard)}
        self._g_pulls = self.gauges.counter(
            "edl_ps_pull_total", "Pull RPCs served by this shard",
            labels=shard_label,
        )
        self._g_pull_ms = self.gauges.histogram(
            "edl_ps_pull_ms", "server-side Pull wall per RPC",
            labels=shard_label,
        )
        self._g_pushes = self.gauges.counter(
            "edl_ps_push_total", "PushGrad RPCs served by this shard",
            labels=shard_label,
        )
        self._g_push_ms = self.gauges.histogram(
            "edl_ps_push_ms", "server-side PushGrad wall per RPC",
            labels=shard_label,
        )
        self.gauges.add_collector(self._collect_gauges)
        # Message-size limits must cover production batches: a full 8192x26
        # dim-8 push is ~8.5 MB of frame, over gRPC's 4 MB default — the
        # server AND the client (PSClient) both raise the cap, or a
        # realistic batch dies with RESOURCE_EXHAUSTED (found by
        # tools/ps_bench.py at exactly the flagship batch shape).
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers),
            options=[
                ("grpc.max_send_message_length", GRPC_MAX_MESSAGE_BYTES),
                ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE_BYTES),
            ],
        )
        self._server.add_generic_rpc_handlers((self._make_handler(),))
        self.port = self._server.add_insecure_port(f"[::]:{port}")
        # grpc reports a lost bind as port 0.  Fail LOUDLY when a specific
        # port was requested: the master advertised that port to workers, so
        # a silently re-bound (or unbound) shard would serve nothing while
        # looking healthy — crashing instead lets the pod relaunch policy
        # retry the bind (the race window is a just-released probe port,
        # master/main._pick_free_ports).
        if self.port == 0 or (port and self.port != port):
            raise RuntimeError(
                f"PS shard {shard} failed to bind port {port} "
                f"(got {self.port})"
            )

    # -- handlers --

    def _store_for(self, meta: Dict[str, Any]):
        store = self._stores.get(meta["table"])
        if store is None:
            raise PSFrameError(
                f"unknown table {meta['table']!r}; this shard serves "
                f"{sorted(self._stores)}"
            )
        return store

    def _require(self, arrays: Dict[str, np.ndarray], name: str, dtype) -> np.ndarray:
        if name not in arrays:
            raise PSFrameError(f"missing array {name!r}")
        arr = arrays[name]
        if arr.dtype != np.dtype(dtype):
            raise PSFrameError(
                f"array {name!r} must be {np.dtype(dtype).str}, got {arr.dtype.str}"
            )
        return arr

    # hot-path: the steady-state embedding read, once per step per worker
    def _pull(self, meta, arrays):
        store = self._store_for(meta)
        ids = self._require(arrays, "ids", np.int64)
        # graftchaos: delay_ps faults land here — the server side of the
        # pull, so the injected latency is indistinguishable from a slow
        # shard to every consumer (worker host-tier pulls, serving cache
        # misses).  No-op when disabled (chaos-discipline).
        chaos.hook("ps:pull", table=meta["table"])
        lock = self._locks[meta["table"]]
        # Span via the non-blocking ring API only (trace-discipline): the
        # PS read is the serving/training tiers' shared tail-latency
        # suspect, so its server-side wall is first-class trace data.
        t0 = time.perf_counter()
        with trace.span(
            "ps:pull", cat="ps.server", table=meta["table"], n_ids=int(ids.size)
        ):
            with lock.read():
                # Fast path: all rows exist — concurrent with other pulls.
                rows, missing = store.try_pull(ids)
            if missing:
                # New ids materialize rows (mutation): exclusive per-table.
                with lock.write():
                    rows = store.pull(ids)
        # graftgauge: O(1) counter/histogram updates (gauge-discipline) —
        # the live twin of the ps:pull span's wall.
        self._g_pulls.inc()
        self._g_pull_ms.observe((time.perf_counter() - t0) * 1e3)
        return {}, {"rows": rows}

    # hot-path: the per-step gradient apply
    def _push_grad(self, meta, arrays):
        store = self._store_for(meta)
        ids = self._require(arrays, "ids", np.int64)
        grads = self._require(arrays, "grads", np.float32)
        if grads.shape != ids.shape + (store.dim,):
            raise PSFrameError(
                f"grads shape {grads.shape} != ids {ids.shape} + (dim "
                f"{store.dim},)"
            )
        t0 = time.perf_counter()
        with trace.span(
            "ps:push_grad", cat="ps.server", table=meta["table"],
            n_ids=int(ids.size),
        ):
            with self._locks[meta["table"]].write():
                store.push_grad(ids, grads)
        self._g_pushes.inc()
        self._g_push_ms.observe((time.perf_counter() - t0) * 1e3)
        return {"applied": int(ids.size)}, {}

    @contextlib.contextmanager
    def _all_write_locks(self):
        """Every table's write lock, sorted order (save/load span tables)."""
        ordered = [self._locks[k] for k in sorted(self._locks)]
        for lock in ordered:
            lock.acquire_write()
        try:
            yield
        finally:
            for lock in reversed(ordered):
                lock.release_write()

    def _save(self, meta, arrays):
        d = os.path.join(meta["directory"], "host_stores", str(meta["step"]))
        os.makedirs(d, exist_ok=True)
        rows = {}
        with self._all_write_locks():
            for key, store in self._stores.items():
                final = os.path.join(
                    d, snapshot_filename(key, self.shard, self.num_shards)
                )
                tmp = durable.tmp_path(final)
                rows[key] = store.save(tmp)
                # Full commit (fsync + rename + dir fsync): a shard
                # rebuild that reads a snapshot the power loss ate would
                # silently lose embedding rows.
                durable.atomic_replace(tmp, final)
        keep = int(meta.get("keep_max", 3))
        self._prune(os.path.join(meta["directory"], "host_stores"), keep)
        return {"rows": {k: int(v) for k, v in rows.items()}}, {}

    def _prune(self, root: str, keep_max: int) -> None:
        """Drop this shard's files from old step dirs; remove emptied dirs.
        Each shard prunes only its own files so concurrent shards never race
        on each other's snapshots."""
        try:
            steps = sorted((int(s) for s in os.listdir(root) if s.isdigit()),
                           reverse=True)
        except FileNotFoundError:
            return
        for old in steps[max(keep_max, 1):]:
            d = os.path.join(root, str(old))
            for key in self._stores:
                try:
                    os.remove(os.path.join(
                        d, snapshot_filename(key, self.shard, self.num_shards)
                    ))
                except FileNotFoundError:
                    pass
            try:
                os.rmdir(d)  # only succeeds once every shard has pruned
            except OSError:
                pass

    def _load(self, meta, arrays):
        d = os.path.join(meta["directory"], "host_stores", str(meta["step"]))
        paths = {
            key: os.path.join(
                d, snapshot_filename(key, self.shard, self.num_shards)
            )
            for key in self._stores
        }
        missing = [p for p in paths.values() if not os.path.exists(p)]
        if missing:
            if meta["strict"]:
                raise PSFrameError(
                    f"snapshot missing for step {meta['step']}: {missing[0]}"
                )
            return {"loaded": False}, {}
        with self._all_write_locks():
            for key, path in paths.items():
                self._stores[key].load(path)
        with self._meta_lock:
            self.restored_step = int(meta["step"])
        return {"loaded": True}, {}

    def _collect_gauges(self) -> None:
        """Scrape-time collector (never the hot handlers — the
        gauge-discipline split): per-table row counts and the restored-step
        marker, refreshed per scrape."""
        for key, s in self._stores.items():
            self.gauges.gauge(
                "edl_ps_rows", "materialized rows per table on this shard",
                labels={"shard": str(self.shard), "table": key},
            ).set(float(len(s)))
        with self._meta_lock:
            restored = self.restored_step
        if restored is not None:
            self.gauges.gauge(
                "edl_ps_restored_step",
                "step this shard restored at (re)start",
                labels={"shard": str(self.shard)},
            ).set(float(restored))

    def _stats(self, meta, arrays):
        with self._meta_lock:
            restored = self.restored_step
        return {
            "shard": self.shard,
            "num_shards": self.num_shards,
            "tables": {k: len(s) for k, s in self._stores.items()},
            # None = fresh stores (nothing restored since (re)start).
            "restored_step": restored,
        }, {}

    # -- plumbing --

    def _make_handler(self) -> grpc.GenericRpcHandler:
        methods = {
            "Pull": self._pull,
            "PushGrad": self._push_grad,
            "Save": self._save,
            "Load": self._load,
            "Stats": self._stats,
        }

        def wrap(name, fn):
            def handler(req: bytes, ctx):
                try:
                    meta, arrays = decode_frame(req)
                    validate_meta(name, meta)
                    out_meta, out_arrays = fn(meta, arrays)
                except PSFrameError as e:
                    ctx.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
                except (IOError, ValueError) as e:
                    ctx.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
                return encode_frame(out_meta, out_arrays)

            return handler

        handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                wrap(name, fn),
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )
            for name, fn in methods.items()
        }
        return grpc.method_handlers_generic_handler(PS_SERVICE_NAME, handlers)

    @property
    def address(self) -> str:
        return f"localhost:{self.port}"

    def start(self) -> "PSServer":
        self._server.start()
        logger.info(
            "PS shard %d/%d serving %s on port %d",
            self.shard, self.num_shards, sorted(self._stores), self.port,
        )
        return self

    def wait(self) -> None:
        self._server.wait_for_termination()

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)
        # Unhook from the (possibly process-shared) registry — a stopped
        # shard's collector must not keep re-publishing its frozen row
        # counts or pin the shard's stores in memory.
        self.gauges.remove_collector(self._collect_gauges)

    def restore_latest(self, checkpoint_dir: str) -> Optional[int]:
        """Startup restore for a (re)launched PS pod: load this shard's slice
        from the NEWEST step dir that has all of this shard's files; return
        the step, or None when no complete snapshot exists (fresh stores).
        Steps with missing/corrupt files for this shard are skipped — an
        older complete snapshot beats a torn newer one."""
        root = os.path.join(checkpoint_dir, "host_stores")
        try:
            steps = sorted((int(s) for s in os.listdir(root) if s.isdigit()),
                           reverse=True)
        except FileNotFoundError:
            return None
        for step in steps:
            try:
                meta, _ = self._load(
                    {"directory": checkpoint_dir, "step": step, "strict": True},
                    {},
                )
                logger.info("restored PS shard %d from step %d", self.shard, step)
                return step
            except (PSFrameError, IOError, ValueError) as e:
                logger.warning("snapshot step %d unusable: %s", step, e)
        return None


class PSClient:
    """Channel + typed calls to ONE PS shard."""

    def __init__(self, address: str):
        self.address = address
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_send_message_length", GRPC_MAX_MESSAGE_BYTES),
                ("grpc.max_receive_message_length", GRPC_MAX_MESSAGE_BYTES),
            ],
        )
        self._stubs: Dict[str, Any] = {}

    def wait_ready(self, timeout_s: float = 20.0) -> None:
        wait_channel_ready(self._channel, service="ps", budget_s=timeout_s)

    def call(
        self,
        method: str,
        meta: Dict[str, Any],
        arrays: Optional[Dict[str, np.ndarray]] = None,
        timeout_s: float = 60.0,
    ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
        validate_meta(method, meta)
        if method not in self._stubs:
            self._stubs[method] = self._channel.unary_unary(
                f"/{PS_SERVICE_NAME}/{method}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
        payload = self._stubs[method](
            encode_frame(meta, arrays or {}), timeout=timeout_s
        )
        return decode_frame(payload)

    def call_async(self, method, meta, arrays=None, timeout_s: float = 60.0):
        """Future-returning variant (parallel fan-out across shards)."""
        validate_meta(method, meta)
        if method not in self._stubs:
            self._stubs[method] = self._channel.unary_unary(
                f"/{PS_SERVICE_NAME}/{method}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
        return self._stubs[method].future(
            encode_frame(meta, arrays or {}), timeout=timeout_s
        )

    def close(self) -> None:
        self._channel.close()


class RemoteEmbeddingStore:
    """HostEmbeddingStore-compatible view of one table across PS shards.

    ``pull``/``push_grad`` take/return the same numpy shapes as the local
    store; ids route to shard ``id mod n`` and per-shard RPCs run in
    parallel (gRPC futures).  The trainer swaps this in for the local store
    when the job runs with PS pods (config.ps_addresses), which is what
    legalizes host-tier tables on multi-process meshes.
    """

    #: Pull/PushGrad retry schedule across a PS shard relaunch: the master
    #: relaunches a crashed shard in seconds (and the relaunched pod restores
    #: its slice from the newest snapshot), so briefly retrying bridges the
    #: gap instead of failing the worker's task — the reference worker's PS
    #: RPC retry plays the same role.
    RETRY_BACKOFFS_S = (1.0, 2.0, 4.0, 8.0)

    #: Status codes worth retrying: the shard is relaunching (UNAVAILABLE)
    #: or the call timed out in flight.  Anything else (INVALID_ARGUMENT,
    #: FAILED_PRECONDITION) is a real error and surfaces immediately.
    TRANSIENT_CODES = (
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
    )

    def __init__(self, table: str, dim: int, addresses: Sequence[str]):
        if not addresses:
            raise ValueError("RemoteEmbeddingStore needs >= 1 PS address")
        self.table = table
        self.dim = dim
        self._clients = [PSClient(a) for a in addresses]
        self.num_shards = len(self._clients)
        # Client-side retry visibility (r14): the counter records into the
        # PROCESS-default registry — the store is constructed deep inside
        # the trainer, and the worker/serving process wires its registry as
        # the default at startup, so the one scrape endpoint shows retries
        # beside everything else the process measures.
        self._g_retries = gaugelib.default().counter(
            "edl_ps_retry_total",
            "client-side transient-outage retries against the PS fleet",
            labels={"table": table},
        )

    def _retry(self, fn):
        """Run ``fn()``, retrying transient shard outages (UNAVAILABLE — the
        pod is relaunching — or a timed-out call).  Non-transient codes
        (INVALID_ARGUMENT etc.) surface immediately.  The schedule rides
        the shared backoff helper (r18, common/rpc.call_with_backoff):
        same 1-2-4-8 s cadence as the pre-r18 RETRY_BACKOFFS_S table
        (jitter-free, so shard-relaunch timing tests stay deterministic),
        with the per-table ``edl_ps_retry_total`` counter and ``ps:retry``
        instant kept beside the helper's shared ``edl_rpc_retry_total``."""

        def _transient(e: BaseException) -> bool:
            return isinstance(e, grpc.RpcError) and (
                e.code() in self.TRANSIENT_CODES
            )

        def _on_retry(e: BaseException, attempt: int, delay: float) -> None:
            # The retry count is trace data: a pull span whose wall
            # includes shard-relaunch backoffs is only explicable with
            # the retries visible beside it.
            trace.instant(
                "ps:retry", cat="ps.client", table=self.table,
                attempt=attempt, code=str(e.code()),
            )
            self._g_retries.inc()
            logger.warning(
                "PS call failed (%s), retry %d/%d in %.0fs",
                e.code(), attempt, len(self.RETRY_BACKOFFS_S), delay,
            )

        return call_with_backoff(
            fn,
            service="ps",
            is_transient=_transient,
            policy=BackoffPolicy(
                base_s=self.RETRY_BACKOFFS_S[0],
                multiplier=2.0,
                max_s=self.RETRY_BACKOFFS_S[-1],
                jitter=0.0,
                max_attempts=len(self.RETRY_BACKOFFS_S) + 1,
            ),
            on_retry=_on_retry,
        )

    def wait_ready(self, timeout_s: float = 20.0) -> None:
        for c in self._clients:
            c.wait_ready(timeout_s)

    def __len__(self) -> int:
        total = 0
        for c in self._clients:
            # Through the transient-outage retry like every other shard
            # call: a len() probe landing inside a shard's relaunch window
            # must wait the seconds out, not fail the caller (graftlint
            # rpc-discipline surfaced this as the one bare stub call).
            meta, _ = self._retry(lambda c=c: c.call("Stats", {}))
            total += int(meta["tables"].get(self.table, 0))
        return total

    def restored_steps(self) -> List[Optional[int]]:
        """Each shard's restored-at-(re)start step (None = fresh stores).
        Lets the worker verify the fleet is CONSISTENT before trusting it —
        shards restore independently (newest complete snapshot each), so a
        crash can leave them on different steps (trainer.restore_host_stores
        fails evaluation/prediction loud on divergence)."""
        out: List[Optional[int]] = []
        for c in self._clients:
            meta, _ = self._retry(lambda c=c: c.call("Stats", {}))
            step = meta.get("restored_step")
            out.append(None if step is None else int(step))
        return out

    def _partition(self, flat_ids: np.ndarray):
        owner = shard_of(flat_ids, self.num_shards)
        parts = [np.nonzero(owner == s)[0] for s in range(self.num_shards)]
        return parts

    def _call_shard(self, s: int, method: str, arrays: Dict[str, np.ndarray]):
        """Synchronous shard call with the transient-outage retry."""
        return self._retry(
            lambda: self._clients[s].call(method, {"table": self.table}, arrays)
        )

    def _fan_out(self, method: str, shard_arrays: List[Tuple[int, Dict[str, np.ndarray]]]):
        """Issue one call per shard in parallel; a shard whose FUTURE fails
        transiently is retried synchronously (the other shards' results are
        kept — for PushGrad a failed future means the shard never applied,
        so the retry cannot double-apply; a response lost AFTER the apply
        can double-apply, which async-PS semantics tolerate, as the
        reference's at-least-once push does).  Returns [(shard, meta,
        arrays)] in input order."""
        futs = [
            (s, arrs, self._clients[s].call_async(method, {"table": self.table}, arrs))
            for s, arrs in shard_arrays
        ]
        results = []
        for s, arrs, fut in futs:
            try:
                meta, arrays = decode_frame(fut.result())
            except grpc.RpcError as e:
                if e.code() not in self.TRANSIENT_CODES:
                    raise
                meta, arrays = self._call_shard(s, method, arrs)
            results.append((s, meta, arrays))
        return results

    def pull(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64)
        flat = ids.ravel()
        out = np.empty((flat.size, self.dim), np.float32)
        with trace.span(
            "ps:pull", cat="ps.client", table=self.table,
            n_ids=int(flat.size), shards=self.num_shards,
        ):
            if self.num_shards == 1:
                _, arrays = self._call_shard(0, "Pull", {"ids": flat})
                out[:] = arrays["rows"]
                return out.reshape(ids.shape + (self.dim,))
            parts = self._partition(flat)
            work = [
                (s, {"ids": flat[idx]})
                for s, idx in enumerate(parts)
                if idx.size
            ]
            for s, _, arrays in self._fan_out("Pull", work):
                out[parts[s]] = arrays["rows"]
        return out.reshape(ids.shape + (self.dim,))

    def push_grad(self, ids: np.ndarray, grads: np.ndarray) -> None:
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            ids.size, self.dim
        )
        with trace.span(
            "ps:push_grad", cat="ps.client", table=self.table,
            n_ids=int(ids.size), shards=self.num_shards,
        ):
            if self.num_shards == 1:
                self._call_shard(0, "PushGrad", {"ids": ids, "grads": grads})
                return
            parts = self._partition(ids)
            work = [
                (s, {"ids": ids[idx], "grads": grads[idx]})
                for s, idx in enumerate(parts)
                if idx.size
            ]
            self._fan_out("PushGrad", work)

    # -- checkpoint fan-out (each shard dumps/loads its own slice) --

    def save_snapshot(self, directory: str, step: int, keep_max: int = 3) -> None:
        # Same transient-outage retry as Pull/PushGrad: a checkpoint boundary
        # landing inside a shard's relaunch window must wait the seconds out,
        # not fail the worker's task.  Save is idempotent (atomic per-file
        # replace), so a retry after a lost response just rewrites the file.
        meta = {"directory": directory, "step": int(step), "keep_max": keep_max}
        # Explicit deadline (the parallel fan-out has no retry wrapper
        # around the futures themselves): a Save is a full-slice disk dump,
        # so it gets headroom over the default RPC timeout — a shard that
        # cannot finish inside it falls to the per-shard retry below.
        futs = [
            c.call_async("Save", meta, timeout_s=120.0) for c in self._clients
        ]
        for s, fut in enumerate(futs):
            try:
                fut.result()
            except grpc.RpcError as e:
                if e.code() not in self.TRANSIENT_CODES:
                    raise
                self._retry(lambda: self._clients[s].call("Save", meta))

    def load_snapshot(self, directory: str, step: int, strict: bool = True) -> bool:
        loaded = []
        for c in self._clients:
            try:
                meta, _ = self._retry(
                    lambda: c.call(
                        "Load",
                        {"directory": directory, "step": int(step),
                         "strict": strict},
                    )
                )
                loaded.append(bool(meta.get("loaded", True)))
            except grpc.RpcError as e:
                if strict:
                    raise FileNotFoundError(
                        f"PS shard at {c.address} failed to load step {step}: "
                        f"{e.details() if hasattr(e, 'details') else e}"
                    ) from e
                loaded.append(False)
        return all(loaded) and bool(loaded)

    def close(self) -> None:
        for c in self._clients:
            c.close()


def parse_ps_addresses(spec: str) -> List[str]:
    return [a.strip() for a in spec.split(",") if a.strip()]
