// Native runtime pieces (C ABI, loaded via ctypes).
//
// Reference parity (SURVEY.md §2 #10-#11 [U/D]): the reference's native
// components are a Go parameter server — an embedding-table KV store with
// server-side sparse optimizers (SGD/Adagrad/Adam) and checkpoint dump/load —
// plus vectorized apply-gradient kernels.  TPU-first re-design: the *sharded*
// embedding path lives in HBM on the mesh (ops/embedding.py); THIS store is
// the host tier for tables that exceed HBM — the worker pulls the batch's
// unique rows to the device, computes dense grads for them, and pushes the
// sparse update back here, where the optimizer applies it in place.  Also
// includes the recordio range-scanner used on the ingest hot path.
//
// Build: see Makefile (g++ -O3 -shared).  No external deps beyond libc++.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- utilities

// splitmix64: deterministic per-id seed for default row init.
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// crc32 (IEEE, reflected), slice-by-8 — the record reader CRC-checks every
// payload on the ingest hot path, so the bytewise table walk (~300 MB/s on
// this host) was the read bottleneck; slice-by-8 processes 8 bytes per
// iteration (~2 GB/s).  Tables generated on first use.
static uint32_t crc_table[8][256];
static bool crc_ready = false;
static void crc_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xedb88320u ^ (c >> 1) : c >> 1;
    crc_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = crc_table[0][i];
    for (int t = 1; t < 8; t++) {
      c = crc_table[0][c & 0xff] ^ (c >> 8);
      crc_table[t][i] = c;
    }
  }
  crc_ready = true;
}
static uint32_t crc32_buf(const uint8_t* p, size_t n) {
  if (!crc_ready) crc_init();
  uint32_t c = 0xffffffffu;
  while (n >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, p, 4);
    std::memcpy(&hi, p + 4, 4);
    lo ^= c;
    c = crc_table[7][lo & 0xff] ^ crc_table[6][(lo >> 8) & 0xff] ^
        crc_table[5][(lo >> 16) & 0xff] ^ crc_table[4][lo >> 24] ^
        crc_table[3][hi & 0xff] ^ crc_table[2][(hi >> 8) & 0xff] ^
        crc_table[1][(hi >> 16) & 0xff] ^ crc_table[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) c = crc_table[0][(c ^ *p++) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

// ------------------------------------------------------- embedding KV store

enum Optimizer { OPT_SGD = 0, OPT_MOMENTUM = 1, OPT_ADAGRAD = 2, OPT_ADAM = 3 };

struct EdlStore {
  int64_t dim;
  int opt;
  float lr, momentum, beta1, beta2, eps;
  float init_scale;
  // stride = weights + optimizer slots, all contiguous per row.
  int64_t stride;
  std::unordered_map<int64_t, int64_t> index;  // id -> row offset (in floats)
  std::vector<float> arena;
  std::vector<int64_t> ids_in_order;  // for checkpoint iteration stability
  std::vector<int32_t> adam_t;        // per-row step count (Adam only)

  int64_t slots() const {
    switch (opt) {
      case OPT_SGD: return 0;
      case OPT_MOMENTUM: return 1;
      case OPT_ADAGRAD: return 1;
      case OPT_ADAM: return 2;
    }
    return 0;
  }

  float* row(int64_t id, bool create) {
    auto it = index.find(id);
    if (it != index.end()) return arena.data() + it->second;
    if (!create) return nullptr;
    int64_t off = (int64_t)arena.size();
    arena.resize(arena.size() + stride, 0.0f);
    float* r = arena.data() + off;
    uint64_t s = splitmix64((uint64_t)id);
    for (int64_t d = 0; d < dim; d++) {
      s = splitmix64(s);
      // uniform in [-init_scale, init_scale)
      r[d] = init_scale * (2.0f * (float)((s >> 11) * (1.0 / 9007199254740992.0)) - 1.0f);
    }
    index.emplace(id, off);
    ids_in_order.push_back(id);
    if (opt == OPT_ADAM) adam_t.push_back(0);
    return r;
  }
};

EdlStore* edl_store_create(int64_t dim, int optimizer, float lr, float momentum,
                           float beta1, float beta2, float eps,
                           float init_scale) {
  EdlStore* s = new EdlStore();
  s->dim = dim;
  s->opt = optimizer;
  s->lr = lr;
  s->momentum = momentum;
  s->beta1 = beta1;
  s->beta2 = beta2;
  s->eps = eps;
  s->init_scale = init_scale;
  s->stride = dim * (1 + s->slots());
  return s;
}

void edl_store_destroy(EdlStore* s) { delete s; }

int64_t edl_store_size(EdlStore* s) { return (int64_t)s->index.size(); }

// Gather rows for n ids into out[n*dim]; rows for unseen ids are initialized.
void edl_store_pull(EdlStore* s, const int64_t* ids, int64_t n, float* out) {
  for (int64_t i = 0; i < n; i++) {
    const float* r = s->row(ids[i], /*create=*/true);
    std::memcpy(out + i * s->dim, r, sizeof(float) * s->dim);
  }
}

// Read-only gather: fills out[n*dim] for ids that EXIST; returns the number
// of missing ids (their rows are left untouched).  Never mutates the store,
// so any number of threads may call it concurrently as long as no writer
// (push/pull-create/load) runs — the PS service's reader-writer fast path
// (ps/service.py): steady-state training pulls hit only existing rows and
// scale across the gRPC thread pool instead of serializing on one mutex.
int64_t edl_store_try_pull(EdlStore* s, const int64_t* ids, int64_t n,
                           float* out) {
  int64_t missing = 0;
  for (int64_t i = 0; i < n; i++) {
    auto it = s->index.find(ids[i]);
    if (it == s->index.end()) {
      missing++;
      continue;
    }
    std::memcpy(out + i * s->dim, s->arena.data() + it->second,
                sizeof(float) * s->dim);
  }
  return missing;
}

// Sparse apply: ids may contain duplicates — contributions are accumulated
// before one optimizer step per distinct row (IndexedSlices semantics).
void edl_store_push_grad(EdlStore* s, const int64_t* ids, int64_t n,
                         const float* grads) {
  const int64_t dim = s->dim;
  std::unordered_map<int64_t, std::vector<float>> acc;
  acc.reserve(n * 2);
  for (int64_t i = 0; i < n; i++) {
    auto& g = acc[ids[i]];
    if (g.empty()) g.assign(dim, 0.0f);
    const float* gi = grads + i * dim;
    for (int64_t d = 0; d < dim; d++) g[d] += gi[d];
  }
  for (auto& kv : acc) {
    float* w = s->row(kv.first, /*create=*/true);
    float* g = kv.second.data();
    switch (s->opt) {
      case OPT_SGD: {
        for (int64_t d = 0; d < dim; d++) w[d] -= s->lr * g[d];
        break;
      }
      case OPT_MOMENTUM: {
        float* m = w + dim;
        for (int64_t d = 0; d < dim; d++) {
          m[d] = s->momentum * m[d] + g[d];
          w[d] -= s->lr * m[d];
        }
        break;
      }
      case OPT_ADAGRAD: {
        float* a = w + dim;
        for (int64_t d = 0; d < dim; d++) {
          a[d] += g[d] * g[d];
          w[d] -= s->lr * g[d] / (std::sqrt(a[d]) + s->eps);
        }
        break;
      }
      case OPT_ADAM: {
        float* m = w + dim;
        float* v = w + 2 * dim;
        int64_t row_i = (int64_t)(s->index[kv.first] / s->stride);
        int32_t t = ++s->adam_t[row_i];
        const float bc1 = 1.0f - std::pow(s->beta1, (float)t);
        const float bc2 = 1.0f - std::pow(s->beta2, (float)t);
        for (int64_t d = 0; d < dim; d++) {
          m[d] = s->beta1 * m[d] + (1.0f - s->beta1) * g[d];
          v[d] = s->beta2 * v[d] + (1.0f - s->beta2) * g[d] * g[d];
          const float mh = m[d] / bc1, vh = v[d] / bc2;
          w[d] -= s->lr * mh / (std::sqrt(vh) + s->eps);
        }
        break;
      }
    }
  }
}

// Checkpoint: [int64 n][int64 dim][int64 stride][int32 opt]
//             then per row: [int64 id][int32 adam_t][stride floats]
// Every write is checked: a short write (full disk, I/O error) must fail the
// save, not surface later as an unreadable checkpoint.
int64_t edl_store_save(EdlStore* s, const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  bool ok = true;
  int64_t n = (int64_t)s->index.size();
  ok &= std::fwrite(&n, 8, 1, f) == 1;
  ok &= std::fwrite(&s->dim, 8, 1, f) == 1;
  ok &= std::fwrite(&s->stride, 8, 1, f) == 1;
  int32_t opt = s->opt;
  ok &= std::fwrite(&opt, 4, 1, f) == 1;
  for (int64_t i = 0; ok && i < n; i++) {
    int64_t id = s->ids_in_order[i];
    int64_t off = s->index[id];
    int32_t t = (s->opt == OPT_ADAM) ? s->adam_t[off / s->stride] : 0;
    ok &= std::fwrite(&id, 8, 1, f) == 1;
    ok &= std::fwrite(&t, 4, 1, f) == 1;
    ok &= std::fwrite(s->arena.data() + off, sizeof(float), s->stride, f) ==
          (size_t)s->stride;
  }
  ok &= std::fclose(f) == 0;
  return ok ? n : -1;
}

int64_t edl_store_load(EdlStore* s, const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t n, dim, stride;
  int32_t opt;
  if (std::fread(&n, 8, 1, f) != 1 || std::fread(&dim, 8, 1, f) != 1 ||
      std::fread(&stride, 8, 1, f) != 1 || std::fread(&opt, 4, 1, f) != 1) {
    std::fclose(f);
    return -1;
  }
  if (dim != s->dim || stride != s->stride || opt != s->opt) {
    std::fclose(f);
    return -2;  // store configuration mismatch
  }
  s->index.clear();
  s->arena.clear();
  s->ids_in_order.clear();
  s->adam_t.clear();
  s->arena.reserve((size_t)n * stride);
  for (int64_t i = 0; i < n; i++) {
    int64_t id;
    int32_t t;
    if (std::fread(&id, 8, 1, f) != 1 || std::fread(&t, 4, 1, f) != 1) {
      std::fclose(f);
      return -1;
    }
    int64_t off = (int64_t)s->arena.size();
    s->arena.resize(s->arena.size() + stride);
    if (std::fread(s->arena.data() + off, sizeof(float), stride, f) !=
        (size_t)stride) {
      std::fclose(f);
      return -1;
    }
    s->index.emplace(id, off);
    s->ids_in_order.push_back(id);
    if (s->opt == OPT_ADAM) s->adam_t.push_back(t);
  }
  std::fclose(f);
  return n;
}

// --------------------------------------------------------- recordio scanner

// Scan an EDLRIO file, filling offsets[] (record byte offsets) up to
// max_records.  Returns the number of records found, -1 on malformed input,
// or -2 if the file holds more than max_records records (truncation is an
// error, never silent).  Mirrors data/recordio.py (the format's source of
// truth).
int64_t edl_recordio_index(const char* path, int64_t* offsets,
                           int64_t max_records) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  char magic[8];
  if (std::fread(magic, 1, 8, f) != 8 ||
      std::memcmp(magic, "EDLRIO\x00\x01", 8) != 0) {
    std::fclose(f);
    return -1;
  }
  std::fseek(f, 0, SEEK_END);
  const int64_t size = std::ftell(f);
  int64_t pos = 8, n = 0;
  while (pos < size && n < max_records) {
    uint32_t hdr[2];
    std::fseek(f, pos, SEEK_SET);
    if (std::fread(hdr, 4, 2, f) != 2) { std::fclose(f); return -1; }
    offsets[n++] = pos;
    pos += 8 + (int64_t)hdr[0];
  }
  std::fclose(f);
  if (pos > size) return -1;
  if (pos < size) return -2;  // records remain beyond max_records
  return n;
}

// Bulk-read records [start, end) given their byte offsets: ONE disk read of
// the contiguous span, then in-memory header walk + CRC check, concatenating
// payloads into out[] and writing each payload's length to lens[].
// ``span_bytes`` is offsets[end]-offsets[start] (or file_size-offsets[start]
// for the final record) — the caller knows both.  Returns total payload
// bytes; -1 on I/O error / malformed framing, -2 on CRC mismatch, -3 if
// out_cap is too small.  This is the ingest hot path: the Python reader's
// per-record fread loop costs ~2 us/record in interpreter overhead alone,
// which at recommendation-model batch sizes (8k records) rivals the whole
// device step (SURVEY.md §2 #14 — the reference feeds workers through
// tf.data's C++ pipeline; this is that role).
int64_t edl_recordio_read(const char* path, const int64_t* offsets,
                          int64_t start, int64_t end, int64_t span_bytes,
                          uint8_t* out, int64_t out_cap, int64_t* lens) {
  if (end <= start) return 0;
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::vector<uint8_t> span((size_t)span_bytes);
  std::fseek(f, (long)offsets[start], SEEK_SET);
  const bool read_ok =
      std::fread(span.data(), 1, (size_t)span_bytes, f) == (size_t)span_bytes;
  std::fclose(f);
  if (!read_ok) return -1;
  int64_t pos = 0, written = 0;
  for (int64_t i = start; i < end; i++) {
    if (pos + 8 > span_bytes) return -1;
    uint32_t len, crc;
    std::memcpy(&len, span.data() + pos, 4);
    std::memcpy(&crc, span.data() + pos + 4, 4);
    pos += 8;
    if (pos + (int64_t)len > span_bytes) return -1;
    if (crc32_buf(span.data() + pos, len) != crc) return -2;
    if (written + (int64_t)len > out_cap) return -3;
    std::memcpy(out + written, span.data() + pos, len);
    lens[i - start] = (int64_t)len;
    written += len;
    pos += len;
  }
  return written;
}

// --------------------------------------------------------- criteo decoder
//
// Decode n Kaggle-TSV criteo records (label \t 13 ints \t 26 hex ids, blanks
// allowed) from one contiguous buffer delimited by cumulative offsets[n+1]
// into labels[n] / dense[n*13] / cat[n*26].  Missing trailing fields and
// blank fields decode to 0, matching the Python feed (data/codecs.py — the
// format's source of truth).  Returns 0, or -(i+1) on a malformed record i.
// Replaces a ~85 us/record Python str.split loop (measured: 692 ms per 8192
// records — 80x the device step) with ~0.3 us/record.

static int8_t hex_lut[256];
static bool hex_ready = false;
static void hex_init() {
  for (int i = 0; i < 256; i++) hex_lut[i] = -1;
  for (int i = 0; i < 10; i++) hex_lut['0' + i] = (int8_t)i;
  for (int i = 0; i < 6; i++) {
    hex_lut['a' + i] = (int8_t)(10 + i);
    hex_lut['A' + i] = (int8_t)(10 + i);
  }
  hex_ready = true;
}

static inline const uint8_t* criteo_float(const uint8_t* p, const uint8_t* end,
                                          float* out, bool* ok) {
  // Minimal decimal float: sign, digits, optional .digits, optional e[+-]exp.
  // Criteo dense features are small integers; the general path exists so
  // hand-written data with decimals parses like Python's float().
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) neg = *p++ == '-';
  double v = 0.0;
  bool any = false;
  while (p < end && *p >= '0' && *p <= '9') { v = v * 10.0 + (*p++ - '0'); any = true; }
  if (p < end && *p == '.') {
    p++;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') { v += (*p++ - '0') * scale; scale *= 0.1; any = true; }
  }
  if (any && p < end && (*p == 'e' || *p == 'E')) {
    p++;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) eneg = *p++ == '-';
    int64_t e = 0;
    while (p < end && *p >= '0' && *p <= '9') e = e * 10 + (*p++ - '0');
    v *= std::pow(10.0, eneg ? (double)-e : (double)e);
  }
  *ok = any && p == end;
  *out = (float)(neg ? -v : v);
  return p;
}

// float32 -> float16 bits, round-to-nearest-even (matches numpy's cast).
static inline uint16_t f32_to_f16(float f) {
  uint32_t x;
  std::memcpy(&x, &f, 4);
  const uint32_t sign = (x >> 16) & 0x8000u;
  const int32_t exp = (int32_t)((x >> 23) & 0xffu) - 127 + 15;
  const uint32_t mant = x & 0x7fffffu;
  if (exp <= 0) {
    if (exp < -10) return (uint16_t)sign;  // underflow to signed zero
    // subnormal half
    uint32_t m = (mant | 0x800000u) >> (1 - exp);
    uint32_t half = sign | (m >> 13);
    uint32_t rem = m & 0x1fffu;
    if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) half++;
    return (uint16_t)half;
  }
  if (exp >= 31) {
    // NaN must stay NaN (qNaN), not collapse to inf (ADVICE r4 #2): the
    // current PRE transform (log1p(max(x,0))) can't produce one, but the
    // cast must match numpy if that ever changes.
    if (((x >> 23) & 0xffu) == 0xffu && mant != 0)
      return (uint16_t)(sign | 0x7e00u);
    return (uint16_t)(sign | 0x7c00u);  // overflow -> inf
  }
  uint32_t half = sign | ((uint32_t)exp << 10) | (mant >> 13);
  const uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) half++;
  return (uint16_t)half;
}

// Test-surface export: the cast's numerics (round-to-nearest-even,
// subnormals, inf, and the NaN branch no current PRE transform can reach)
// are verified against numpy's cast in tests/test_host_store.py.
uint16_t edl_f32_to_f16(float f) { return f32_to_f16(f); }

}  // extern "C" — paused: templates need C++ linkage; resumed below.

// Shared criteo parse core.  PRE=false fills raw arrays (labels int32,
// dense float32, cat int32 = the hex id bit-cast).  PRE=true applies the
// model's host-side preprocessing during the parse — the reference runs its
// preprocessing layers inside the input pipeline the same way (SURVEY.md
// §2 #15) — emitting labels uint8, dense float16 log1p, cat uint16 hashed
// into [0, buckets) with the models/tabular.py multiplicative hash.  The
// compact forms exist to cut PCIe/link bytes per example (160 B -> 79 B).
template <bool PRE, typename LabelT, typename DenseT, typename CatT>
static int64_t criteo_parse(const uint8_t* buf, const int64_t* offsets,
                            int64_t n, LabelT* labels, DenseT* dense,
                            CatT* cat, uint32_t buckets) {
  if (!hex_ready) hex_init();
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* p = buf + offsets[i];
    const uint8_t* rec_end = buf + offsets[i + 1];
    // label: small non-negative int
    int64_t lab = 0;
    bool any = false;
    while (p < rec_end && *p >= '0' && *p <= '9') { lab = lab * 10 + (*p++ - '0'); any = true; }
    if (!any || (p < rec_end && *p != '\t')) return -(i + 1);
    labels[i] = (LabelT)lab;
    // 13 dense fields (blank -> 0.0); output rows pre-zeroed by the caller
    // (for PRE, transform(0) == 0 so missing fields stay correct).
    // Fast path: plain (possibly signed) integers — what the Kaggle dump
    // holds — parsed in one pass; anything else re-parses as a float.
    DenseT* drow = dense + i * 13;
    for (int j = 0; j < 13 && p < rec_end; j++) {
      p++;  // consume the '\t' that ended the previous field
      const uint8_t* fstart = p;
      bool neg = false;
      if (p < rec_end && *p == '-') { neg = true; p++; }
      int64_t v = 0;
      while (p < rec_end && (uint8_t)(*p - '0') < 10) v = v * 10 + (*p++ - '0');
      float val;
      bool got = false;
      if (p == rec_end || *p == '\t') {
        if (p > fstart + (neg ? 1 : 0)) {
          val = (float)(neg ? -v : v);
          got = true;
        } else if (neg) {
          return -(i + 1);  // a bare "-" is not a number (match float('-'))
        }
      } else {
        const uint8_t* fend = p;
        while (fend < rec_end && *fend != '\t') fend++;
        bool ok;
        criteo_float(fstart, fend, &val, &ok);
        if (!ok) return -(i + 1);
        p = fend;
        got = true;
      }
      if (got) {
        if (PRE) {
          // models/tabular.py log_normalize: log1p(max(x, 0)), then the
          // numpy-identical round-to-nearest f16 cast.
          drow[j] = (DenseT)f32_to_f16(std::log1p(val > 0.0f ? val : 0.0f));
        } else {
          drow[j] = (DenseT)val;
        }
      }
    }
    // 26 categorical hex ids (blank -> 0), via a 256-entry nibble LUT.
    CatT* crow = cat + i * 26;
    for (int j = 0; j < 26 && p < rec_end; j++) {
      p++;
      uint32_t v = 0;
      bool got = false;
      while (p < rec_end && *p != '\t') {
        const int8_t d = hex_lut[*p];
        if (d < 0) return -(i + 1);
        v = (v << 4) | (uint32_t)d;
        got = true;
        p++;
      }
      if (got) {
        if (PRE) {
          // models/tabular.py hash_buckets: h = id * 2654435761 (uint32
          // wraparound); h ^= h >> 16; h % buckets.
          uint32_t h = v * 2654435761u;
          h ^= h >> 16;
          crow[j] = (CatT)(h % buckets);
        } else {
          crow[j] = (CatT)(int32_t)v;
        }
      }
    }
    if (p != rec_end) return -(i + 1);  // surplus fields: malformed
  }
  return 0;
}

extern "C" {

int64_t edl_criteo_decode(const uint8_t* buf, const int64_t* offsets,
                          int64_t n, int32_t* labels, float* dense,
                          int32_t* cat) {
  return criteo_parse<false>(buf, offsets, n, labels, dense, cat, 0u);
}

// Census CSV decode (Wide&Deep, BASELINE config #3): ``label,5 numerics,
// 9 categorical strings`` per record.  Numerics follow the ToNumber layer
// (strip; empty/invalid -> 0.0); strings follow the Hashing layer
// (crc32(stripped bytes) % hash_bins — preprocessing/layers.py is the
// source of truth, equality pinned by tests).  Returns 0 or -(i+1) on a
// record whose label fails to parse (the only hard-error field).
int64_t edl_census_decode(const uint8_t* buf, const int64_t* offsets,
                          int64_t n, int32_t* labels, float* dense,
                          int32_t* cat, int64_t hash_bins) {
  for (int64_t i = 0; i < n; i++) {
    const uint8_t* p = buf + offsets[i];
    const uint8_t* rec_end = buf + offsets[i + 1];
    int64_t lab = 0;
    bool neg = false, any = false;
    if (p < rec_end && *p == '-') { neg = true; p++; }
    while (p < rec_end && *p >= '0' && *p <= '9') { lab = lab * 10 + (*p++ - '0'); any = true; }
    if (!any || (p < rec_end && *p != ',')) return -(i + 1);
    labels[i] = (int32_t)(neg ? -lab : lab);
    float* drow = dense + i * 5;
    for (int j = 0; j < 5 && p < rec_end; j++) {
      p++;  // consume ','
      const uint8_t* fend = p;
      while (fend < rec_end && *fend != ',') fend++;
      const uint8_t* s = p;
      const uint8_t* e = fend;
      while (s < e && (*s == ' ' || *s == '\t' || *s == '\r' || *s == '\n')) s++;
      while (e > s && (e[-1] == ' ' || e[-1] == '\t' || e[-1] == '\r' || e[-1] == '\n')) e--;
      if (e > s) {
        bool ok;
        float v;
        criteo_float(s, e, &v, &ok);
        if (ok) drow[j] = v;  // invalid -> stays 0.0 (ToNumber default)
      }
      p = fend;
    }
    int32_t* crow = cat + i * 9;
    for (int j = 0; j < 9 && p < rec_end; j++) {
      p++;
      const uint8_t* fend = p;
      while (fend < rec_end && *fend != ',') fend++;
      const uint8_t* s = p;
      const uint8_t* e = fend;
      while (s < e && (*s == ' ' || *s == '\t' || *s == '\r' || *s == '\n')) s++;
      while (e > s && (e[-1] == ' ' || e[-1] == '\t' || e[-1] == '\r' || e[-1] == '\n')) e--;
      crow[j] = (int32_t)(crc32_buf(s, (size_t)(e - s)) %
                          (uint64_t)hash_bins);
      p = fend;
    }
    if (p != rec_end) return -(i + 1);
  }
  return 0;
}

// Preprocessed decode: labels uint8, dense float16 (log1p-normalized), cat
// uint16 (hashed into [0, buckets); requires buckets <= 65536).  Halves the
// host->device bytes per example — see criteo_parse.
int64_t edl_criteo_decode_pre(const uint8_t* buf, const int64_t* offsets,
                              int64_t n, uint8_t* labels, uint16_t* dense,
                              uint16_t* cat, int64_t buckets) {
  if (buckets < 1 || buckets > 65536) return -(n + 1);
  return criteo_parse<true>(buf, offsets, n, labels, dense, cat,
                            (uint32_t)buckets);
}

// CRC-verify records [start, end) given their offsets; returns the index of
// the first corrupt record, or -1 if all pass.
int64_t edl_recordio_verify(const char* path, const int64_t* offsets,
                            int64_t start, int64_t end) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return start;
  std::vector<uint8_t> buf;
  for (int64_t i = start; i < end; i++) {
    uint32_t hdr[2];
    std::fseek(f, offsets[i], SEEK_SET);
    if (std::fread(hdr, 4, 2, f) != 2) { std::fclose(f); return i; }
    buf.resize(hdr[0]);
    if (hdr[0] && std::fread(buf.data(), 1, hdr[0], f) != hdr[0]) {
      std::fclose(f);
      return i;
    }
    if (crc32_buf(buf.data(), buf.size()) != hdr[1]) {
      std::fclose(f);
      return i;
    }
  }
  std::fclose(f);
  return -1;
}

}  // extern "C"
