"""Reshard PS host-store snapshots between fleet sizes.

A PS fleet's size is fixed for the life of a job (``id mod n`` partition —
ps/service.py), and each shard's snapshot file only loads into a fleet of
the SAME size: resize the fleet between jobs and the old snapshots are
stranded (a relaunched shard of the new size finds no
``{key}.shard{i}of{M}.bin`` and restores nothing).  This module rewrites a
snapshot step for a new fleet size OFFLINE, preserving every row's values
AND optimizer slots bit-for-bit.

It parses the native store's file format directly (ps/native/edl_native.cc
``edl_store_save``): header ``n:i64, dim:i64, stride:i64, opt:i32`` then
``n`` records of ``id:i64, adam_t:i32, stride*f32`` — the stride covers the
row plus its server-side optimizer slots, so resharding moves adagrad/adam
state along with the weights.

CLI:
    python -m elasticdl_tpu.ps.reshard --directory CKPT_DIR --step S \
        --new-shards M
rewrites every table found at ``CKPT_DIR/host_stores/S`` in place (new
shard files appear next to the old ones; pass ``--prune-old`` to delete the
old sharding's files after a successful rewrite).
"""

from __future__ import annotations

import argparse
import os
import re
import struct
from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np

from elasticdl_tpu.common import durable
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.ps.service import shard_of, snapshot_filename

logger = get_logger("ps.reshard")

_HEADER = struct.Struct("<qqqi")  # n, dim, stride, opt
_REC_HEAD = struct.Struct("<qi")  # id, adam_t

_FILE_RE = re.compile(r"^(?P<key>.+)\.shard(?P<i>\d+)of(?P<n>\d+)\.bin$")


def _record_dtype(stride: int) -> np.dtype:
    """The native writer's uniform record layout as a numpy structured dtype
    — one memcpy-speed pass instead of a per-row python loop (the host tier
    exists for beyond-HBM tables; per-row parsing would take minutes)."""
    return np.dtype(
        [("id", "<i8"), ("t", "<i4"), ("row", "<f4", (stride,))]
    )


def read_snapshot(path: str) -> Tuple[dict, np.ndarray, np.ndarray, np.ndarray]:
    """Parse one shard file -> (header, ids [n], adam_t [n], rows [n, stride])."""
    with open(path, "rb") as f:
        raw = f.read()
    if len(raw) < _HEADER.size:
        raise ValueError(f"{path}: truncated header")
    n, dim, stride, opt = _HEADER.unpack_from(raw)
    dtype = _record_dtype(stride)
    if len(raw) != _HEADER.size + n * dtype.itemsize:
        raise ValueError(
            f"{path}: expected {n} records of {dtype.itemsize} bytes, "
            f"got {len(raw) - _HEADER.size} payload bytes"
        )
    recs = np.frombuffer(raw, dtype, count=n, offset=_HEADER.size)
    return (
        {"dim": dim, "stride": stride, "opt": opt},
        recs["id"].copy(),
        recs["t"].copy(),
        recs["row"].copy(),
    )


def write_snapshot(path: str, header: dict, ids, adam_t, rows) -> None:
    """Write records in the native format, atomically
    (durable.atomic_publish: a resharded snapshot must commit whole)."""
    stride = header["stride"]
    recs = np.empty((len(ids),), _record_dtype(stride))
    recs["id"] = np.asarray(ids, np.int64)
    recs["t"] = np.asarray(adam_t, np.int32)
    recs["row"] = np.asarray(rows, np.float32).reshape(len(ids), stride)
    payload = _HEADER.pack(
        len(ids), header["dim"], stride, header["opt"]
    ) + recs.tobytes()
    durable.atomic_publish(path, payload)


def _tables_in(step_dir: str) -> Dict[str, Tuple[int, Dict[int, str]]]:
    """{table_key: (fleet_size, {shard_index: path})} for one step dir.

    Grouped by (key, fleet size) internally and REFUSING mixed shardings of
    the same table: without ``--prune-old`` a previous reshard leaves both
    sizes' files side by side, and silently mixing them (index collisions
    resolved by listdir order) would drop rows without an error.
    """
    by_size: Dict[Tuple[str, int], Dict[int, str]] = defaultdict(dict)
    for name in os.listdir(step_dir):
        m = _FILE_RE.match(name)
        if m:
            by_size[(m.group("key"), int(m.group("n")))][int(m.group("i"))] = (
                os.path.join(step_dir, name)
            )
    sizes_per_key: Dict[str, List[int]] = defaultdict(list)
    for key, n in by_size:
        sizes_per_key[key].append(n)
    for key, sizes in sizes_per_key.items():
        if len(sizes) > 1:
            raise ValueError(
                f"table {key!r} has snapshots for MULTIPLE fleet sizes "
                f"{sorted(sizes)} in {step_dir}; delete the stale sharding "
                "(or rerun the previous reshard with --prune-old) first"
            )
    return {key: (n, shards) for (key, n), shards in by_size.items()}


def reshard_step(
    directory: str, step: int, new_shards: int, prune_old: bool = False
) -> Dict[str, int]:
    """Rewrite every table at ``directory/host_stores/step`` for a
    ``new_shards``-sized fleet.  Returns {table_key: row_count}.  Refuses
    torn inputs (a missing old shard would silently drop its rows) and
    mixed shardings (see _tables_in)."""
    if new_shards <= 0:
        raise ValueError("new_shards must be positive")
    step_dir = os.path.join(directory, "host_stores", str(step))
    tables = _tables_in(step_dir)
    if not tables:
        raise FileNotFoundError(f"no shard files under {step_dir}")
    out: Dict[str, int] = {}
    for key, (old_n, shards) in tables.items():
        missing = [i for i in range(old_n) if i not in shards]
        if missing:
            raise FileNotFoundError(
                f"table {key!r} step {step}: shard {missing[0]} of {old_n} "
                "missing — torn snapshot, refusing to reshard"
            )
        header = None
        all_ids: List[np.ndarray] = []
        all_t: List[np.ndarray] = []
        all_rows: List[np.ndarray] = []
        for i in range(old_n):
            h, ids, adam_t, rows = read_snapshot(shards[i])
            if header is None:
                header = h
            elif h != header:
                raise ValueError(
                    f"table {key!r}: shard {i} header {h} != shard 0 {header}"
                )
            # Sanity: every id really belongs to the shard that held it.
            owners = shard_of(ids, old_n)
            if ids.size and not (owners == i).all():
                bad = ids[owners != i][0]
                raise ValueError(
                    f"table {key!r}: id {bad} found in shard {i} of {old_n} "
                    f"but belongs to shard {int(shard_of(np.array([bad]), old_n)[0])}"
                )
            all_ids.append(ids)
            all_t.append(adam_t)
            all_rows.append(rows)
        ids = np.concatenate(all_ids) if all_ids else np.empty((0,), np.int64)
        adam_t = np.concatenate(all_t)
        rows = np.concatenate(all_rows)
        owners = shard_of(ids, new_shards)
        for j in range(new_shards):
            sel = owners == j
            write_snapshot(
                os.path.join(
                    step_dir, snapshot_filename(key, j, new_shards)
                ),
                header, ids[sel], adam_t[sel], rows[sel],
            )
        if prune_old and old_n != new_shards:
            for i in range(old_n):
                os.remove(shards[i])
        out[key] = int(ids.size)
        logger.info(
            "resharded %s step %d: %d rows, %d -> %d shards",
            key, step, ids.size, old_n, new_shards,
        )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m elasticdl_tpu.ps.reshard")
    ap.add_argument("--directory", required=True, help="job checkpoint dir")
    ap.add_argument("--step", type=int, required=True)
    ap.add_argument("--new-shards", type=int, required=True)
    ap.add_argument("--prune-old", action="store_true")
    args = ap.parse_args(argv)
    counts = reshard_step(
        args.directory, args.step, args.new_shards, prune_old=args.prune_old
    )
    print({"resharded": counts, "new_shards": args.new_shards})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
