"""Native host tier: C++ embedding store + recordio scanner (ctypes).

Reference parity (SURVEY.md §2 #10-#11): the reference's native code is its
Go parameter server (embedding KV store, server-side sparse optimizers,
checkpoint dump) and its kernels.  Here the sharded fast path is HBM-resident
(ops/embedding.py); this package is the C++ host tier for beyond-HBM tables
and the native ingest scanner.
"""

from elasticdl_tpu.ps.host_store import (  # noqa: F401
    HostEmbeddingStore,
    native_lib_available,
)
from elasticdl_tpu.ps.service import (  # noqa: F401
    PSClient,
    PSServer,
    RemoteEmbeddingStore,
)
