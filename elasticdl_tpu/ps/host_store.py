"""ctypes bindings for the native host embedding store (ps/native/).

The shared library is built on first use (g++ is in the image; no pybind11,
per environment constraints).  All APIs take/return numpy arrays; ids are
int64, rows float32.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from elasticdl_tpu.common import locksan
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("ps.host_store")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libedl_native.so")
_OPTIMIZERS = {"sgd": 0, "momentum": 1, "adagrad": 2, "adam": 3}

_lib_lock = locksan.lock("_lib_lock", leaf=True)  # lock-order: leaf
_lib: Optional[ctypes.CDLL] = None
_lib_error: Optional[str] = None

_i64 = ctypes.c_int64
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")


def _build() -> None:
    subprocess.run(
        ["make", "-s", "-C", _NATIVE_DIR],
        check=True,
        capture_output=True,
        text=True,
    )


def _load() -> ctypes.CDLL:
    global _lib, _lib_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_error is not None:
            raise RuntimeError(_lib_error)
        try:
            src = os.path.join(_NATIVE_DIR, "edl_native.cc")
            if not os.path.exists(_LIB_PATH) or os.path.getmtime(
                _LIB_PATH
            ) < os.path.getmtime(src):
                _build()
            try:
                lib = ctypes.CDLL(_LIB_PATH)
            except OSError:
                # A stale/foreign-arch binary that is newer than the source
                # still can't load — rebuild once from source and retry.
                _build()
                lib = ctypes.CDLL(_LIB_PATH)
        except (subprocess.CalledProcessError, OSError) as e:
            _lib_error = f"native lib unavailable: {e}"
            # Said ONCE, loudly: every ingest hot path (criteo/census
            # decode, bulk recordio reads, host stores) silently degrades to
            # Python fallbacks that are ~80x slower (docs/perf.md) — a
            # profile-invisible collapse unless it is logged.  Subsequent
            # calls fail fast on the cached error without re-logging.
            logger.warning(
                "%s — ingest/PS hot paths fall back to Python "
                "implementations (~80x slower decode; see docs/perf.md)",
                _lib_error,
            )
            raise RuntimeError(_lib_error) from e

        lib.edl_store_create.restype = ctypes.c_void_p
        lib.edl_store_create.argtypes = [
            _i64, ctypes.c_int,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float,
        ]
        lib.edl_store_destroy.argtypes = [ctypes.c_void_p]
        lib.edl_store_size.restype = _i64
        lib.edl_store_size.argtypes = [ctypes.c_void_p]
        lib.edl_store_pull.argtypes = [ctypes.c_void_p, _i64p, _i64, _f32p]
        lib.edl_store_try_pull.restype = _i64
        lib.edl_store_try_pull.argtypes = [ctypes.c_void_p, _i64p, _i64, _f32p]
        lib.edl_store_push_grad.argtypes = [ctypes.c_void_p, _i64p, _i64, _f32p]
        lib.edl_store_save.restype = _i64
        lib.edl_store_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.edl_store_load.restype = _i64
        lib.edl_store_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.edl_recordio_index.restype = _i64
        lib.edl_recordio_index.argtypes = [ctypes.c_char_p, _i64p, _i64]
        lib.edl_recordio_verify.restype = _i64
        lib.edl_recordio_verify.argtypes = [ctypes.c_char_p, _i64p, _i64, _i64]
        lib.edl_recordio_read.restype = _i64
        lib.edl_recordio_read.argtypes = [
            ctypes.c_char_p, _i64p, _i64, _i64, _i64, _u8p, _i64, _i64p,
        ]
        lib.edl_criteo_decode.restype = _i64
        lib.edl_criteo_decode.argtypes = [_u8p, _i64p, _i64, _i32p, _f32p, _i32p]
        lib.edl_criteo_decode_pre.restype = _i64
        lib.edl_criteo_decode_pre.argtypes = [
            _u8p, _i64p, _i64, _u8p, _u16p, _u16p, _i64,
        ]
        lib.edl_census_decode.restype = _i64
        lib.edl_census_decode.argtypes = [
            _u8p, _i64p, _i64, _i32p, _f32p, _i32p, _i64,
        ]
        _lib = lib
        return lib


def native_lib_available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


class HostEmbeddingStore:
    """Growable id->row store with server-side sparse optimizers.

    The host tier of the ParameterServer strategy: rows materialize on first
    pull (deterministic per-id init), ``push_grad`` applies one optimizer
    step per distinct id with duplicate contributions pre-accumulated
    (IndexedSlices semantics — same contract the mesh-sharded path's AD
    transpose provides on-device).
    """

    def __init__(
        self,
        dim: int,
        optimizer: str = "adagrad",
        learning_rate: float = 0.01,
        momentum: float = 0.9,
        beta1: float = 0.9,
        beta2: float = 0.999,
        eps: float = 1e-8,
        init_scale: float = 0.05,
    ):
        if optimizer not in _OPTIMIZERS:
            raise ValueError(
                f"unknown optimizer {optimizer!r}, pick from {sorted(_OPTIMIZERS)}"
            )
        self._lib = _load()
        self.dim = dim
        self.optimizer = optimizer
        self._ptr = self._lib.edl_store_create(
            dim, _OPTIMIZERS[optimizer],
            learning_rate, momentum, beta1, beta2, eps, init_scale,
        )

    def __len__(self) -> int:
        return int(self._lib.edl_store_size(self._ptr))

    def pull(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64)
        out = np.empty((ids.size, self.dim), np.float32)
        self._lib.edl_store_pull(self._ptr, ids.ravel(), ids.size, out)
        return out.reshape(ids.shape + (self.dim,))

    def try_pull(self, ids: np.ndarray):
        """Read-only gather: (rows, n_missing).  Safe to run concurrently
        with other readers (NOT with push/pull/load); the PS service uses it
        as the shared-lock fast path and falls back to the exclusive
        ``pull`` when ids are missing."""
        ids = np.ascontiguousarray(ids, np.int64)
        out = np.empty((ids.size, self.dim), np.float32)
        missing = int(
            self._lib.edl_store_try_pull(self._ptr, ids.ravel(), ids.size, out)
        )
        return out.reshape(ids.shape + (self.dim,)), missing

    def push_grad(self, ids: np.ndarray, grads: np.ndarray) -> None:
        ids = np.ascontiguousarray(ids, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32).reshape(ids.size, self.dim)
        self._lib.edl_store_push_grad(self._ptr, ids, ids.size, grads)

    def save(self, path: str) -> int:
        n = int(self._lib.edl_store_save(self._ptr, path.encode()))
        if n < 0:
            raise IOError(f"save to {path} failed")
        return n

    def load(self, path: str) -> int:
        n = int(self._lib.edl_store_load(self._ptr, path.encode()))
        if n == -2:
            raise ValueError("checkpoint optimizer/dim mismatch")
        if n < 0:
            raise IOError(f"load from {path} failed")
        return n

    def close(self) -> None:
        if self._ptr:
            self._lib.edl_store_destroy(self._ptr)
            self._ptr = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def recordio_index_native(path: str) -> np.ndarray:
    """Native recordio offset scan (data/recordio.py's fast path)."""
    lib = _load()
    # Every record costs at least its 8-byte header, so file_size/8 is a hard
    # bound on the record count — but allocating that many int64s up front
    # would cost as much memory as the file itself.  Start from a typical
    # record-count guess and grow on the scanner's -2 (capacity) signal.
    hard_bound = max(os.path.getsize(path) // 8, 1)
    cap = min(hard_bound, 1 << 20)
    while True:
        offsets = np.empty((cap,), np.int64)
        n = int(lib.edl_recordio_index(path.encode(), offsets, cap))
        if n == -2:
            if cap >= hard_bound:
                raise IOError(f"{path}: more records than the size bound allows")
            cap = min(cap * 16, hard_bound)
            continue
        if n < 0:
            raise IOError(f"{path}: malformed recordio")
        return offsets[:n].copy()


def recordio_verify_native(path: str, offsets: np.ndarray, start: int, end: int) -> int:
    lib = _load()
    offsets = np.ascontiguousarray(offsets, np.int64)
    return int(lib.edl_recordio_verify(path.encode(), offsets, start, end))


def recordio_read_native(
    path: str, offsets: np.ndarray, start: int, end: int, file_size: int
) -> tuple:
    """Bulk CRC-checked range read: one disk read + in-memory header walk.

    Returns (payloads: uint8[total], cumulative_offsets: int64[n+1]) — the
    packed form data.packed.PackedRecords wraps.  The ingest hot path
    (SURVEY.md §2 #14: the reference's tf.data C++ pipeline role).
    """
    lib = _load()
    offsets = np.ascontiguousarray(offsets, np.int64)
    n = end - start
    if n <= 0:
        return np.empty((0,), np.uint8), np.zeros((1,), np.int64)
    span = (int(offsets[end]) if end < len(offsets) else file_size) - int(
        offsets[start]
    )
    out = np.empty((span - 8 * n,), np.uint8)
    lens = np.empty((n,), np.int64)
    got = int(
        lib.edl_recordio_read(
            path.encode(), offsets, start, end, span, out, len(out), lens
        )
    )
    if got == -2:
        raise IOError(f"{path}: CRC mismatch in records [{start}, {end})")
    if got < 0:
        raise IOError(f"{path}: malformed recordio in records [{start}, {end})")
    cum = np.empty((n + 1,), np.int64)
    cum[0] = 0
    np.cumsum(lens, out=cum[1:])
    return out[:got], cum


def criteo_decode_native(buf: np.ndarray, offsets: np.ndarray) -> tuple:
    """Decode n packed criteo TSV records -> (labels[n], dense[n,13], cat[n,26]).

    ``offsets`` is cumulative (n+1 entries) into ``buf``; blanks and missing
    trailing fields decode to 0 exactly like the Python feed in
    data/codecs.py (the format's source of truth, numerics-tested against it).
    """
    lib = _load()
    buf = np.ascontiguousarray(buf, np.uint8)
    offsets = np.ascontiguousarray(offsets, np.int64)
    n = len(offsets) - 1
    labels = np.zeros((n,), np.int32)
    dense = np.zeros((n, 13), np.float32)
    cat = np.zeros((n, 26), np.int32)
    rc = int(lib.edl_criteo_decode(buf, offsets, n, labels, dense, cat))
    if rc < 0:
        i = -rc - 1
        bad = bytes(buf[offsets[i] : offsets[i + 1]])
        raise ValueError(f"malformed criteo record {i}: {bad[:120]!r}")
    return labels, dense, cat


def census_decode_native(
    buf: np.ndarray, offsets: np.ndarray, hash_bins: int
) -> tuple:
    """Census CSV decode -> (labels[n], dense[n,5] f32, cat[n,9] i32).

    Numerics follow preprocessing.ToNumber (strip; empty/invalid -> 0.0);
    strings follow preprocessing.Hashing (crc32 % hash_bins) — equality with
    the Python feed pinned by tests/test_data.py."""
    lib = _load()
    buf = np.ascontiguousarray(buf, np.uint8)
    offsets = np.ascontiguousarray(offsets, np.int64)
    n = len(offsets) - 1
    labels = np.zeros((n,), np.int32)
    dense = np.zeros((n, 5), np.float32)
    cat = np.zeros((n, 9), np.int32)
    rc = int(
        lib.edl_census_decode(buf, offsets, n, labels, dense, cat, hash_bins)
    )
    if rc < 0:
        i = -rc - 1
        bad = bytes(buf[offsets[i] : offsets[i + 1]])
        raise ValueError(f"malformed census record {i}: {bad[:120]!r}")
    return labels, dense, cat


def criteo_decode_pre_native(
    buf: np.ndarray, offsets: np.ndarray, buckets: int
) -> tuple:
    """Preprocessed criteo decode: the model's host-side feature transforms
    (models/tabular.py hash_buckets + log_normalize) applied DURING the
    parse, emitting compact wire types — labels uint8, dense float16
    (log1p), cat uint16 in [0, buckets).  79 B/example vs the raw decode's
    160 B: the host->device link is the e2e bottleneck on remote-attached
    chips (docs/perf.md).  Requires buckets <= 65536."""
    lib = _load()
    buf = np.ascontiguousarray(buf, np.uint8)
    offsets = np.ascontiguousarray(offsets, np.int64)
    n = len(offsets) - 1
    labels = np.zeros((n,), np.uint8)
    dense = np.zeros((n, 13), np.uint16)
    cat = np.zeros((n, 26), np.uint16)
    rc = int(
        lib.edl_criteo_decode_pre(buf, offsets, n, labels, dense, cat, buckets)
    )
    if rc == -(n + 1):
        raise ValueError(f"buckets={buckets} out of range for uint16 decode")
    if rc < 0:
        i = -rc - 1
        bad = bytes(buf[offsets[i] : offsets[i + 1]])
        raise ValueError(f"malformed criteo record {i}: {bad[:120]!r}")
    return labels, dense.view(np.float16), cat
