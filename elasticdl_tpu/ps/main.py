"""PS pod entry point — ``python -m elasticdl_tpu.ps.main``.

The master launches ``--num_ps_pods`` of these (master/main.py) exactly as it
launches worker pods; each serves one ``id mod n`` shard of every host-tier
table (ps/service.py).  Reference parity: the reference's PS pod main
(SURVEY.md §2 #10 [U]) — a gRPC server process created by the master, loading
its table slice from the latest checkpoint on (re)start.

Environment (set by the master's pod env, same bus as workers):

- ``ELASTICDL_JOB_CONFIG``  — the job config JSON (model spec -> host_io).
- ``ELASTICDL_WORKER_SLOT`` — this pod's slot = PS shard index.
- ``ELASTICDL_PS_PORTS``    — comma list; this shard binds its slot's port.

PS pods never touch an accelerator: the model spec is loaded only for its
``host_io`` table descriptors, on CPU.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from typing import List, Optional

# PS pods must not grab the TPU chips the workers need — force CPU
# UNCONDITIONALLY (not setdefault: the pod env inherits the worker-oriented
# JAX_PLATFORMS) and re-assert through jax.config, which beats the image
# sitecustomize's force-registered TPU plugin (common/platform.py).
os.environ["JAX_PLATFORMS"] = "cpu"

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.log_utils import get_logger, set_level
from elasticdl_tpu.common.platform import apply_platform_env

apply_platform_env()

logger = get_logger("ps.main")


def main(argv: Optional[List[str]] = None) -> int:
    config = JobConfig.from_env()
    set_level(config.log_level)
    if config.trace:
        # PS-shard spans (ps:pull / ps:push_grad server halves) record
        # locally; the dump tool reaches them via the shard's own process
        # buffer only if shipped — PS pods have no heartbeat channel, so
        # their window is in-process observability (logs/debug) for now.
        from elasticdl_tpu.common import trace as _trace

        _trace.configure(enabled=True, capacity=config.trace_buffer_events)
    if config.chaos:
        # graftchaos rides the same config bus as --trace: delay_ps faults
        # arm in the shard process itself (GRAFT_CHAOS env works too).
        from elasticdl_tpu import chaos as _chaos

        _chaos.configure(config.chaos)

    slot = int(os.environ.get("ELASTICDL_WORKER_SLOT", "0"))
    ports = [
        int(p) for p in os.environ.get("ELASTICDL_PS_PORTS", "0").split(",")
    ]
    num_shards = max(config.num_ps_pods, 1)
    port = ports[slot] if slot < len(ports) else 0

    from elasticdl_tpu.models.spec import load_model_spec_for_job

    spec = load_model_spec_for_job(config)
    if not spec.host_io:
        logger.warning(
            "model %s declares no host-tier tables; PS shard %d idles",
            spec.name, slot,
        )

    from elasticdl_tpu.ps.service import PSServer

    server = PSServer(
        spec.host_io, shard=slot, num_shards=num_shards, port=port
    )
    if config.checkpoint_dir:
        server.restore_latest(config.checkpoint_dir)

    # graftgauge (r14): the shard's live /metrics endpoint — pull/push
    # rates, latency histograms and per-table row counts (PSServer records
    # into the process-default registry).  Daemon threads of their own: a
    # shard wedged in a Save must still answer the scrape.
    from elasticdl_tpu.common.metrics_http import maybe_start

    metrics_server = maybe_start(
        config.gauge_port,
        server.gauges.render_prometheus,
        health_fn=lambda: {
            "role": "ps",
            "shard": slot,
            "num_shards": num_shards,
        },
        registry=server.gauges,
    )

    stop = threading.Event()

    def _terminate(signum, frame):
        logger.info("PS shard %d: signal %d, shutting down", slot, signum)
        stop.set()

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)

    server.start()
    try:
        while not stop.is_set():
            stop.wait(1.0)
    finally:
        server.stop(grace=5.0)
        if metrics_server is not None:
            metrics_server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
