"""Preprocessing layer implementations.

See package docstring for the host/device split.  Every layer follows the
same contract:

- ``adapt(batches)`` — optional fit pass over an iterable of numpy arrays
  (or one array); accumulates state incrementally so arbitrarily large
  datasets stream through.
- ``__call__(x)`` — pure transform.  Works on numpy arrays (host, feed
  stage) and on jax arrays (traced into the jitted step) wherever dtypes
  allow; string inputs are host-only.
- ``get_config()/from_config`` — JSON-serializable state, so fitted
  preprocessing ships to workers over the config bus like the reference
  bakes it into the model image.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

Array = Any  # numpy or jax array


def _numpy_like(x: Array) -> bool:
    return isinstance(x, np.ndarray) or np.isscalar(x) or isinstance(x, (list, tuple))


def _xp(x: Array):
    """The array namespace to compute in: numpy on host data, jnp under jit."""
    if _numpy_like(x):
        return np
    import jax.numpy as jnp

    return jnp


def _norm_token(v: Any) -> Any:
    """Normalize a vocab token to a JSON-safe python scalar: numpy scalars
    unwrap, bytes decode (surrogateescape keeps arbitrary bytes reversible).
    Applied at adapt/init AND lookup time so b'a' and 'a' resolve alike."""
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, bytes):
        return v.decode("utf-8", "surrogateescape")
    return v


def _batches(data: Union[Array, Iterable[Array]]) -> Iterable[np.ndarray]:
    if isinstance(data, np.ndarray):
        yield data
        return
    for batch in data:
        yield np.asarray(batch)


# 32-bit FNV-1a: deterministic across hosts/processes (unlike python's
# salted hash()), cheap to vectorize in numpy, and — because jax disables
# x64 by default — computable identically in jnp uint32 (multiplication is
# mod 2^32 in both namespaces).  Integer ids hash by their low 32 bits:
# embedding id spaces fit in 32 bits on TPU anyway, so nothing aliases.
_FNV_OFFSET32 = 2166136261
_FNV_PRIME32 = 16777619


def _fnv1a_u32(data: np.ndarray) -> np.ndarray:
    """Vectorized FNV-1a of each element's 4 low little-endian bytes."""
    v = (data.astype(np.int64).astype(np.uint64) & np.uint64(0xFFFFFFFF)).astype(
        np.uint32
    )
    h = np.full(v.shape, _FNV_OFFSET32, np.uint32)
    with np.errstate(over="ignore"):
        for shift in range(0, 32, 8):
            h = (h ^ ((v >> np.uint32(shift)) & np.uint32(0xFF))) * np.uint32(
                _FNV_PRIME32
            )
    return h


def _hash_bytes(s: bytes) -> int:
    # Strings never cross into jit, so the string hash only needs to be
    # stable across processes — zlib.crc32 (one C call) keeps the feed path
    # fast where a per-byte Python FNV loop would dominate batch assembly.
    return zlib.crc32(s) & 0xFFFFFFFF


class Hashing:
    """Hash integer or string features into ``[0, num_bins)``.

    The reference's Hashing layer wraps tf.strings.to_hash_bucket_fast; here
    integers use a vectorized 32-bit FNV-1a mix — identical in numpy and in
    jnp under jit, so host and device agree — while strings (host-only by
    nature) use crc32, one C call each, to keep feed-stage batch assembly
    fast.  Both are stable across processes, so master and every worker
    agree; integer and string inputs hash into unrelated bucket assignments.
    """

    def __init__(self, num_bins: int):
        if num_bins <= 0:
            raise ValueError("num_bins must be positive")
        self.num_bins = num_bins

    def __call__(self, x: Array) -> Array:
        if _numpy_like(x):
            arr = np.asarray(x)
            if arr.dtype.kind in ("U", "S", "O"):
                flat = np.array(
                    [
                        _hash_bytes(
                            s.encode() if isinstance(s, str) else bytes(s)
                        )
                        % self.num_bins
                        for s in arr.ravel()
                    ],
                    np.int64,
                )
                return flat.reshape(arr.shape)
            return (_fnv1a_u32(arr) % np.uint32(self.num_bins)).astype(np.int64)
        import jax.numpy as jnp

        v = x.astype(jnp.uint32)
        h = jnp.full(v.shape, _FNV_OFFSET32, jnp.uint32)
        for shift in range(0, 32, 8):
            h = (h ^ ((v >> shift) & jnp.uint32(0xFF))) * jnp.uint32(_FNV_PRIME32)
        return (h % jnp.uint32(self.num_bins)).astype(jnp.int32)

    def get_config(self) -> Dict:
        return {"num_bins": self.num_bins}

    @classmethod
    def from_config(cls, cfg: Dict) -> "Hashing":
        return cls(**cfg)


class IndexLookup:
    """Map categorical values to dense indices via a fitted vocabulary.

    Out-of-vocabulary values map to ``num_oov`` rolling buckets placed
    *before* the vocab (index = hash % num_oov), as the reference's
    IndexLookup does.  ``adapt`` builds the vocab by frequency; a fixed
    vocabulary can be passed directly.  Host-side only for strings; fitted
    integer vocabs also work under jit via sorted-array searchsorted.
    """

    def __init__(
        self,
        vocabulary: Optional[Sequence] = None,
        num_oov: int = 1,
        max_tokens: int = 0,
    ):
        if num_oov < 0:
            raise ValueError("num_oov must be >= 0")
        self.num_oov = num_oov
        self.max_tokens = max_tokens
        self._counts: Dict[Any, int] = {}
        self.vocabulary: List = (
            [_norm_token(v) for v in vocabulary] if vocabulary is not None else []
        )
        self._index: Dict[Any, int] = {}
        self._reindex()

    def _reindex(self) -> None:
        self._index = {
            tok: i + self.num_oov for i, tok in enumerate(self.vocabulary)
        }
        # Integer vocabs additionally support vectorized/jit lookup.
        self._int_vocab: Optional[np.ndarray] = None
        if self.vocabulary and all(
            isinstance(t, (int, np.integer)) for t in self.vocabulary
        ):
            order = np.argsort(np.asarray(self.vocabulary, np.int64))
            self._int_sorted = np.asarray(self.vocabulary, np.int64)[order]
            self._int_rank = order.astype(np.int64)  # sorted pos -> vocab pos
            self._int_vocab = self._int_sorted

    def adapt(self, data: Union[Array, Iterable[Array]]) -> "IndexLookup":
        for batch in _batches(data):
            values, counts = np.unique(batch.ravel(), return_counts=True)
            for v, c in zip(values.tolist(), counts.tolist()):
                v = _norm_token(v)
                self._counts[v] = self._counts.get(v, 0) + c
        ordered = sorted(self._counts.items(), key=lambda kv: (-kv[1], str(kv[0])))
        if self.max_tokens:
            ordered = ordered[: self.max_tokens]
        self.vocabulary = [v for v, _ in ordered]
        self._reindex()
        return self

    @property
    def vocab_size(self) -> int:
        """Total output index space (oov buckets + vocab)."""
        return self.num_oov + len(self.vocabulary)

    def _oov_index(self, value: Any) -> int:
        if self.num_oov == 0:
            raise KeyError(f"{value!r} not in vocabulary (num_oov=0)")
        if isinstance(value, (int, np.integer)):
            return int(_fnv1a_u32(np.asarray([value]))[0] % self.num_oov)
        if isinstance(value, bytes):
            data = value
        else:
            # str, float, bool, ... — hash the canonical string form so any
            # adapt()-able token type lands in a stable OOV bucket.
            data = str(value).encode("utf-8", "surrogateescape")
        return _hash_bytes(data) % self.num_oov

    def __call__(self, x: Array) -> Array:
        if _numpy_like(x):
            arr = np.asarray(x)
            index = self._index
            flat = np.array(
                [
                    index[v] if (v := _norm_token(raw)) in index
                    else self._oov_index(v)
                    for raw in arr.ravel().tolist()
                ],
                np.int64,
            )
            return flat.reshape(arr.shape)
        if self._int_vocab is None:
            raise TypeError(
                "IndexLookup under jit needs an integer vocabulary; "
                "string lookup must run in feed (host)"
            )
        if self.num_oov == 0:
            # The host path raises KeyError per OOV value; traced code can't
            # branch on data, so a silent nearest-index result would map OOV
            # features onto another token's embedding row.  Refuse instead.
            raise ValueError(
                "IndexLookup with num_oov=0 cannot run under jit (OOV inputs "
                "would silently alias in-vocab indices); use num_oov >= 1"
            )
        import jax.numpy as jnp

        sorted_vocab = jnp.asarray(self._int_sorted)
        rank = jnp.asarray(self._int_rank)
        pos = jnp.searchsorted(sorted_vocab, x)
        pos_c = jnp.clip(pos, 0, len(self._int_sorted) - 1)
        hit = sorted_vocab[pos_c] == x
        in_vocab = rank[pos_c] + self.num_oov
        oov = Hashing(self.num_oov)(x)
        return jnp.where(hit, in_vocab, oov)

    def get_config(self) -> Dict:
        # vocabulary is normalized to JSON-safe scalars at adapt/init time
        return {
            "vocabulary": list(self.vocabulary),
            "num_oov": self.num_oov,
            "max_tokens": self.max_tokens,
        }

    @classmethod
    def from_config(cls, cfg: Dict) -> "IndexLookup":
        return cls(**cfg)


class Normalizer:
    """Standardize numeric features with adapted mean/variance (Welford-style
    streaming accumulation, so adapt() handles any dataset size)."""

    def __init__(
        self, mean: Optional[Array] = None, variance: Optional[Array] = None
    ):
        self.mean = None if mean is None else np.asarray(mean, np.float64)
        self.variance = (
            None if variance is None else np.asarray(variance, np.float64)
        )
        self._count = 0.0

    def adapt(self, data: Union[Array, Iterable[Array]]) -> "Normalizer":
        for batch in _batches(data):
            b = batch.astype(np.float64)
            b = b.reshape(-1, b.shape[-1]) if b.ndim > 1 else b.reshape(-1, 1)
            n_b = b.shape[0]
            mean_b = b.mean(0)
            var_b = b.var(0)
            if self._count == 0:
                self.mean, self.variance, self._count = mean_b, var_b, n_b
                continue
            n = self._count + n_b
            delta = mean_b - self.mean
            self.variance = (
                self._count * self.variance
                + n_b * var_b
                + (self._count * n_b / n) * delta**2
            ) / n
            self.mean = self.mean + delta * n_b / n
            self._count = n
        return self

    def __call__(self, x: Array) -> Array:
        if self.mean is None:
            raise RuntimeError("Normalizer not adapted and no mean/variance given")
        xp = _xp(x)
        mean = xp.asarray(self.mean, dtype=xp.float32)
        std = xp.sqrt(xp.asarray(self.variance, dtype=xp.float32) + 1e-7)
        return (x - mean) / std

    def get_config(self) -> Dict:
        return {
            "mean": None if self.mean is None else np.asarray(self.mean).tolist(),
            "variance": None
            if self.variance is None
            else np.asarray(self.variance).tolist(),
        }

    @classmethod
    def from_config(cls, cfg: Dict) -> "Normalizer":
        return cls(**cfg)


class Discretization:
    """Bucketize numeric values by boundaries; ``adapt`` picks quantile
    boundaries (``num_bins``-iles) like the reference layer.  Output ids lie
    in ``[0, num_bins)``; works under jit via searchsorted."""

    def __init__(
        self, bin_boundaries: Optional[Sequence[float]] = None, num_bins: int = 0
    ):
        self.bin_boundaries = (
            None if bin_boundaries is None else [float(b) for b in bin_boundaries]
        )
        self.num_bins = num_bins
        self._samples: List[np.ndarray] = []

    def adapt(
        self, data: Union[Array, Iterable[Array]], max_samples: int = 1_000_000
    ) -> "Discretization":
        if not self.num_bins:
            raise ValueError("adapt() needs num_bins")
        rng = np.random.default_rng(0)
        for batch in _batches(data):
            flat = batch.astype(np.float64).ravel()
            if len(flat) > max_samples:
                flat = rng.choice(flat, max_samples, replace=False)
            self._samples.append(flat)
        sample = np.concatenate(self._samples)
        if len(sample) > max_samples:  # keep the reservoir bounded
            sample = rng.choice(sample, max_samples, replace=False)
            self._samples = [sample]
        qs = np.linspace(0, 1, self.num_bins + 1)[1:-1]
        self.bin_boundaries = np.quantile(sample, qs).tolist()
        return self

    def __call__(self, x: Array) -> Array:
        if self.bin_boundaries is None:
            raise RuntimeError("Discretization not adapted and no boundaries given")
        xp = _xp(x)
        bounds = xp.asarray(self.bin_boundaries, dtype=xp.float32)
        return xp.searchsorted(bounds, xp.asarray(x, dtype=xp.float32)).astype(
            xp.int64 if xp is np else xp.int32
        )

    def get_config(self) -> Dict:
        return {"bin_boundaries": self.bin_boundaries, "num_bins": self.num_bins}

    @classmethod
    def from_config(cls, cfg: Dict) -> "Discretization":
        return cls(**cfg)


class RoundIdentity:
    """Round a numeric feature to an integer id, clipped to ``[0, max_value)``
    (the reference's RoundIdentity feeds embedding lookups this way)."""

    def __init__(self, max_value: int):
        if max_value <= 0:
            raise ValueError("max_value must be positive")
        self.max_value = max_value

    def __call__(self, x: Array) -> Array:
        xp = _xp(x)
        rounded = xp.round(xp.asarray(x, dtype=xp.float32))
        return xp.clip(rounded, 0, self.max_value - 1).astype(
            xp.int64 if xp is np else xp.int32
        )

    def get_config(self) -> Dict:
        return {"max_value": self.max_value}

    @classmethod
    def from_config(cls, cfg: Dict) -> "RoundIdentity":
        return cls(**cfg)


class ToNumber:
    """Parse string/bytes features to numbers host-side (feed stage); numeric
    input passes through.  Empty/invalid strings map to ``default``."""

    def __init__(self, out_dtype: str = "float32", default: float = 0.0):
        self.out_dtype = out_dtype
        self.default = default

    def __call__(self, x: Array) -> Array:
        arr = np.asarray(x)
        if arr.dtype.kind not in ("U", "S", "O"):
            return arr.astype(self.out_dtype)

        def parse(s):
            if isinstance(s, bytes):
                s = s.decode()
            s = s.strip()
            if not s:
                return self.default
            try:
                return float(s)
            except ValueError:
                return self.default

        flat = np.array([parse(s) for s in arr.ravel()], np.float64)
        return flat.reshape(arr.shape).astype(self.out_dtype)

    def get_config(self) -> Dict:
        return {"out_dtype": self.out_dtype, "default": self.default}

    @classmethod
    def from_config(cls, cfg: Dict) -> "ToNumber":
        return cls(**cfg)


class ConcatenateWithOffset:
    """Concatenate per-feature id arrays into one id space: feature ``i``'s
    ids are shifted by the total size of features ``0..i-1`` so a single
    shared embedding table serves them all (the reference uses this to merge
    feature columns into its PS-sharded Embedding)."""

    def __init__(self, sizes: Sequence[int]):
        self.sizes = [int(s) for s in sizes]
        self.offsets = np.concatenate([[0], np.cumsum(self.sizes)[:-1]]).astype(
            np.int64
        )
        self.total_size = int(np.sum(self.sizes))

    def __call__(self, features: Sequence[Array]) -> Array:
        if len(features) != len(self.sizes):
            raise ValueError(
                f"expected {len(self.sizes)} features, got {len(features)}"
            )
        xp = _xp(features[0])
        cols = []
        for i, f in enumerate(features):
            f = xp.asarray(f)
            col = f if f.ndim > 1 else f[:, None]
            cols.append(col + int(self.offsets[i]))
        return xp.concatenate(cols, axis=-1)

    def get_config(self) -> Dict:
        return {"sizes": self.sizes}

    @classmethod
    def from_config(cls, cfg: Dict) -> "ConcatenateWithOffset":
        return cls(**cfg)
