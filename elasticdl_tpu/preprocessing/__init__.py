"""Feature preprocessing layers.

Reference parity (SURVEY.md §2 #15 [U — mount empty at survey time]): the
reference ships ``elasticdl_preprocessing/`` — Keras layers (Hashing,
IndexLookup, Normalizer, Discretization, RoundIdentity, ToNumber,
ConcatenateWithOffset) replacing ``tf.feature_column`` for its tabular
models (census Wide&Deep, Criteo DeepFM).

TPU rebuild: each layer is a small stateful-at-fit-time / pure-at-call-time
object.  ``adapt()`` (vocab building, moment accumulation, quantile
boundaries) runs host-side over numpy record batches — that's feed-stage
work, off the accelerator, exactly where the reference runs it too.
``__call__`` is pure array math: on numpy inputs (inside ``ModelSpec.feed``)
it stays on host; on jnp inputs it traces into the jitted step — no data-
dependent shapes, so XLA compiles it once.  String hashing/lookup is
host-only (strings can't cross into jit) and therefore belongs in ``feed``.
"""

from elasticdl_tpu.preprocessing.layers import (
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    Normalizer,
    RoundIdentity,
    ToNumber,
)

__all__ = [
    "Hashing",
    "IndexLookup",
    "Normalizer",
    "Discretization",
    "RoundIdentity",
    "ToNumber",
    "ConcatenateWithOffset",
]
