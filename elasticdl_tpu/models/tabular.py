"""Shared helpers for the tabular models (Wide&Deep / DeepFM).

The reference preprocesses categorical features with its
``elasticdl_preprocessing`` Keras layers (hashing / vocab lookup) and feeds
each feature to its own ``elasticdl.layers.Embedding`` living on the parameter
server [U — upstream layout; fork mount empty at survey time].

TPU-first redesign: instead of one table (and one PS round-trip) per feature,
all categorical features share ONE fused id space — feature ``f``'s hashed
bucket ``h`` maps to global id ``f * buckets + h``.  One table, one collective
lookup per step, maximally batched for the MXU/ICI; the per-feature structure
survives as the offset.  Hashing happens on-device inside the jitted step so
the host feed stays trivial.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Multiplicative hashing constant (Knuth); cheap and good enough for feature
# bucketing — matches the role of the reference's Hashing preprocessing layer.
_HASH_MULT = jnp.uint32(2654435761)


def hash_buckets(ids: jax.Array, num_buckets: int) -> jax.Array:
    """Hash arbitrary non-negative int ids into [0, num_buckets) on device."""
    h = ids.astype(jnp.uint32) * _HASH_MULT
    h ^= h >> 16
    return (h % jnp.uint32(num_buckets)).astype(jnp.int32)


def fuse_feature_ids(cat_ids: jax.Array, buckets_per_feature: int) -> jax.Array:
    """[batch, n_features] raw ids -> fused global ids in one shared table.

    Feature ``f`` occupies rows ``[f*B, (f+1)*B)`` of the fused table, so a
    single embedding lookup serves every feature at once.
    """
    n_features = cat_ids.shape[-1]
    hashed = hash_buckets(cat_ids, buckets_per_feature)
    offsets = jnp.arange(n_features, dtype=jnp.int32) * buckets_per_feature
    return hashed + offsets


def fuse_feature_ids_np(cat_ids, buckets_per_feature: int):
    """Numpy twin of :func:`fuse_feature_ids` (bit-for-bit identical ids) —
    host-tier pulls compute ids on the host before the jitted step."""
    import numpy as np

    ids = np.asarray(cat_ids)
    h = ids.astype(np.uint32) * np.uint32(2654435761)
    h ^= h >> np.uint32(16)
    hashed = (h % np.uint32(buckets_per_feature)).astype(np.int64)
    offsets = np.arange(ids.shape[-1], dtype=np.int64) * buckets_per_feature
    return hashed + offsets


def log_normalize(dense: jax.Array) -> jax.Array:
    """log(1+x) for non-negative numeric features (standard Criteo recipe)."""
    return jnp.log1p(jnp.maximum(dense.astype(jnp.float32), 0.0))


def binary_metrics(logits: jax.Array, labels: jax.Array, mask=None) -> dict:
    """Loss/accuracy/calibration for binary CTR-style tasks (mask: eval
    tail padding — see models/metrics.py)."""
    from elasticdl_tpu.models.metrics import auc_histograms, masked_mean

    prob = jax.nn.sigmoid(logits)
    pred = (prob >= 0.5).astype(jnp.int32)
    labels_f = labels.astype(jnp.float32)
    bce_per_example = (
        jnp.maximum(logits, 0) - logits * labels_f + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )
    return {
        "loss": masked_mean(bce_per_example, mask),
        "accuracy": masked_mean(pred == labels, mask),
        # mean(prob)/mean(label): ~1.0 when calibrated, a standard CTR sanity metric
        "calibration": masked_mean(prob, mask)
        / jnp.maximum(masked_mean(labels_f, mask), 1e-6),
        # Streaming ROC AUC (the reference's headline Criteo metric): score
        # histograms here, the scalar derived at each pipeline's end
        # (common/metrics.finalize_metrics).
        **auc_histograms(prob, labels, mask),
    }


def bce_loss(logits: jax.Array, labels: jax.Array, mask=None) -> jax.Array:
    """BCE over real examples only (mask: wrap-padded training/eval tails —
    padding carries zero loss, hence zero gradient)."""
    from elasticdl_tpu.models.metrics import masked_mean

    labels_f = labels.astype(jnp.float32)
    per_example = (
        jnp.maximum(logits, 0) - logits * labels_f + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )
    return masked_mean(per_example, mask)
