"""The model contract: what a model-zoo entry must provide.

Reference parity: ElasticDL loads a user module from ``--model_zoo`` /
``--model_def`` and expects ``custom_model()`` (a Keras model), ``loss``,
``optimizer``, ``feed`` plus optional ``eval_metrics_fn`` [U — upstream
contract; fork mount was empty at survey time].  Here the same roles are pure
functions over pytrees so the whole step jits:

- ``init(rng) -> params``                 ~ custom_model() variable creation
- ``apply(params, batch, train) -> out``  ~ model.call
- ``loss(out, batch) -> scalar``          ~ loss
- ``metrics(out, batch) -> dict``         ~ eval_metrics_fn
- ``optimizer``                           ~ optimizer (optax)
- ``feed(records) -> batch``              ~ feed / dataset_fn
- ``predict(params, batch) -> outputs``   ~ predict-mode / serving outputs
  (client-ready values, e.g. probabilities; defaults to apply(train=False))
- ``embedding_tables``                    ~ elasticdl.layers.Embedding usage:
  names of params that are sparse embedding tables, which the
  ParameterServer strategy shards row-wise over the mesh.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

Params = Any  # pytree
Batch = Any  # pytree of arrays


@dataclasses.dataclass(frozen=True)
class EmbeddingTableSpec:
    """Declares one mesh-sharded embedding table inside the param pytree.

    ``path`` addresses the table array in the params pytree (tuple of keys).
    The table is **div-sharded** by row over the mesh's embedding axis: with
    ``n`` shards and padded vocab ``V'``, shard ``i`` owns contiguous rows
    ``[i*V'/n, (i+1)*V'/n)`` (GSPMD's natural layout of a global array — see
    ``elasticdl_tpu.ops.embedding``).  This plays the role of the reference
    PS's partitioned embedding KV store; load balance across shards is
    irrelevant here because the collective lookup does uniform masked work on
    every device regardless of the id distribution.
    """

    path: Tuple[str, ...]
    vocab_size: int
    dim: int


@dataclasses.dataclass(frozen=True)
class HostTableIO:
    """One HOST-TIER embedding table: rows live in the native C++ store
    (``ps/host_store.HostEmbeddingStore``) on the worker host, not in HBM —
    the reference's external-PS tier, for tables too large for the mesh.

    Per step the trainer pulls the batch's rows (``ids_fn`` computes the ids
    host-side in numpy, matching the model's on-device id math bit-for-bit),
    injects them into the batch under the table's key, differentiates the
    jitted step with respect to the injected array, and pushes the sparse
    cotangents back; the store applies its own optimizer per distinct id
    with duplicates pre-accumulated (IndexedSlices semantics, server-side —
    SURVEY.md §2 #10).
    """

    ids_fn: Callable[[Batch], Any]  # numpy batch -> numpy ids [b, F]
    dim: int
    optimizer: str = "adagrad"
    learning_rate: float = 0.01
    init_scale: float = 0.05
    # Sequence-parallel models ONLY: declares that ids_fn returns per-TOKEN
    # ids [b, S(, ...)] whose dim 1 is the model's sequence dim, so the
    # injected rows legally shard with the sequence.  Without the
    # declaration a [b, F]-shaped table under SP would silently
    # feature-slice — the trainer refuses instead (parallel/trainer.py).
    per_token: bool = False


@dataclasses.dataclass
class ModelSpec:
    name: str
    init: Callable[..., Params]  # (rng) -> params
    apply: Callable[..., Any]  # (params, batch, train=bool) -> outputs
    loss: Callable[[Any, Batch], Any]  # (outputs, batch) -> scalar
    metrics: Callable[[Any, Batch], Dict[str, Any]]
    optimizer: Any  # optax.GradientTransformation
    feed: Optional[Callable[[Sequence[bytes]], Batch]] = None
    embedding_tables: List[EmbeddingTableSpec] = dataclasses.field(
        default_factory=list
    )
    # Host-tier tables: batch key -> HostTableIO.  The model's apply reads
    # the injected vectors from the batch under the key instead of looking
    # up a params table.
    host_io: Dict[str, "HostTableIO"] = dataclasses.field(default_factory=dict)
    # Which batch dimension the mesh axis shards: 0 = data parallelism
    # (examples, the default), 1 = sequence/context parallelism (each device
    # holds every example's [S/n] chunk — ring attention territory).  Leaves
    # with ndim <= batch_shard_dim (e.g. per-example masks under SP)
    # replicate on a 1-D mesh; on hierarchical (dp, ep) meshes they follow
    # the example dim's dp sharding (trainer._batch_spec_for).
    batch_shard_dim: int = 0
    # Tensor-parallel sharding plan (r20, the 2D ``(dp, tp)`` mesh): a
    # callable ``(params) -> tree`` matching the params structure whose
    # leaves are the int dim each weight shards over the ``tp`` axis
    # (Megatron column/row splits) or None for replicated leaves.  The
    # trainer uses it to lay params AND their optimizer moments out on
    # the tp axis; None (the default) means the model is tp-oblivious
    # and only ever runs on 1-D / (dp, ep) meshes.
    tensor_sharding: Optional[Callable[[Params], Any]] = None
    # Example batch (tiny) for compile checks / shape inference.
    example_batch: Optional[Callable[[int], Batch]] = None
    # Inference entry point (the serving tier's forward, and predict-mode
    # jobs): ``(params, batch, ctx=...) -> per-example outputs`` ready for a
    # client — e.g. sigmoid probability for the binary tabular models,
    # class probabilities for mnist — instead of raw training logits.
    # None = serve ``apply(params, batch, train=False)`` outputs as-is.
    # Jitted inside build_predict_step, so the transform is free on device.
    predict: Optional[Callable[..., Any]] = None


def load_model_spec(model_zoo: str, model_def: str, **params: Any) -> ModelSpec:
    """Load ``model_spec`` from a zoo module.

    ``model_def`` is "module.function" relative to the ``model_zoo`` package,
    mirroring the reference's ``--model_zoo``/``--model_def`` resolution.
    """
    module_name, _, fn_name = model_def.rpartition(".")
    if not module_name:
        raise ValueError(
            f"--model_def must look like 'module.function', got {model_def!r}"
        )
    module = importlib.import_module(f"{model_zoo}.{module_name}")
    fn = getattr(module, fn_name)
    spec = fn(**params)
    if not isinstance(spec, ModelSpec):
        raise TypeError(f"{model_def} returned {type(spec)}, expected ModelSpec")
    return spec


def load_model_spec_for_job(config: Any) -> ModelSpec:
    """Load the model for a JobConfig, plumbing job-level knobs.

    ``--learning_rate`` / ``--compute_dtype`` flags are forwarded to the model
    fn when it accepts them; explicit ``--model_params`` entries win (same
    precedence the reference gives model-module definitions over defaults).
    """
    import inspect

    params: dict = {}
    module_name, _, fn_name = config.model_def.rpartition(".")
    module = importlib.import_module(f"{config.model_zoo}.{module_name}")
    accepted = inspect.signature(getattr(module, fn_name)).parameters
    if "learning_rate" in accepted:
        params["learning_rate"] = config.learning_rate
    if "compute_dtype" in accepted:
        params["compute_dtype"] = config.compute_dtype
    params.update(config.parsed_model_params())
    return load_model_spec(config.model_zoo, config.model_def, **params)
