"""DeepFM on Criteo-Kaggle — BASELINE.json config #4, the flagship benchmark
("DeepFM on Criteo-Kaggle, PS embedding + dense AllReduce hybrid").

Reference parity [D: config list; sources unverifiable — mount empty at survey
time]: the reference builds DeepFM from ``elasticdl.layers.Embedding`` (tables
on the gRPC parameter server) plus Keras dense layers synced via Horovod
allreduce.  Here the "hybrid" is just two partition specs inside ONE jitted
step: the fused embedding tables are row-sharded over the mesh (declared via
``embedding_tables``), dense params are replicated with psum'd grads.

Criteo schema: 13 numeric ("I1..I13", log1p-normalized) + 26 categorical
("C1..C26", hashed into a fused table — see models/tabular.py).

Model = first-order linear term + FM second-order pairwise interactions
+ DNN over [embeddings; normalized numerics]; all three heads sum into one
logit.  Compute in bfloat16 (MXU-native), f32 params/loss.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import optax

from elasticdl_tpu.data.codecs import criteo_feed, criteo_feed_pre
from elasticdl_tpu.models.spec import EmbeddingTableSpec, HostTableIO, ModelSpec
from elasticdl_tpu.models.tabular import (
    bce_loss,
    binary_metrics,
    fuse_feature_ids,
    fuse_feature_ids_np,
    log_normalize,
)
from elasticdl_tpu.ops.embedding import (
    ParallelContext,
    embedding_lookup,
    pack_table,
)

NUM_DENSE = 13
NUM_CAT = 26


HOST_FM_KEY = "__host__fm_table"


def _init_params(
    rng: jax.Array,
    buckets_per_feature: int,
    embedding_dim: int,
    hidden: tuple,
    host_tier: bool = False,
) -> Dict[str, Any]:
    vocab = NUM_CAT * buckets_per_feature
    ks = jax.random.split(rng, 4 + len(hidden))
    glorot = jax.nn.initializers.glorot_normal()
    # One sharded table (the "parameter server" part) holds BOTH the FM
    # embedding (dims 0..embedding_dim-1, normal init) and the first-order
    # linear weight (last dim, zero init) per id: the per-id scatter/gather
    # cost is per PHYSICAL ROW (128 lanes) regardless of dim, so a separate
    # dim-1 linear table would double the dominant scatter-add for 1/128th
    # of a row's payload (profiled: tools/profile_step.py).  Stored
    # lane-packed — see ops/embedding.py: whole-physical-row gathers/
    # scatters are the TPU fast path (flat-slice layout hit a serial
    # per-row loop).
    params: Dict[str, Any] = {
        # Replicated dense params (the "allreduce" part).
        "dense_linear": {
            "w": jnp.zeros((NUM_DENSE, 1), jnp.float32),
            "b": jnp.zeros((1,), jnp.float32),
        },
        "mlp": {},
    }
    if not host_tier:
        # Host-tier mode keeps NO device table: rows live in the native C++
        # store (lazy, per-id) and arrive through the batch.
        fm_logical = jnp.concatenate(
            [
                jax.random.normal(ks[0], (vocab, embedding_dim)) * 0.01,
                jnp.zeros((vocab, 1), jnp.float32),
            ],
            axis=-1,
        )
        params["fm_table"] = pack_table(fm_logical, embedding_dim + 1)
    in_dim = NUM_CAT * embedding_dim + NUM_DENSE
    for i, width in enumerate(hidden):
        params["mlp"][f"layer{i}"] = {
            "w": glorot(ks[2 + i], (in_dim, width), jnp.float32),
            "b": jnp.zeros((width,), jnp.float32),
        }
        in_dim = width
    params["mlp"]["out"] = {
        "w": glorot(ks[2 + len(hidden)], (in_dim, 1), jnp.float32),
        "b": jnp.zeros((1,), jnp.float32),
    }
    return params


def _apply(
    params,
    batch,
    train: bool = False,
    ctx: ParallelContext = ParallelContext(),
    buckets_per_feature: int = 0,
    embedding_dim: int = 8,
    compute_dtype=jnp.bfloat16,
    **_,
):
    # Pipeline-preprocessed batches (criteo_feed_pre) arrive with the host
    # transforms already applied — float16 dense is log1p'd, uint16 cat ids
    # are hashed bucket ids.  Dtype is static under jit, so this branch
    # costs nothing at runtime.
    d = batch["dense"]
    dense = (
        d.astype(jnp.float32) if d.dtype == jnp.float16 else log_normalize(d)
    )

    if HOST_FM_KEY in batch:
        # Host-tier: vectors were pulled from the C++ store and injected by
        # the trainer; their cotangents flow back out as sparse grads.
        vecs = batch[HOST_FM_KEY]  # [b, 26, dim+1]
    else:
        c = batch["cat"]
        if c.dtype == jnp.uint16:  # pre-hashed: apply the feature offsets only
            ids = c.astype(jnp.int32) + (
                jnp.arange(NUM_CAT, dtype=jnp.int32) * buckets_per_feature
            )
        else:
            ids = fuse_feature_ids(c, buckets_per_feature)  # [b, 26]
        vecs = embedding_lookup(
            params["fm_table"], ids, ctx, dim=embedding_dim + 1
        )
    emb, lin = vecs[..., :embedding_dim], vecs[..., embedding_dim]  # [b,26,d],[b,26]

    emb = emb.astype(compute_dtype)
    dense_c = dense.astype(compute_dtype)

    # First-order: sparse linear + dense linear.
    first = jnp.sum(lin, axis=-1, dtype=jnp.float32)
    dl = params["dense_linear"]
    first = first + (dense @ dl["w"])[:, 0] + dl["b"][0]

    # Second-order FM: 0.5 * sum_d[(sum_f v)^2 - sum_f v^2].
    sum_v = jnp.sum(emb, axis=1)
    sum_v2 = jnp.sum(emb * emb, axis=1)
    fm = 0.5 * jnp.sum(sum_v * sum_v - sum_v2, axis=-1).astype(jnp.float32)

    # Deep head.
    x = jnp.concatenate([emb.reshape(emb.shape[0], -1), dense_c], axis=-1)
    mlp = params["mlp"]
    n_hidden = len(mlp) - 1
    for i in range(n_hidden):
        layer = jax.tree.map(lambda a: a.astype(compute_dtype), mlp[f"layer{i}"])
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    out = jax.tree.map(lambda a: a.astype(compute_dtype), mlp["out"])
    deep = (x @ out["w"] + out["b"])[:, 0].astype(jnp.float32)

    return first + fm + deep


def _predict(params, batch, ctx: ParallelContext = ParallelContext(), **kw):
    """Inference entry (serving tier / predict jobs): click probability in
    [0, 1], not the raw logit — what an online caller actually consumes."""
    return jax.nn.sigmoid(_apply(params, batch, train=False, ctx=ctx, **kw))


def _loss(logits, batch, mask=None):
    return bce_loss(logits, batch["labels"], mask)


def _metrics(logits, batch, mask=None):
    return binary_metrics(logits, batch["labels"], mask)


def _example_batch(batch_size: int, pre: bool = False):
    if pre:
        return {
            "dense": jnp.zeros((batch_size, NUM_DENSE), jnp.float16),
            "cat": jnp.zeros((batch_size, NUM_CAT), jnp.uint16),
            "labels": jnp.zeros((batch_size,), jnp.uint8),
        }
    return {
        "dense": jnp.zeros((batch_size, NUM_DENSE), jnp.float32),
        "cat": jnp.zeros((batch_size, NUM_CAT), jnp.int32),
        "labels": jnp.zeros((batch_size,), jnp.int32),
    }


def model_spec(
    learning_rate: float = 1e-3,
    compute_dtype: str = "bfloat16",
    buckets_per_feature: int = 65536,
    embedding_dim: int = 8,
    hidden: Any = (400, 400),
    host_tier: Any = "auto",
    pipeline_preprocess: Any = "auto",
) -> ModelSpec:
    """``host_tier``: True places the FM table in the native host store
    (ps/host_store) instead of HBM; "auto" promotes it when the padded table
    plus Adam moments would crowd a chip's HBM (ops.embedding guard) — the
    reference's external gRPC-PS tier, for vocabularies beyond mesh memory.

    ``pipeline_preprocess``: run the feature transforms (hash bucketing +
    log1p) in the input pipeline's C++ decoder instead of on device,
    shipping compact dtypes (uint16/float16/uint8 — 79 B/example vs 160 B).
    The reference's preprocessing layers live in the input pipeline the same
    way (SURVEY.md §2 #15).  "auto" enables it for the mesh-tier model
    whenever the bucket count fits uint16; the on-device transform path
    remains for raw batches (numerics pinned equal by tests).
    """
    if isinstance(hidden, (list, tuple)):
        hidden = tuple(int(h) for h in hidden)
    else:  # "400,400" via --model_params
        hidden = tuple(int(h) for h in str(hidden).split(",") if h)
    dtype = jnp.dtype(compute_dtype)
    vocab = NUM_CAT * buckets_per_feature
    dim = embedding_dim
    if host_tier == "auto":
        from elasticdl_tpu.ops.embedding import exceeds_hbm_guard

        host_tier = exceeds_hbm_guard(vocab, dim + 1)
    host_tier = bool(host_tier)
    if pipeline_preprocess == "auto":
        # Host-tier pulls need the RAW ids (fuse_feature_ids_np over the
        # full 32-bit space); uint16 bucket ids only exist for <= 2^16.
        pipeline_preprocess = not host_tier and buckets_per_feature <= 65536
    pipeline_preprocess = bool(pipeline_preprocess)
    if pipeline_preprocess and (host_tier or buckets_per_feature > 65536):
        raise ValueError(
            "pipeline_preprocess requires the mesh-tier model and "
            "buckets_per_feature <= 65536"
        )
    return ModelSpec(
        name="deepfm",
        init=functools.partial(
            _init_params,
            buckets_per_feature=buckets_per_feature,
            embedding_dim=dim,
            hidden=hidden,
            host_tier=host_tier,
        ),
        apply=functools.partial(
            _apply,
            buckets_per_feature=buckets_per_feature,
            embedding_dim=dim,
            compute_dtype=dtype,
        ),
        predict=functools.partial(
            _predict,
            buckets_per_feature=buckets_per_feature,
            embedding_dim=dim,
            compute_dtype=dtype,
        ),
        loss=_loss,
        metrics=_metrics,
        optimizer=optax.adam(learning_rate),
        embedding_tables=(
            []
            if host_tier
            else [
                EmbeddingTableSpec(
                    path=("fm_table",), vocab_size=vocab, dim=dim + 1
                )
            ]
        ),
        host_io=(
            {
                HOST_FM_KEY: HostTableIO(
                    ids_fn=functools.partial(
                        _host_ids, buckets_per_feature=buckets_per_feature
                    ),
                    dim=dim + 1,
                    optimizer="adagrad",
                    learning_rate=learning_rate * 10,
                    init_scale=0.01,
                )
            }
            if host_tier
            else {}
        ),
        feed=(
            functools.partial(criteo_feed_pre, buckets=buckets_per_feature)
            if pipeline_preprocess
            else criteo_feed
        ),
        example_batch=functools.partial(
            _example_batch, pre=pipeline_preprocess
        ),
    )


def _host_ids(batch, buckets_per_feature: int):
    """Host-side (numpy) fused ids — identical to the on-device hash."""
    return fuse_feature_ids_np(batch["cat"], buckets_per_feature)
