"""Wide&Deep on Census-income — BASELINE.json config #3 ("Wide&Deep on Census
income, ParameterServer mode + elasticdl.layers.Embedding").

Reference parity [D: config list; sources unverifiable — mount empty at survey
time]: the reference's census model feeds ``elasticdl_preprocessing`` hashing/
lookup layers into PS-hosted embeddings.  Here both the wide table (linear
weights over hashed singles + pairwise crosses) and the deep table are fused,
mesh-sharded embedding tables; hashing and crossing run on-device inside the
jitted step (models/tabular.py).

Census schema (classic UCI adult): 5 numeric (age, education_num,
capital_gain, capital_loss, hours_per_week) + 9 categorical (workclass,
education, marital_status, occupation, relationship, race, sex,
native_country, income-bracket source field unused).
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import optax

from elasticdl_tpu.data.codecs import census_feed
from elasticdl_tpu.models.spec import EmbeddingTableSpec, ModelSpec
from elasticdl_tpu.models.tabular import (
    bce_loss,
    binary_metrics,
    fuse_feature_ids,
    hash_buckets,
    log_normalize,
)
from elasticdl_tpu.ops.embedding import (
    ParallelContext,
    embedding_lookup,
    init_table,
    table_shape,
)

NUM_DENSE = 5
NUM_CAT = 9
_CROSSES = tuple(itertools.combinations(range(NUM_CAT), 2))  # all 36 pairs


def _wide_ids(cat: jax.Array, buckets: int) -> jax.Array:
    """[b, NUM_CAT + len(_CROSSES)] fused wide-table ids: hashed singles then
    hashed pairwise crosses, each slot with its own row range."""
    singles = fuse_feature_ids(cat, buckets)  # [b, 9]
    a = cat[:, [i for i, _ in _CROSSES]].astype(jnp.uint32)
    b = cat[:, [j for _, j in _CROSSES]].astype(jnp.uint32)
    crossed = hash_buckets(a * jnp.uint32(1000003) + b, buckets)
    offsets = (NUM_CAT + jnp.arange(len(_CROSSES), dtype=jnp.int32)) * buckets
    return jnp.concatenate([singles, crossed + offsets], axis=-1)


def _init_params(rng, buckets: int, embedding_dim: int, hidden: tuple):
    wide_vocab = (NUM_CAT + len(_CROSSES)) * buckets
    deep_vocab = NUM_CAT * buckets
    ks = jax.random.split(rng, 3 + len(hidden))
    glorot = jax.nn.initializers.glorot_normal()
    params: Dict[str, Any] = {
        # Lane-packed tables — see ops/embedding.py for why (TPU gather layout).
        "wide": jnp.zeros(table_shape(wide_vocab, 1), jnp.float32),
        "deep_embedding": init_table(
            ks[0], deep_vocab, embedding_dim, scale=0.05
        ),
        "mlp": {},
        "bias": jnp.zeros((1,), jnp.float32),
    }
    in_dim = NUM_CAT * embedding_dim + NUM_DENSE
    for i, width in enumerate(hidden):
        params["mlp"][f"layer{i}"] = {
            "w": glorot(ks[1 + i], (in_dim, width), jnp.float32),
            "b": jnp.zeros((width,), jnp.float32),
        }
        in_dim = width
    params["mlp"]["out"] = {
        "w": glorot(ks[1 + len(hidden)], (in_dim, 1), jnp.float32),
        "b": jnp.zeros((1,), jnp.float32),
    }
    return params


def _apply(
    params,
    batch,
    train: bool = False,
    ctx: ParallelContext = ParallelContext(),
    buckets: int = 0,
    embedding_dim: int = 8,
    compute_dtype=jnp.bfloat16,
    **_,
):
    cat = batch["cat"]
    dense = log_normalize(batch["dense"])

    wide_ids = _wide_ids(cat, buckets)
    deep_ids = fuse_feature_ids(cat, buckets)

    wide_w = embedding_lookup(params["wide"], wide_ids, ctx, dim=1)  # [b, nw, 1]
    emb = embedding_lookup(
        params["deep_embedding"], deep_ids, ctx, dim=embedding_dim
    )  # [b, 9, d]

    wide = jnp.sum(wide_w[..., 0], axis=-1, dtype=jnp.float32)

    x = jnp.concatenate(
        [emb.reshape(emb.shape[0], -1), dense], axis=-1
    ).astype(compute_dtype)
    mlp = params["mlp"]
    for i in range(len(mlp) - 1):
        layer = jax.tree.map(lambda a: a.astype(compute_dtype), mlp[f"layer{i}"])
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    out = jax.tree.map(lambda a: a.astype(compute_dtype), mlp["out"])
    deep = (x @ out["w"] + out["b"])[:, 0].astype(jnp.float32)

    return wide + deep + params["bias"][0]


def _predict(params, batch, ctx: ParallelContext = ParallelContext(), **kw):
    """Inference entry (serving tier / predict jobs): income-bracket
    probability in [0, 1] rather than the raw logit."""
    return jax.nn.sigmoid(_apply(params, batch, train=False, ctx=ctx, **kw))


def _loss(logits, batch, mask=None):
    return bce_loss(logits, batch["labels"], mask)


def _metrics(logits, batch, mask=None):
    return binary_metrics(logits, batch["labels"], mask)


def _example_batch(batch_size: int):
    return {
        "dense": jnp.zeros((batch_size, NUM_DENSE), jnp.float32),
        "cat": jnp.zeros((batch_size, NUM_CAT), jnp.int32),
        "labels": jnp.zeros((batch_size,), jnp.int32),
    }


def model_spec(
    learning_rate: float = 1e-3,
    compute_dtype: str = "bfloat16",
    buckets: int = 1024,
    embedding_dim: int = 8,
    hidden: Any = (100, 50),
) -> ModelSpec:
    if isinstance(hidden, (list, tuple)):
        hidden = tuple(int(h) for h in hidden)
    else:
        hidden = tuple(int(h) for h in str(hidden).split(",") if h)
    dtype = jnp.dtype(compute_dtype)
    return ModelSpec(
        name="wide_deep",
        init=functools.partial(
            _init_params, buckets=buckets, embedding_dim=embedding_dim, hidden=hidden
        ),
        apply=functools.partial(
            _apply, buckets=buckets, embedding_dim=embedding_dim, compute_dtype=dtype
        ),
        predict=functools.partial(
            _predict, buckets=buckets, embedding_dim=embedding_dim, compute_dtype=dtype
        ),
        loss=_loss,
        metrics=_metrics,
        optimizer=optax.adam(learning_rate),
        embedding_tables=[
            EmbeddingTableSpec(
                path=("wide",),
                vocab_size=(NUM_CAT + len(_CROSSES)) * buckets,
                dim=1,
            ),
            EmbeddingTableSpec(
                path=("deep_embedding",),
                vocab_size=NUM_CAT * buckets,
                dim=embedding_dim,
            ),
        ],
        feed=census_feed,
        example_batch=_example_batch,
    )
