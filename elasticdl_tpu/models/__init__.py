"""Model zoo + model contract.

The reference's model contract (model_zoo modules exporting
``custom_model()/loss/optimizer/feed`` [U: mount empty at survey time,
upstream layout]) is re-cast functionally for JAX: each model-zoo module
exports ``model_spec(**params) -> ModelSpec`` — pure init/apply/loss/metrics
functions plus an optax optimizer and embedding-table metadata so the trainer
can shard sparse tables over the mesh.
"""

from elasticdl_tpu.models.spec import (  # noqa: F401
    EmbeddingTableSpec,
    ModelSpec,
    load_model_spec,
    load_model_spec_for_job,
)
