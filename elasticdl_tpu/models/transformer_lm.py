"""Decoder-only transformer LM with ring-attention sequence parallelism.

Long-context is first-class in this rebuild (the reference predates it —
SURVEY.md §2 parallelism table: SP/CP absent upstream; this is a TPU-native
capability extension, not a parity item).  The model declares
``batch_shard_dim=1``: the trainer shards the SEQUENCE dimension over the
mesh axis, each device holds ``[B, S/n]`` of every sequence, and attention
runs blockwise while K/V blocks rotate around the ICI ring
(``ops/ring_attention.py`` — compute overlaps the ppermute transfer, so HBM
per device scales with S/n, enabling sequences that cannot fit one chip).

The label shift never crosses shard boundaries: the codec stores S+1 tokens
per record and the feed emits (tokens[:-1], tokens[1:]) BEFORE sharding.
Dense params are replicated with psum'd grads (the AllReduce strategy), so
SP composes with the existing trainer unchanged; positions are globalized
with the device's axis index.

``model_spec(parallelism="tensor")`` (r20) selects the hybrid-parallel
variant for the 2D ``(dp, tp)`` mesh instead: Megatron column/row-split
projections (``wqkv``/``w1`` column-sharded, ``wo``/``w2`` row-sharded
over ``tp``, declared via ``ModelSpec.tensor_sharding``), batch sharded
over ``dp`` (``batch_shard_dim=0``), ONE ``tp`` all-reduce per residual
branch through ``parallel/collectives``'s custom-VJP pair (``tp_grad_sync``
/ ``tp_all_reduce`` — identity<->psum transposes hand-written because the
shim's check_vma=False shard_map would transpose psum to psum and
over-count replicated cotangents by ``tp``).  The same apply runs dense on
a 1-D mesh (``ctx.tp_axis is None``), which is what a 2D->1D elastic
re-partition degrades to.

Architecture: pre-RMSNorm blocks, causal MHA (ring, or local full under
tensor parallelism), GELU MLP (4x), learned positional embedding,
weight-tied LM head.  bfloat16 compute, f32 params.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import optax
from jax import lax

from elasticdl_tpu.common.jax_compat import axis_size
from elasticdl_tpu.data.codecs import lm_feed
from elasticdl_tpu.models.spec import ModelSpec
from elasticdl_tpu.ops.ring_attention import attention_reference, ring_attention
from elasticdl_tpu.ops.embedding import ParallelContext


def _rms_norm(x, scale, eps=1e-6):
    # Stats and the normalize/affine arithmetic in f32, ONE downcast at the
    # end.  The previous form multiplied the downcast value by the f32
    # ``scale`` param LAST, silently promoting every tensor downstream of
    # the first norm (q/k/v, MLP, residuals) to f32 — the "bfloat16
    # compute" stream was f32 end to end (caught when the flash-attention
    # kernel received f32 operands and blew its VMEM budget at L=8192).
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return ((x * jax.lax.rsqrt(var + eps)) * scale).astype(x.dtype)


def _init_params(
    rng, vocab: int, dim: int, n_heads: int, n_layers: int, max_seq: int
) -> Dict[str, Any]:
    ks = iter(jax.random.split(rng, 3 + 5 * n_layers))
    scale = dim**-0.5
    params: Dict[str, Any] = {
        "tok_emb": jax.random.normal(next(ks), (vocab, dim)) * scale,
        "pos_emb": jax.random.normal(next(ks), (max_seq, dim)) * 0.01,
        "ln_f": jnp.ones((dim,), jnp.float32),
        "blocks": {},
    }
    for i in range(n_layers):
        params["blocks"][f"b{i}"] = {
            "ln1": jnp.ones((dim,), jnp.float32),
            "wqkv": jax.random.normal(next(ks), (dim, 3 * dim)) * scale,
            "wo": jax.random.normal(next(ks), (dim, dim)) * scale,
            "ln2": jnp.ones((dim,), jnp.float32),
            "w1": jax.random.normal(next(ks), (dim, 4 * dim)) * scale,
            "w2": jax.random.normal(next(ks), (4 * dim, dim)) * (0.5 * scale),
        }
    return params


def _block(x, blk, axis, n_heads, compute_dtype):
    """One pre-norm transformer block (attention + MLP residual)."""
    b, l, dim = x.shape
    head_dim = dim // n_heads
    h = _rms_norm(x, blk["ln1"])
    qkv = h @ blk["wqkv"].astype(compute_dtype)  # [B, L, 3*dim]
    q, k, v = jnp.split(qkv.reshape(b, l, 3 * n_heads, head_dim), 3, axis=2)
    # Blockwise causal attention; K/V ring over the sequence axis.
    att = ring_attention(q, k, v, axis_name=axis, causal=True)
    x = x + att.reshape(b, l, dim) @ blk["wo"].astype(compute_dtype)
    h = _rms_norm(x, blk["ln2"])
    h = jax.nn.gelu(h @ blk["w1"].astype(compute_dtype))
    return x + h @ blk["w2"].astype(compute_dtype)


def _apply(
    params,
    batch,
    train: bool = False,
    ctx: ParallelContext = ParallelContext(),
    n_heads: int = 4,
    compute_dtype=jnp.bfloat16,
    remat: bool = True,
    **_,
):
    tokens = batch["tokens"]  # [B, L_local] (sequence-sharded over the axis)
    l = tokens.shape[1]
    axis = ctx.axis_name
    # Fail loud on over-long sequences: positions past max_seq would silently
    # CLAMP on the pos_emb gather (same stance as the embedding OOV contract).
    n_shards = axis_size(axis) if axis is not None else 1
    if l * n_shards > params["pos_emb"].shape[0]:
        raise ValueError(
            f"global sequence length {l * n_shards} exceeds max_seq "
            f"{params['pos_emb'].shape[0]}; raise max_seq in the model spec"
        )
    # Global positions of this device's sequence chunk.
    offset = lax.axis_index(axis) * l if axis is not None else 0
    pos = offset + jnp.arange(l)

    x = params["tok_emb"][tokens] + params["pos_emb"][pos][None]
    x = x.astype(compute_dtype)
    # Rematerialization (jax.checkpoint) per block in TRAINING: activations
    # inside a block are recomputed during the backward instead of living in
    # HBM for the whole forward — peak activation memory drops from
    # O(n_layers * B * S/n * dim * ~10) to ~one block's worth (+ the residual
    # stream), the standard FLOPs-for-HBM trade for long sequences.  The
    # ring-attention ppermutes replay fine under remat (pure collective).
    # Eval/predict skip it — there is no backward to save memory for.
    block_fn = functools.partial(
        _block, axis=axis, n_heads=n_heads, compute_dtype=compute_dtype
    )
    if remat and train:
        block_fn = jax.checkpoint(block_fn)
    for name in sorted(params["blocks"]):
        x = block_fn(x, params["blocks"][name])
    x = _rms_norm(x, params["ln_f"])
    # Weight-tied head; logits in f32 for a stable softmax/CE.
    return (x @ params["tok_emb"].T.astype(compute_dtype)).astype(jnp.float32)


def _tp_block(x, blk, tp_axis, n_heads, compute_dtype):
    """One pre-norm block, tensor-parallel (Megatron split).

    This rank holds ``wqkv``/``w1`` column shards and ``wo``/``w2`` row
    shards; ``x`` (the residual stream) and the norm gains are replicated
    across ``tp``.  Each residual branch costs exactly one tp all-reduce
    (the *g* op after its row-split matmul); the matching *f* op sits
    AFTER the norm so the norm gain differentiates against the full,
    already-summed cotangent rather than one rank's partial.  Attention
    runs complete locally over this rank's ``n_heads/tp`` heads — head
    splitting needs no sequence collective at all.

    With ``tp_axis=None`` (1-D mesh, or no mesh) the shards are the full
    matrices and both collectives drop out: the dense path, bit-identical
    in every column-split matmul, which is what the mesh2d parity probe
    leans on.
    """
    # Trace-time import: a module-level one would close the ops ->
    # parallel -> ops import cycle (parallel/__init__ pulls the trainer,
    # which needs ops.embedding mid-initialization).
    from elasticdl_tpu.parallel.collectives import tp_all_reduce, tp_grad_sync

    b, l, dim = x.shape
    tp = axis_size(tp_axis) if tp_axis is not None else 1
    local_heads = n_heads // tp
    head_dim = dim // n_heads
    h = _rms_norm(x, blk["ln1"])
    if tp_axis is not None:
        h = tp_grad_sync(h, tp_axis)
    qkv = h @ blk["wqkv"].astype(compute_dtype)  # [B, L, 3*dim/tp]
    # HEAD-MAJOR column layout ([q_h | k_h | v_h] per head, heads
    # consecutive): a contiguous 1/tp column shard is then exactly this
    # rank's heads with their complete q/k/v — the split the tp sharding
    # plan's wqkv dim-1 entry produces.  (The sequence-parallel _block
    # reads the same random init as [all-q | all-k | all-v]; both are
    # valid labelings of iid columns, but only head-major composes with
    # contiguous sharding.)
    qkv = qkv.reshape(b, l, local_heads, 3, head_dim)
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    att = attention_reference(q, k, v, causal=True)
    out = att.reshape(b, l, dim // tp) @ blk["wo"].astype(compute_dtype)
    if tp_axis is not None:
        out = tp_all_reduce(out, tp_axis)
    x = x + out
    h = _rms_norm(x, blk["ln2"])
    if tp_axis is not None:
        h = tp_grad_sync(h, tp_axis)
    h = jax.nn.gelu(h @ blk["w1"].astype(compute_dtype))
    out = h @ blk["w2"].astype(compute_dtype)
    if tp_axis is not None:
        out = tp_all_reduce(out, tp_axis)
    return x + out


def _tp_apply(
    params,
    batch,
    train: bool = False,
    ctx: ParallelContext = ParallelContext(),
    n_heads: int = 4,
    compute_dtype=jnp.bfloat16,
    remat: bool = True,
    **_,
):
    """Hybrid-parallel forward: batch rows sharded over ``dp`` (each
    device sees ``[B/dp, L]`` complete sequences — positions need no
    axis offset), weight shards over ``ctx.tp_axis``."""
    tokens = batch["tokens"]  # [B_local, L] — full sequences
    l = tokens.shape[1]
    tp = axis_size(ctx.tp_axis) if ctx.tp_axis is not None else 1
    if n_heads % tp:
        raise ValueError(
            f"tensor parallelism {tp} does not divide n_heads {n_heads}; "
            f"pick tp from the head count's divisor chain"
        )
    if l > params["pos_emb"].shape[0]:
        raise ValueError(
            f"sequence length {l} exceeds max_seq "
            f"{params['pos_emb'].shape[0]}; raise max_seq in the model spec"
        )
    pos = jnp.arange(l)
    x = params["tok_emb"][tokens] + params["pos_emb"][pos][None]
    x = x.astype(compute_dtype)
    block_fn = functools.partial(
        _tp_block, tp_axis=ctx.tp_axis, n_heads=n_heads,
        compute_dtype=compute_dtype,
    )
    if remat and train:
        block_fn = jax.checkpoint(block_fn)
    for name in sorted(params["blocks"]):
        x = block_fn(x, params["blocks"][name])
    x = _rms_norm(x, params["ln_f"])
    return (x @ params["tok_emb"].T.astype(compute_dtype)).astype(jnp.float32)


def _tp_dims(params):
    """The ``ModelSpec.tensor_sharding`` plan: which dim of each weight
    shards over ``tp``.  Column splits (``wqkv``, ``w1``) shard dim 1 —
    their outputs are per-rank slices; row splits (``wo``, ``w2``) shard
    dim 0 — their outputs are partial sums the block's ``tp_all_reduce``
    completes.  Everything else (embeddings, norm gains) replicates."""
    return {
        "tok_emb": None,
        "pos_emb": None,
        "ln_f": None,
        "blocks": {
            name: {
                "ln1": None,
                "wqkv": 1,
                "wo": 0,
                "ln2": None,
                "w1": 1,
                "w2": 0,
            }
            for name in params["blocks"]
        },
    }


def _loss(logits, batch, mask=None):
    # Mean CE over this device's tokens (mask: whole padded SEQUENCES carry
    # zero weight); the trainer's count/total weighting makes it the global
    # mean.
    from elasticdl_tpu.models.metrics import masked_mean

    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"]
    )
    return masked_mean(ce, mask)


def _metrics(logits, batch):
    ce = _loss(logits, batch)
    acc = jnp.mean(
        (jnp.argmax(logits, -1) == batch["labels"]).astype(jnp.float32)
    )
    return {"loss": ce, "accuracy": acc}


def _example_batch(batch_size: int, seq_len: int = 256):
    return {
        "tokens": jnp.zeros((batch_size, seq_len), jnp.int32),
        "labels": jnp.zeros((batch_size, seq_len), jnp.int32),
    }


def model_spec(
    learning_rate: float = 3e-4,
    compute_dtype: str = "bfloat16",
    vocab: int = 8192,
    dim: int = 256,
    n_heads: int = 4,
    n_layers: int = 2,
    max_seq: int = 4096,
    seq_len: int = 256,
    remat: bool = True,
    parallelism: str = "sequence",
) -> ModelSpec:
    """``parallelism`` picks the scale axis: ``"sequence"`` (default,
    ring attention over a 1-D mesh's sequence shards) or ``"tensor"``
    (Megatron weight shards over the 2D mesh's ``tp`` axis, batch over
    ``dp`` — see module docstring)."""
    if parallelism not in ("sequence", "tensor"):
        raise ValueError(
            f"parallelism must be 'sequence' or 'tensor', got {parallelism!r}"
        )
    dtype = jnp.dtype(compute_dtype)
    tensor = parallelism == "tensor"
    apply_fn = _tp_apply if tensor else _apply
    return ModelSpec(
        name="transformer_lm",
        init=functools.partial(
            _init_params,
            vocab=vocab,
            dim=dim,
            n_heads=n_heads,
            n_layers=n_layers,
            max_seq=max_seq,
        ),
        apply=functools.partial(
            apply_fn, n_heads=n_heads, compute_dtype=dtype, remat=remat
        ),
        loss=_loss,
        metrics=_metrics,
        optimizer=optax.adamw(learning_rate),
        feed=lm_feed,
        example_batch=functools.partial(_example_batch, seq_len=seq_len),
        # sequence parallelism shards dim 1 (see module docstring); tensor
        # parallelism keeps sequences whole and shards examples over dp.
        batch_shard_dim=0 if tensor else 1,
        tensor_sharding=_tp_dims if tensor else None,
    )
