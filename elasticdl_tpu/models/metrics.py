"""Mask-aware metric helpers.

Eval shards rarely divide the minibatch size, and XLA needs static shapes,
so the worker wrap-pads the tail chunk and feeds a ``__mask__`` vector
(1.0 = real example, 0.0 = padding).  Metrics functions accept that mask and
compute means over REAL examples only — without it, the padded duplicates
were over-weighted (VERDICT r2 Weak #4).  The trainer aggregates the masked
local means across devices as psum(mean·count)/psum(count), which is exact
even when devices hold different numbers of real examples.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def masked_mean(values: jax.Array, mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean of per-example ``values`` [b] over real examples only."""
    values = values.astype(jnp.float32)
    if mask is None:
        return jnp.mean(values)
    m = mask.astype(jnp.float32)
    return jnp.sum(values * m) / jnp.maximum(jnp.sum(m), 1e-12)
