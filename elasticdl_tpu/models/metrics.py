"""Mask-aware metric helpers.

Eval shards rarely divide the minibatch size, and XLA needs static shapes,
so the worker wrap-pads the tail chunk and feeds a ``__mask__`` vector
(1.0 = real example, 0.0 = padding).  Metrics functions accept that mask and
compute means over REAL examples only — without it, the padded duplicates
were over-weighted (VERDICT r2 Weak #4).  The trainer aggregates the masked
local means across devices as psum(mean·count)/psum(count), which is exact
even when devices hold different numbers of real examples.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def masked_mean(values: jax.Array, mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean of per-example ``values`` over real examples only.

    ``values`` may carry trailing per-example dims (e.g. per-token CE
    [b, s]); the [b] mask broadcasts across them, so every real example's
    elements weigh equally."""
    values = values.astype(jnp.float32)
    if mask is None:
        return jnp.mean(values)
    m = mask.astype(jnp.float32)
    if m.ndim < values.ndim:
        m = m.reshape(m.shape + (1,) * (values.ndim - m.ndim))
    w = jnp.broadcast_to(m, values.shape)
    return jnp.sum(values * w) / jnp.maximum(jnp.sum(w), 1e-12)


#: Score-histogram resolution for streaming AUC.  512 buckets bounds the
#: binning bias at ~2e-3 worst-case (uniform ties within a bucket count
#: half) — the same knob as TF's AUC ``num_thresholds``.
AUC_BINS = 512


def auc_histograms(
    probs: jax.Array,
    labels: jax.Array,
    mask: Optional[jax.Array] = None,
    n_bins: int = AUC_BINS,
) -> dict:
    """Per-bucket positive/negative counts of ``probs`` in [0, 1].

    The device-side half of streaming AUC (common/metrics.py
    ``auc_from_histograms``): histograms are LINEAR, so they survive every
    aggregation layer — masked minibatch sums, the eval step's
    psum(mean*count)/total, the worker's per-task weighting, the master's
    cross-worker weighted mean — and the AUC derived at the end equals the
    AUC of the pooled predictions (exactly, for scores on the bucket grid;
    to ~1/n_bins otherwise).  Returns {AUC_POS: [n_bins], AUC_NEG: [n_bins]}
    metric entries, normalized to MEANS (divided by the real-example count)
    so they weight-average identically to the scalar metrics around them —
    AUC is scale-invariant, so the normalization cancels.
    """
    from elasticdl_tpu.common.metrics import AUC_NEG, AUC_POS

    probs = probs.astype(jnp.float32).reshape(-1)
    labels_f = labels.astype(jnp.float32).reshape(-1)
    m = (
        jnp.ones_like(probs)
        if mask is None
        else mask.astype(jnp.float32).reshape(-1)
    )
    idx = jnp.clip((probs * n_bins).astype(jnp.int32), 0, n_bins - 1)
    pos = jnp.zeros((n_bins,), jnp.float32).at[idx].add(m * labels_f)
    neg = jnp.zeros((n_bins,), jnp.float32).at[idx].add(m * (1.0 - labels_f))
    count = jnp.maximum(jnp.sum(m), 1e-12)
    return {AUC_POS: pos / count, AUC_NEG: neg / count}
