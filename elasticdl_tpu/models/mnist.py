"""MNIST model — parity with the reference's model_zoo mnist functional model
(BASELINE.json config #1; reference path model_zoo/mnist [D], unverifiable in
detail: mount empty at survey time).

The reference uses a small Keras functional CNN; here it is a pure-JAX CNN
(conv -> relu -> conv -> relu -> maxpool -> mlp) written so the whole step
fuses under jit.  Compute runs in ``compute_dtype`` (bfloat16 by default —
MXU-native) with f32 params and f32 loss.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import optax

from elasticdl_tpu.data.codecs import mnist_feed
from elasticdl_tpu.models.spec import ModelSpec

IMAGE_SHAPE = (28, 28, 1)
NUM_CLASSES = 10


def _init_params(rng: jax.Array, compute_dtype=jnp.bfloat16) -> Dict[str, Any]:
    k = jax.random.split(rng, 4)
    he = jax.nn.initializers.he_normal()
    return {
        "conv1": {
            "w": he(k[0], (3, 3, 1, 32), jnp.float32),
            "b": jnp.zeros((32,), jnp.float32),
        },
        "conv2": {
            "w": he(k[1], (3, 3, 32, 64), jnp.float32),
            "b": jnp.zeros((64,), jnp.float32),
        },
        "dense1": {
            "w": he(k[2], (12 * 12 * 64, 128), jnp.float32),
            "b": jnp.zeros((128,), jnp.float32),
        },
        "dense2": {
            "w": he(k[3], (128, NUM_CLASSES), jnp.float32),
            "b": jnp.zeros((NUM_CLASSES,), jnp.float32),
        },
    }


def _apply(params, batch, train: bool = False, compute_dtype=jnp.bfloat16, **_):
    x = batch["images"].astype(compute_dtype)
    if x.ndim == 3:
        x = x[..., None]
    cast = lambda p: jax.tree.map(lambda a: a.astype(compute_dtype), p)
    c1, c2 = cast(params["conv1"]), cast(params["conv2"])
    d1, d2 = cast(params["dense1"]), cast(params["dense2"])

    dn = jax.lax.conv_dimension_numbers(x.shape, c1["w"].shape, ("NHWC", "HWIO", "NHWC"))
    x = jax.lax.conv_general_dilated(x, c1["w"], (1, 1), "VALID", dimension_numbers=dn)
    x = jax.nn.relu(x + c1["b"])
    dn = jax.lax.conv_dimension_numbers(x.shape, c2["w"].shape, ("NHWC", "HWIO", "NHWC"))
    x = jax.lax.conv_general_dilated(x, c2["w"], (1, 1), "VALID", dimension_numbers=dn)
    x = jax.nn.relu(x + c2["b"])
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ d1["w"] + d1["b"])
    logits = x @ d2["w"] + d2["b"]
    return logits.astype(jnp.float32)


def _loss(logits, batch, mask=None):
    from elasticdl_tpu.models.metrics import masked_mean

    labels = batch["labels"]
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return masked_mean(ce, mask)


def _predict(params, batch, compute_dtype=jnp.bfloat16, **_):
    """Inference entry (serving tier / predict jobs): class probabilities
    [b, 10] rather than raw logits."""
    return jax.nn.softmax(
        _apply(params, batch, train=False, compute_dtype=compute_dtype), axis=-1
    )


def _metrics(logits, batch, mask=None) -> Dict[str, Any]:
    from elasticdl_tpu.models.metrics import masked_mean

    labels = batch["labels"]
    return {
        "accuracy": masked_mean(jnp.argmax(logits, -1) == labels, mask),
        "loss": masked_mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, labels),
            mask,
        ),
    }


def _example_batch(batch_size: int):
    return {
        "images": jnp.zeros((batch_size,) + IMAGE_SHAPE, jnp.float32),
        "labels": jnp.zeros((batch_size,), jnp.int32),
    }


def model_spec(learning_rate: float = 1e-3, compute_dtype: str = "bfloat16") -> ModelSpec:
    dtype = jnp.dtype(compute_dtype)
    return ModelSpec(
        name="mnist",
        init=functools.partial(_init_params, compute_dtype=dtype),
        apply=functools.partial(_apply, compute_dtype=dtype),
        predict=functools.partial(_predict, compute_dtype=dtype),
        loss=_loss,
        metrics=_metrics,
        optimizer=optax.sgd(learning_rate, momentum=0.9),
        feed=mnist_feed,
        example_batch=_example_batch,
    )
