"""ResNet-50 on CIFAR-10 — BASELINE.json config #2 ("ResNet-50 on CIFAR-10,
AllReduce mode").

Reference parity [D: config list; sources unverifiable — mount empty at survey
time]: the reference trains a Keras ResNet via Horovod allreduce.  Rebuilt as
a pure-JAX bottleneck ResNet whose whole train step jits over the mesh.

TPU-first choices:
- **GroupNorm instead of BatchNorm.**  BatchNorm's running stats are mutable
  state and need cross-replica sync to be correct under data parallelism;
  GroupNorm is the standard stat-free substitute on TPU pods (same accuracy
  class on CIFAR) and keeps ``apply`` a pure function of the param pytree,
  so the AllReduce step stays a single fused XLA program.
- CIFAR stem (3x3 stride-1 conv, no maxpool) instead of the ImageNet 7x7/s2
  stem, as is standard for 32x32 inputs.
- Compute in bfloat16 (MXU), f32 params, f32 norm statistics.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax

from elasticdl_tpu.data.codecs import cifar10_feed
from elasticdl_tpu.models.spec import ModelSpec

NUM_CLASSES = 10


def _conv_init(rng, shape):
    return jax.nn.initializers.he_normal()(rng, shape, jnp.float32)


def _conv(x, w, stride=1):
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME", dimension_numbers=dn
    )


def _group_norm(x, scale, bias, groups=8, eps=1e-5):
    """GroupNorm with no full-size f32 intermediate.

    The naive form (upcast x to f32, mean/var, normalize, affine, downcast)
    spent ~40% of the ResNet-50 step in convert_element_type + f32
    elementwise + multi-pass reduces (per-op trace, tools/profile_step.py
    --config resnet50_imagenet).  TPU-native form:

    - moments in ONE pass: sum and sum-of-squares reduced directly from the
      bf16 input with f32 accumulation (XLA fuses the upcast/square into the
      reduction input; no [b,h,w,c] f32 tensor is ever materialized);
    - statistics + affine folded into per-(batch, channel) a/b vectors in
      f32 (tiny), applied to the activation as a single fused bf16
      multiply-add — one read + one write of x instead of five+.

    Gradients flow through the folded a/b exactly as through the unfolded
    math (they are the same function of x); only the dtype of the big
    elementwise stream changes, which is the point.

    Rounding caveat of the fold: a/b are computed in f32 but CAST TO x's
    dtype before the fused multiply-add, so in bf16 both the product
    ``x * a`` and the pre-added offset ``b - mean * a`` round to 8
    mantissa bits — the unfolded form would subtract the mean from x at
    higher effective precision before scaling.  When |bias| ≈ |mean * a|
    the offset suffers bf16 cancellation ON TOP of the one-pass variance
    cancellation noted above.  Accepted because post-norm activations are
    O(1) (absolute rounding error ~2^-8 of a unit-scale stream, below the
    noise the bf16 convs already inject) and the fold is what buys the
    single-pass memory shape; models sensitive to it should run the norm
    stream in f32, not un-fold.
    """
    b, h, w, c = x.shape
    g = min(groups, c)
    cg = c // g
    xg = x.reshape(b, h, w, g, cg)
    n = h * w * cg
    s = jnp.sum(xg, axis=(1, 2, 4), dtype=jnp.float32)  # [b, g]
    ss = jnp.sum(
        jnp.square(xg.astype(jnp.float32)), axis=(1, 2, 4)
    )  # [b, g]
    mean = s / n
    # One-pass variance; activations are O(1) post-norm/relu so the
    # E[x^2]-E[x]^2 cancellation is benign in f32.  Clamp for safety.
    var = jnp.maximum(ss / n - jnp.square(mean), 0.0)
    inv = jax.lax.rsqrt(var + eps)  # [b, g]
    a = inv[:, :, None] * scale.reshape(g, cg)  # [b, g, cg]
    off = bias.reshape(g, cg) - mean[:, :, None] * a
    a = a.reshape(b, 1, 1, c).astype(x.dtype)
    off = off.reshape(b, 1, 1, c).astype(x.dtype)
    return x * a + off


def _init_block(rng, in_ch: int, mid_ch: int, stride: int) -> Dict[str, Any]:
    out_ch = mid_ch * 4
    ks = jax.random.split(rng, 4)
    block = {
        "conv1": _conv_init(ks[0], (1, 1, in_ch, mid_ch)),
        "gn1": {"scale": jnp.ones((mid_ch,)), "bias": jnp.zeros((mid_ch,))},
        "conv2": _conv_init(ks[1], (3, 3, mid_ch, mid_ch)),
        "gn2": {"scale": jnp.ones((mid_ch,)), "bias": jnp.zeros((mid_ch,))},
        "conv3": _conv_init(ks[2], (1, 1, mid_ch, out_ch)),
        # Zero-init the last norm scale: residual branches start as identity,
        # the standard trick for stable large-batch training.
        "gn3": {"scale": jnp.zeros((out_ch,)), "bias": jnp.zeros((out_ch,))},
    }
    if stride != 1 or in_ch != out_ch:
        block["proj"] = _conv_init(ks[3], (1, 1, in_ch, out_ch))
        block["gn_proj"] = {"scale": jnp.ones((out_ch,)), "bias": jnp.zeros((out_ch,))}
    return block


def _apply_block(params, x, stride: int):
    y = _conv(x, params["conv1"].astype(x.dtype))
    y = jax.nn.relu(_group_norm(y, **params["gn1"]))
    y = _conv(y, params["conv2"].astype(x.dtype), stride)
    y = jax.nn.relu(_group_norm(y, **params["gn2"]))
    y = _conv(y, params["conv3"].astype(x.dtype))
    y = _group_norm(y, **params["gn3"])
    if "proj" in params:
        x = _conv(x, params["proj"].astype(x.dtype), stride)
        x = _group_norm(x, **params["gn_proj"])
    return jax.nn.relu(x + y)


def _init_params(
    rng,
    stages: Tuple[int, ...],
    width: int,
    num_classes: int = NUM_CLASSES,
    imagenet_stem: bool = False,
) -> Dict[str, Any]:
    ks = jax.random.split(rng, 2 + len(stages))
    # ImageNet stem: 7x7/s2 conv (+ 3x3/s2 maxpool in apply) — the standard
    # 224x224 configuration and the honest MXU-utilization benchmark shape
    # (32x32 CIFAR convs are too small to tile the systolic array well).
    stem_kernel = (7, 7, 3, width) if imagenet_stem else (3, 3, 3, width)
    params: Dict[str, Any] = {
        "stem": {
            "conv": _conv_init(ks[0], stem_kernel),
            "gn": {"scale": jnp.ones((width,)), "bias": jnp.zeros((width,))},
        },
        "stages": {},
    }
    in_ch = width
    for s, n_blocks in enumerate(stages):
        mid = width * (2**s)
        stage = {}
        block_keys = jax.random.split(ks[1 + s], n_blocks)
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            stage[f"block{b}"] = _init_block(block_keys[b], in_ch, mid, stride)
            in_ch = mid * 4
        params["stages"][f"stage{s}"] = stage
    params["head"] = {
        "w": jax.nn.initializers.glorot_normal()(
            ks[-1], (in_ch, num_classes), jnp.float32
        ),
        "b": jnp.zeros((num_classes,), jnp.float32),
    }
    return params


def _apply(
    params,
    batch,
    train: bool = False,
    stages: Tuple[int, ...] = (),
    compute_dtype=jnp.bfloat16,
    imagenet_stem: bool = False,
    **_,
):
    x = batch["images"].astype(compute_dtype)
    stem = params["stem"]
    x = _conv(x, stem["conv"].astype(compute_dtype), 2 if imagenet_stem else 1)
    x = jax.nn.relu(_group_norm(x, **stem["gn"]))
    if imagenet_stem:
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
        )
    for s, n_blocks in enumerate(stages):
        for b in range(n_blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            x = _apply_block(params["stages"][f"stage{s}"][f"block{b}"], x, stride)
    x = jnp.mean(x, axis=(1, 2), dtype=jnp.float32)
    head = params["head"]
    return x @ head["w"] + head["b"]


def _loss(logits, batch, mask=None):
    from elasticdl_tpu.models.metrics import masked_mean

    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"]
    )
    return masked_mean(ce, mask)


def _metrics(logits, batch, mask=None):
    from elasticdl_tpu.models.metrics import masked_mean

    return {
        "accuracy": masked_mean(jnp.argmax(logits, -1) == batch["labels"], mask),
        "loss": masked_mean(
            optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["labels"]
            ),
            mask,
        ),
    }


def _example_batch(batch_size: int, image_size: int = 32):
    return {
        "images": jnp.zeros(
            (batch_size, image_size, image_size, 3), jnp.float32
        ),
        "labels": jnp.zeros((batch_size,), jnp.int32),
    }


def model_spec(
    learning_rate: float = 0.1,
    compute_dtype: str = "bfloat16",
    depth: int = 50,
    width: int = 64,
    image_size: int = 32,
    num_classes: int = NUM_CLASSES,
    imagenet_stem: bool = False,
) -> ModelSpec:
    """depth=50 -> bottleneck stages (3,4,6,3); depth=14 (tests) -> (1,1,1,1).

    ``image_size=224, num_classes=1000, imagenet_stem=True`` is the
    standard ImageNet ResNet-50 — the configuration MFU benchmarks use
    (tools/bench_all.py 'resnet50_imagenet'); the CIFAR default matches
    BASELINE config #2.
    """
    stage_map = {50: (3, 4, 6, 3), 26: (2, 2, 2, 2), 14: (1, 1, 1, 1)}
    if depth not in stage_map:
        raise ValueError(f"unsupported depth {depth}, pick from {sorted(stage_map)}")
    stages = stage_map[depth]
    dtype = jnp.dtype(compute_dtype)
    if image_size != 32 or num_classes != NUM_CLASSES:
        # Non-CIFAR shapes have no dataset codec in the zoo: a job feeding
        # cifar10_feed records into this variant would silently recompile
        # against 32x32/10-class batches and train 990 dead classes.
        # Fail loudly; the MFU bench feeds synthetic batches directly.
        def feed(records):
            raise RuntimeError(
                f"resnet image_size={image_size}/num_classes={num_classes} "
                "has no dataset codec — this variant takes synthetic "
                "batches (tools/bench_all.py) or a custom feed, not "
                "cifar10 records"
            )
    else:
        feed = cifar10_feed
    return ModelSpec(
        name=f"cifar10_resnet{depth}",
        init=functools.partial(
            _init_params, stages=stages, width=width,
            num_classes=num_classes, imagenet_stem=imagenet_stem,
        ),
        apply=functools.partial(
            _apply, stages=stages, compute_dtype=dtype,
            imagenet_stem=imagenet_stem,
        ),
        loss=_loss,
        metrics=_metrics,
        optimizer=optax.sgd(learning_rate, momentum=0.9, nesterov=True),
        feed=feed,
        example_batch=functools.partial(
            _example_batch, image_size=image_size
        ),
    )
