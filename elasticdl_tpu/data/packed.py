"""Packed record batches: one contiguous buffer + cumulative offsets.

The worker's ingest path moves thousands of small records per device step;
materializing each as a Python ``bytes`` object costs more interpreter time
than the device step itself at recommendation-model batch sizes.  Readers
that can, return a ``PackedRecords`` (one bulk CRC-checked C++ read —
ps/host_store.recordio_read_native); feeds that can, decode straight from
its buffer (data/codecs.py criteo path).  Everything else treats it as the
``Sequence[bytes]`` it duck-types, so the packed form is purely an
optimization, never a new contract (SURVEY.md §2 #14 — the reference gets
this for free from tf.data's C++ pipeline).
"""

from __future__ import annotations

from typing import Iterator, Sequence, Union

import numpy as np


class PackedRecords(Sequence):
    """Immutable batch of variable-length records over one shared buffer.

    ``offsets`` has n+1 entries; record i is ``buf[offsets[i]:offsets[i+1]]``.
    Slicing returns a zero-copy view (shared buffer, sliced offsets);
    indexing returns ``bytes``.
    """

    __slots__ = ("buf", "offsets")

    def __init__(self, buf: np.ndarray, offsets: np.ndarray):
        self.buf = buf
        self.offsets = offsets

    @classmethod
    def from_records(cls, records: Sequence[bytes]) -> "PackedRecords":
        lens = np.fromiter(
            (len(r) for r in records), np.int64, count=len(records)
        )
        offsets = np.empty((len(records) + 1,), np.int64)
        offsets[0] = 0
        np.cumsum(lens, out=offsets[1:])
        buf = np.frombuffer(b"".join(records), np.uint8)
        return cls(buf, offsets)

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(
        self, i: Union[int, slice]
    ) -> Union[bytes, "PackedRecords"]:
        if isinstance(i, slice):
            start, stop, step = i.indices(len(self))
            if step != 1:
                raise ValueError("PackedRecords slices must be contiguous")
            return PackedRecords(self.buf, self.offsets[start : stop + 1])
        if i < 0:
            i += len(self)
        return bytes(self.buf[self.offsets[i] : self.offsets[i + 1]])

    def __iter__(self) -> Iterator[bytes]:
        for i in range(len(self)):
            yield self[i]

    def tobytes(self) -> bytes:
        """The records' payloads, concatenated (no separators)."""
        return bytes(self.buf[self.offsets[0] : self.offsets[-1]])

    def span(self) -> np.ndarray:
        """Zero-copy uint8 view of the concatenated payloads."""
        return self.buf[self.offsets[0] : self.offsets[-1]]


def concat_records(records: Sequence[bytes]) -> np.ndarray:
    """Concatenated payload bytes of any record sequence as a uint8 array —
    zero-copy for PackedRecords, one join otherwise."""
    if isinstance(records, PackedRecords):
        return records.span()
    return np.frombuffer(b"".join(records), np.uint8)


def as_packed(records: Sequence[bytes]) -> PackedRecords:
    if isinstance(records, PackedRecords):
        return records
    return PackedRecords.from_records(records)
