"""Shardable data readers.

Reference parity (SURVEY.md §2 #14 [U — mount empty at survey time]): the
master calls ``create_shards()`` to enumerate (name, start, end) ranges that
become dispatchable tasks; workers call ``read_records(shard)`` for the range
a task names.  Epoch/task logic lives in the master's TaskDispatcher, NOT
here — readers are stateless range servers, which is what makes a preempted
worker's work requeue-able with no data loss.
"""

from __future__ import annotations

import dataclasses
import glob
import os
from typing import Dict, Iterator, List, Optional

from elasticdl_tpu.data.recordio import RecordIOReader


@dataclasses.dataclass(frozen=True)
class Shard:
    """A half-open record range [start, end) within a named source."""

    name: str
    start: int
    end: int

    @property
    def size(self) -> int:
        return self.end - self.start


class AbstractDataReader:
    """Stateless, range-addressable record source."""

    #: True when concurrent ``read_records``/``read_records_packed`` calls
    #: on DISJOINT ranges of one source are safe from multiple threads —
    #: the opt-in the worker's parallel ingest (data/ingest_pool.py)
    #: requires before splitting a task's range across pool threads.
    #: File-backed readers open a fresh handle per read, so they qualify;
    #: readers holding a shared connection (sqlite tables) do not.
    thread_safe_ranges = False

    def create_shards(self, records_per_shard: int) -> List[Shard]:
        raise NotImplementedError

    def read_records(self, shard: Shard) -> Iterator[bytes]:
        raise NotImplementedError

    def sources(self) -> List[str]:
        """The source names this reader can serve shards for."""
        raise NotImplementedError


def _expand(path_spec: str) -> List[str]:
    """A data path may be a file, a directory, or a glob."""
    if os.path.isdir(path_spec):
        files = sorted(
            os.path.join(path_spec, f) for f in os.listdir(path_spec)
        )
    else:
        files = sorted(glob.glob(path_spec)) or [path_spec]
    missing = [f for f in files if not os.path.isfile(f)]
    if missing:
        raise FileNotFoundError(f"data files not found: {missing}")
    return files


def _range_shards(sizes: Dict[str, int], records_per_shard: int) -> List[Shard]:
    shards = []
    for name, total in sizes.items():
        for start in range(0, total, records_per_shard):
            shards.append(Shard(name, start, min(start + records_per_shard, total)))
    return shards


class RecordIODataReader(AbstractDataReader):
    thread_safe_ranges = True  # per-read file handles; shared offsets index

    def __init__(self, data_path: str, **_):
        self._readers = {p: RecordIOReader(p) for p in _expand(data_path)}

    def create_shards(self, records_per_shard: int) -> List[Shard]:
        sizes = {p: len(r) for p, r in self._readers.items()}
        return _range_shards(sizes, records_per_shard)

    def read_records(self, shard: Shard) -> Iterator[bytes]:
        return self._readers[shard.name].read_range(shard.start, shard.end)

    def read_records_packed(self, shard: Shard):
        """Bulk packed read (data/packed.py) — the worker's ingest hot path
        uses this when a reader offers it; others fall back to
        ``read_records``."""
        return self._readers[shard.name].read_range_packed(
            shard.start, shard.end
        )

    def sources(self) -> List[str]:
        return sorted(self._readers)


class CSVDataReader(AbstractDataReader):
    """Text files, one record per line; ranges address line numbers.

    ``skip_header=True`` drops the first line of each file.  Line offsets are
    indexed once per file (same trade as the recordio scan).
    """

    # Per-read file handles; a cold offsets index built concurrently is an
    # idempotent double-compute (both threads assign equal lists), not a
    # correctness hazard.
    thread_safe_ranges = True

    def __init__(self, data_path: str, skip_header: bool = False, **_):
        self._files = _expand(data_path)
        self._skip = 1 if skip_header else 0
        self._index: Dict[str, List[int]] = {}

    def _offsets(self, path: str) -> List[int]:
        if path not in self._index:
            offsets = []
            with open(path, "rb") as f:
                pos = f.tell()
                for line in f:
                    offsets.append(pos)
                    pos += len(line)
            self._index[path] = offsets[self._skip :]
        return self._index[path]

    def create_shards(self, records_per_shard: int) -> List[Shard]:
        sizes = {p: len(self._offsets(p)) for p in self._files}
        return _range_shards(sizes, records_per_shard)

    def read_records(self, shard: Shard) -> Iterator[bytes]:
        offsets = self._offsets(shard.name)
        # Clamp like the recordio reader: an over-long range must not yield
        # phantom empty records past EOF.
        end = min(shard.end, len(offsets))
        if shard.start >= end:
            return
        with open(shard.name, "rb") as f:
            f.seek(offsets[shard.start])
            for _ in range(end - shard.start):
                yield f.readline().rstrip(b"\r\n")

    def read_records_packed(self, shard: Shard):
        """One bulk read + C-level newline split instead of a readline loop
        (data/packed.py: the per-record interpreter overhead rivals the
        device step at recommendation batch sizes)."""
        from elasticdl_tpu.data.packed import PackedRecords

        offsets = self._offsets(shard.name)
        n = min(shard.end, len(offsets)) - shard.start
        if n <= 0:
            import numpy as np

            return PackedRecords(
                np.empty((0,), np.uint8), np.zeros((1,), np.int64)
            )
        with open(shard.name, "rb") as f:
            f.seek(offsets[shard.start])
            end = (
                offsets[shard.end]
                if shard.end < len(offsets)
                else os.path.getsize(shard.name)
            )
            span = f.read(end - offsets[shard.start])
        lines = span.split(b"\n")[:n]
        return PackedRecords.from_records([l.rstrip(b"\r\n") for l in lines])

    def sources(self) -> List[str]:
        return list(self._files)


class CompositeDataReader(AbstractDataReader):
    """Routes shards by source name across several readers.

    A worker serves training AND evaluation (and prediction) tasks from one
    task queue, but those tasks' shards name files from different datasets;
    this reader dispatches each shard to the reader that owns its source.
    """

    def __init__(self, readers: List[AbstractDataReader]):
        self._readers = list(readers)
        self._by_source: Dict[str, AbstractDataReader] = {}
        for reader in self._readers:
            for source in reader.sources():
                self._by_source[source] = reader
        # Parallel range reads are only safe when EVERY routed reader is.
        self.thread_safe_ranges = all(
            getattr(r, "thread_safe_ranges", False) for r in self._readers
        )

    def create_shards(self, records_per_shard: int) -> List[Shard]:
        return [
            s for r in self._readers for s in r.create_shards(records_per_shard)
        ]

    def read_records(self, shard: Shard) -> Iterator[bytes]:
        reader = self._by_source.get(shard.name)
        if reader is None:
            raise KeyError(f"no reader serves source {shard.name!r}")
        return reader.read_records(shard)

    def read_records_packed(self, shard: Shard):
        """Forward the packed fast path when the owning reader has one,
        else None (the worker then uses ``read_records``)."""
        reader = self._by_source.get(shard.name)
        if reader is None:
            raise KeyError(f"no reader serves source {shard.name!r}")
        fast = getattr(reader, "read_records_packed", None)
        return fast(shard) if fast is not None else None

    def sources(self) -> List[str]:
        return sorted(self._by_source)


def _split_table_path(data_path: str) -> tuple:
    """Split ``db.sqlite#tablename`` — but only when the full string isn't
    itself an existing path, so filenames containing '#' keep working."""
    if os.path.exists(data_path):
        return data_path, ""
    path, _, table = data_path.partition("#")
    return path, table


def _make_table_reader(data_path: str, **params) -> AbstractDataReader:
    from elasticdl_tpu.data.table import TableDataReader

    path, table = _split_table_path(data_path)
    if table:
        params.setdefault("table", table)
    files = _expand(path)
    if len(files) == 1:
        return TableDataReader(files[0], **params)
    # A directory/glob of database files: one reader per file, routed by
    # shard name (each table reader's source is "<file>#<table>").
    return CompositeDataReader([TableDataReader(f, **params) for f in files])


_READERS = {
    "recordio": RecordIODataReader,
    "csv": CSVDataReader,
    "text": CSVDataReader,
    "table": _make_table_reader,  # ODPS-table parity (SQLite-backed)
    "sqlite": _make_table_reader,
}


def create_data_reader(
    data_path: str, reader_params: Optional[dict] = None
) -> AbstractDataReader:
    """Build a reader for ``data_path``.

    ``reader_params`` (the config's ``--data_reader_params``) may carry
    ``format=recordio|csv|table`` plus reader kwargs; default is sniffed
    from the first file's magic bytes.
    """
    params = dict(reader_params or {})
    fmt = params.pop("format", None)
    if fmt is None:
        first = _expand(_split_table_path(data_path)[0])[0]
        with open(first, "rb") as f:
            from elasticdl_tpu.data.recordio import MAGIC
            from elasticdl_tpu.data.table import SQLITE_MAGIC

            head = f.read(max(len(MAGIC), len(SQLITE_MAGIC)))
        if head.startswith(MAGIC):
            fmt = "recordio"
        elif head.startswith(SQLITE_MAGIC):
            fmt = "table"
        else:
            fmt = "csv"
    if fmt not in _READERS:
        raise ValueError(f"unknown data format {fmt!r}, pick from {sorted(_READERS)}")
    return _READERS[fmt](data_path, **params)
