"""Bounded thread pool for intra-task parallel shard ingest.

The r5 stage table (artifacts/ingest_stages_r05.json) pins the e2e bound on
single-threaded host read+decode: ~574k examples/sec against a 910k
device-step ceiling — the chip idles ~2/3 of each task waiting on one
host core.  The codec stack is embarrassingly parallel WITHIN a task: the
recordio bulk read, the C++ CRC check, and the C++ criteo decode all
release the GIL, and every record decodes independently of its neighbors.
This module owns the sub-task parallelism:

- ``plan_chunks`` splits a shard's record range into contiguous sub-ranges
  whose interior boundaries are minibatch-aligned, so per-chunk feeds
  reshape to ``[t_i, mb, ...]`` stacks that concatenate — in chunk order —
  into exactly the bytes the serial path produces (record order, ragged
  tail, and ``__mask__`` semantics are untouched; pinned by tests).
- ``IngestPool`` runs the chunk decodes on a bounded
  ``ThreadPoolExecutor`` (workers named ``edl-ingest_*`` so thread dumps
  and locksan reports attribute ingest work) and reassembles results in
  submission order.

The reference gets this for free from tf.data's threaded C++ pipeline
(SURVEY.md §2 #14); ElasWave (PAPERS.md) makes the same keep-the-
accelerator-fed point for elastic fleets.  Pure stdlib — this module must
stay importable by jax-free processes (graftlint import-hygiene).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Sequence, Tuple, TypeVar

_T = TypeVar("_T")
_R = TypeVar("_R")

#: Auto mode (``ingest_threads=0``) resolves to this many threads at most:
#: past ~4 the decode stops being the task bound (the chunk split also
#: bottoms out at one minibatch per chunk) and extra threads only fight
#: the trainer for cores.
AUTO_THREADS_CAP = 4


def resolve_threads(requested: int) -> int:
    """The pool width a request resolves to: explicit positive values are
    taken as-is; 0 (auto) uses the host's cores up to AUTO_THREADS_CAP."""
    if requested > 0:
        return requested
    return max(1, min(AUTO_THREADS_CAP, os.cpu_count() or 1))


def plan_chunks(
    start: int, end: int, minibatch: int, threads: int
) -> List[Tuple[int, int]]:
    """Split record range ``[start, end)`` into up to ``threads`` contiguous
    sub-ranges covering it exactly, every interior boundary a multiple of
    ``minibatch`` records from ``start``.  The ragged tail (records past
    the last full minibatch) rides the LAST chunk, so only that chunk can
    produce leftover records — reassembly stays a plain ordered concat.
    Fewer than 2 full minibatches (nothing to split) or ``threads <= 1``
    returns the whole range as one chunk."""
    n = max(0, end - start)
    n_full = n // minibatch if minibatch > 0 else 0
    if threads <= 1 or n_full < 2:
        return [(start, end)]
    k = min(threads, n_full)
    per = -(-n_full // k)  # ceil: minibatches per chunk
    chunks: List[Tuple[int, int]] = []
    i = 0
    while i < n_full:
        j = min(i + per, n_full)
        chunks.append((start + i * minibatch, start + j * minibatch))
        i = j
    if end > chunks[-1][1]:  # ragged tail -> last chunk
        chunks[-1] = (chunks[-1][0], end)
    return chunks


class IngestPool:
    """Bounded worker pool for parallel chunk decode, results in order.

    One instance per worker process, shared by every concurrent task prep
    (the k-deep prep pipeline submits chunk work from its own prep
    threads; chunks from different tasks interleave freely on the pool —
    per-task order is preserved by each ``map_ordered`` call's futures).
    ``threads <= 1`` degrades to inline serial execution with no pool at
    all, so the serial path stays byte-for-byte the pre-r9 code path.
    """

    def __init__(self, threads: int = 0):
        self.threads = resolve_threads(threads)
        self._pool = (
            ThreadPoolExecutor(
                max_workers=self.threads, thread_name_prefix="edl-ingest"
            )
            if self.threads > 1
            else None
        )

    @property
    def parallel(self) -> bool:
        return self._pool is not None

    # hot-path: submission only — the decode runs on the pool threads
    def submit(self, fn: Callable[..., _R], *args):
        """Submit one unit of ingest work; returns a Future.  Callers on
        the task loop must not block on the result outside an accounted
        phase boundary."""
        if self._pool is None:
            raise RuntimeError("IngestPool is serial (threads <= 1)")
        return self._pool.submit(fn, *args)

    def map_ordered(
        self, fn: Callable[[_T], _R], items: Sequence[_T]
    ) -> List[_R]:
        """Run ``fn`` over ``items`` concurrently, returning results in
        input order (the property chunk reassembly depends on).  Runs
        inline when the pool is serial or there is nothing to overlap.
        Blocks until every item completes — call from prep/worker threads,
        not from the task loop's dispatch path."""
        if self._pool is None or len(items) < 2:
            return [fn(it) for it in items]
        futures = [self._pool.submit(fn, it) for it in items]
        # .result() re-raises the first chunk failure; later futures still
        # run to completion on the bounded pool (no leak, no orphan).
        return [f.result() for f in futures]

    def shutdown(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
