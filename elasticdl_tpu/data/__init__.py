"""Data layer: shardable readers + the record->batch feed path.

Reference parity (SURVEY.md §2 #14, upstream layout [U — mount empty at
survey time]): ``AbstractDataReader`` with ``create_shards()`` /
``read_records(task)``, implemented for RecordIO files, ODPS tables and CSV
text.  Here: a recordio-style length-prefixed binary format, CSV/text lines,
and synthetic generators (ODPS is cloud-SDK-gated in the reference and out of
an offline TPU image's scope — the reader ABC is the extension point).

Records cross the reader as ``bytes``; each model-zoo module exports a
``feed`` that vectorizes records into device-ready arrays (the reference's
``feed``/``dataset_fn`` role).
"""

from elasticdl_tpu.data.reader import (  # noqa: F401
    AbstractDataReader,
    CSVDataReader,
    RecordIODataReader,
    Shard,
    create_data_reader,
)
from elasticdl_tpu.data.recordio import RecordIOReader, RecordIOWriter  # noqa: F401
