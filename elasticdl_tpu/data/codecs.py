"""Record codecs: serialized example <-> device-ready numpy batches.

The encode side is used by the synthetic-data generators and tests; the feed
(decode) side is each model's ``ModelSpec.feed`` (the reference's
``feed``/``dataset_fn`` role).  Formats mirror the real datasets' canonical
shapes so a user can point the readers at actual MNIST/Criteo/Census dumps:

- mnist/cifar10: raw little-endian bytes, image uint8s then one label byte
  (recordio payloads).
- criteo: the Kaggle TSV — ``label\\t13 ints\\t26 hex cat ids`` with blanks
  allowed (missing values).
- census: CSV — ``label,5 numerics,9 categorical strings``.

String categoricals are mapped to stable int ids host-side by the
preprocessing Hashing layer (32-bit FNV-1a, elasticdl_tpu/preprocessing);
the model re-buckets them on device (models/tabular.py), matching the
reference's Hashing-preprocessing-then-Embedding pipeline.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from elasticdl_tpu.data.packed import as_packed, concat_records

# ---------------- image families ----------------


def encode_image_example(image: np.ndarray, label: int) -> bytes:
    return np.ascontiguousarray(image, dtype=np.uint8).tobytes() + bytes([label])


def _image_feed(records: Sequence[bytes], shape) -> dict:
    n = int(np.prod(shape))
    buf = concat_records(records).reshape(-1, n + 1)
    images = buf[:, :n].reshape((-1,) + shape).astype(np.float32) / 255.0
    labels = buf[:, n].astype(np.int32)
    return {"images": images, "labels": labels}


def mnist_feed(records: Sequence[bytes]) -> dict:
    return _image_feed(records, (28, 28, 1))


def cifar10_feed(records: Sequence[bytes]) -> dict:
    return _image_feed(records, (32, 32, 3))


# ---------------- criteo (deepfm) ----------------

_CRITEO_DENSE = 13
_CRITEO_CAT = 26


def encode_criteo_example(
    label: int, dense: Sequence[float], cats: Sequence[int]
) -> bytes:
    fields = [str(label)]
    fields += ["" if d is None else str(int(d)) for d in dense]
    fields += ["%08x" % (c & 0xFFFFFFFF) for c in cats]
    return "\t".join(fields).encode()


def criteo_feed(records: Sequence[bytes]) -> dict:
    """Criteo TSV -> batch.  Hot path: the C++ decoder (~0.3 us/record)
    over the packed buffer; the Python loop below is the semantic source of
    truth and the fallback when the native lib is unavailable (measured 692
    ms per 8192 records — 80x the device step, hence the native path;
    numerics equality is pinned by tests/test_data.py)."""
    try:
        from elasticdl_tpu.ps.host_store import criteo_decode_native

        packed = as_packed(records)
        labels, dense, cat = criteo_decode_native(packed.buf, packed.offsets)
        return {"dense": dense, "cat": cat, "labels": labels}
    except (RuntimeError, ImportError):
        pass
    n = len(records)
    dense = np.zeros((n, _CRITEO_DENSE), np.float32)
    cat = np.zeros((n, _CRITEO_CAT), np.int32)
    labels = np.zeros((n,), np.int32)
    for i, rec in enumerate(records):
        parts = rec.decode().split("\t")
        labels[i] = int(parts[0])
        for j, v in enumerate(parts[1 : 1 + _CRITEO_DENSE]):
            dense[i, j] = float(v) if v else 0.0
        for j, v in enumerate(parts[1 + _CRITEO_DENSE :]):
            cat[i, j] = np.int32(np.uint32(int(v, 16))) if v else 0
    return {"dense": dense, "cat": cat, "labels": labels}


def criteo_feed_pre(records: Sequence[bytes], buckets: int) -> dict:
    """Criteo TSV -> PREPROCESSED batch: the DeepFM host-side feature
    transforms (models/tabular.py hash_buckets + log_normalize) fused into
    the C++ parse, emitting compact wire dtypes (labels uint8, dense float16
    log1p, cat uint16 bucket ids).  The reference runs its preprocessing
    layers inside the input pipeline the same way (SURVEY.md §2 #15); here
    it also halves host->device bytes, the e2e bottleneck on
    remote-attached chips.  Falls back to the raw feed + numpy transforms
    when the native lib is unavailable (bit-compatible; pinned by tests)."""
    try:
        from elasticdl_tpu.ps.host_store import criteo_decode_pre_native

        packed = as_packed(records)
        labels, dense, cat = criteo_decode_pre_native(
            packed.buf, packed.offsets, buckets
        )
        return {"dense": dense, "cat": cat, "labels": labels}
    except (RuntimeError, ImportError):
        raw = criteo_feed(records)
        h = raw["cat"].astype(np.uint32) * np.uint32(2654435761)
        h ^= h >> np.uint32(16)
        return {
            "dense": np.log1p(np.maximum(raw["dense"], 0.0)).astype(np.float16),
            "cat": (h % np.uint32(buckets)).astype(np.uint16),
            "labels": raw["labels"].astype(np.uint8),
        }


# ---------------- census (wide&deep) ----------------

_CENSUS_DENSE = 5
_CENSUS_CAT = 9


def encode_census_example(
    label: int, dense: Sequence[float], cats: Sequence[str]
) -> bytes:
    fields = [str(label)] + [str(float(d)) for d in dense] + list(cats)
    return ",".join(fields).encode()


def census_feed(records: Sequence[bytes]) -> dict:
    """Census CSV -> batch, via the preprocessing layers (the reference feeds
    census through elasticdl_preprocessing hashing/number layers the same
    way; SURVEY.md §2 #15).  String categoricals are hashed host-side into a
    31-bit id space; the model re-buckets them on device.  Hot path: the C++
    decoder (same ToNumber/Hashing semantics, pinned by tests); the layer
    pipeline below is the source of truth and fallback."""
    try:
        from elasticdl_tpu.ps.host_store import census_decode_native

        packed = as_packed(records)
        labels, dense, cat = census_decode_native(
            packed.buf, packed.offsets, 1 << 31
        )
        return {"dense": dense, "cat": cat, "labels": labels}
    except (RuntimeError, ImportError):
        pass
    from elasticdl_tpu.preprocessing import Hashing, ToNumber

    to_number = ToNumber(out_dtype="float32", default=0.0)
    hashing = Hashing(1 << 31)
    n = len(records)
    dense_raw = np.empty((n, _CENSUS_DENSE), object)
    cat_raw = np.empty((n, _CENSUS_CAT), object)
    labels = np.zeros((n,), np.int32)
    for i, rec in enumerate(records):
        parts = rec.decode().split(",")
        labels[i] = int(parts[0])
        dense_raw[i] = parts[1 : 1 + _CENSUS_DENSE]
        cat_raw[i] = [v.strip() for v in parts[1 + _CENSUS_DENSE :]]
    return {
        "dense": to_number(dense_raw),
        "cat": hashing(cat_raw).astype(np.int32),
        "labels": labels,
    }


# ---------------- language modeling (transformer_lm) ----------------


def encode_lm_example(tokens: np.ndarray) -> bytes:
    """One training sequence of S+1 int32 token ids (the +1 supplies the
    next-token labels; the feed splits tokens[:-1] / tokens[1:], so the
    label shift never crosses a sequence-parallel shard boundary)."""
    return np.ascontiguousarray(tokens, np.int32).tobytes()


def lm_feed(records: Sequence[bytes]) -> dict:
    buf = concat_records(records).view(np.int32)
    seq_plus_1 = len(records[0]) // 4
    seqs = buf.reshape(len(records), seq_plus_1)
    return {
        "tokens": np.ascontiguousarray(seqs[:, :-1]),
        "labels": np.ascontiguousarray(seqs[:, 1:]),
    }
