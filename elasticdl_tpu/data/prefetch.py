"""Background prefetch of decoded batches.

The worker's train loop is a strict alternation without this: decode batch n
on the host, then run the device step, host idle while the TPU computes.  A
single background thread pulling the decode generator into a bounded queue
overlaps the two — the decode work (numpy + the C++ codec + file reads, all
GIL-releasing) runs while the device step is in flight, which is the whole
reason the reference routes ingest through tf.data's threaded C++ pipeline
(SURVEY.md §2 #14, §3.3).  Depth bounds host memory: at most ``depth``
decoded batches exist beyond the one being consumed.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

_DONE = object()


class _Failure:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch(
    iterable: Iterable, depth: int = 2, name: str = "edl-prefetch"
) -> Iterator:
    """Iterate ``iterable`` on a daemon thread, keeping up to ``depth`` items
    decoded ahead.  Exceptions raised by the producer re-raise at the
    consumer's next pull (fail-loud: a malformed record must kill the task,
    not vanish into a thread).  ``depth < 1`` returns the iterable unchanged.
    ``name`` labels the producer thread (the worker passes
    ``prefetch:<task_id>``) so thread dumps and locksan reports attribute
    ingest threads to the task that owns them.

    A consumer that abandons iteration early (task failure mid-shard)
    cancels the producer: the generator's close/GC sets the cancel event,
    and the producer — which only ever blocks on the queue with a short
    timeout — notices and exits, dropping its buffered batches.  Without
    that, every abandoned task would pin a thread plus ``depth`` decoded
    batches forever.
    """
    if depth < 1:
        return iter(iterable)
    q: queue.Queue = queue.Queue(maxsize=depth)
    cancelled = threading.Event()

    def _put(item) -> bool:
        while not cancelled.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce() -> None:
        try:
            for item in iterable:
                if not _put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — transported to consumer
            _put(_Failure(e))
            return
        _put(_DONE)

    def _consume() -> Iterator:
        # Lazy start (ADVICE r4 #1): a generator abandoned before its first
        # next() never executes its body, so its finally never runs — an
        # eagerly started producer would then spin on 0.1 s put-retries
        # forever, pinning ``depth`` decoded batches.  Starting the thread
        # on the first pull means no pull, no thread, no leak.
        threading.Thread(target=_produce, name=name, daemon=True).start()
        try:
            while True:
                item = q.get()
                if item is _DONE:
                    return
                if isinstance(item, _Failure):
                    raise item.exc
                yield item
        finally:
            cancelled.set()

    return _consume()
