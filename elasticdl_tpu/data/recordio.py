"""A minimal recordio-style container: the reference stores training data in
RecordIO files whose numbered records make range-sharding natural (SURVEY.md
§2 #14 [U]).  Format, per record:

    [uint32 payload_len][uint32 crc32(payload)][payload bytes]

little-endian, no compression.  Files carry a 8-byte magic header.  A sidecar
index is NOT required: ``RecordIOReader.index()`` scans once and caches record
offsets, so shard handout (record ranges) and ranged reads are O(1) after the
first scan.  A C++ scanner for the hot ingest path lives in
``elasticdl_tpu/ps/native`` (built lazily; this module is the pure-Python
fallback and the format's source of truth).
"""

from __future__ import annotations

import os
import struct
import zlib
from collections import OrderedDict
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from elasticdl_tpu.common import locksan

MAGIC = b"EDLRIO\x00\x01"
_HDR = struct.Struct("<II")

#: Process-level offsets cache, keyed by ``(path, mtime_ns, size)``: the
#: e2e worker re-opens the same file once per task (and, since r9, once
#: per parallel ingest chunk), and every fresh ``RecordIOReader`` used to
#: pay the full index scan again.  Keying on mtime+size means an appended
#: or rewritten file can never serve a stale index — its old entry just
#: ages out.  Bounded LRU; offsets lists are append-only after insertion
#: (readers treat them as immutable), so sharing one list across reader
#: instances and threads is safe.
_INDEX_CACHE: "OrderedDict[Tuple[str, int, int], List[int]]" = OrderedDict()
_INDEX_CACHE_MAX = 64
_index_cache_lock = locksan.lock("_index_cache_lock", leaf=True)  # lock-order: leaf


class RecordIOWriter:
    def __init__(self, path: str):
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self._count = 0

    def write(self, payload: bytes) -> None:
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self._count += 1

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> "RecordIOWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def count(self) -> int:
        return self._count


class RecordIOReader:
    def __init__(self, path: str):
        self.path = path
        self._offsets: Optional[List[int]] = None
        with open(path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                raise ValueError(f"{path}: not a recordio file")

    def index(self) -> List[int]:
        """Byte offset of each record (one-time scan, shared process-wide
        through the ``(path, mtime, size)``-keyed cache — sub-chunk readers
        and per-task reader instances must not re-scan the same bytes)."""
        if self._offsets is None:
            st = os.stat(self.path)
            key = (self.path, st.st_mtime_ns, st.st_size)
            with _index_cache_lock:
                cached = _INDEX_CACHE.get(key)
                if cached is not None:
                    _INDEX_CACHE.move_to_end(key)
            if cached is not None:
                self._offsets = cached
                return cached
            offsets = []
            size = st.st_size
            with open(self.path, "rb") as f:
                pos = len(MAGIC)
                while pos < size:
                    offsets.append(pos)
                    f.seek(pos)
                    length, _ = _HDR.unpack(f.read(_HDR.size))
                    pos += _HDR.size + length
            with _index_cache_lock:
                _INDEX_CACHE[key] = offsets
                _INDEX_CACHE.move_to_end(key)
                while len(_INDEX_CACHE) > _INDEX_CACHE_MAX:
                    _INDEX_CACHE.popitem(last=False)
            self._offsets = offsets
        return self._offsets

    def __len__(self) -> int:
        return len(self.index())

    def read_range(self, start: int, end: int) -> Iterator[bytes]:
        """Yield records [start, end) by record index, CRC-checked."""
        offsets = self.index()
        end = min(end, len(offsets))
        if start >= end:
            return
        with open(self.path, "rb") as f:
            f.seek(offsets[start])
            for _ in range(end - start):
                length, crc = _HDR.unpack(f.read(_HDR.size))
                payload = f.read(length)
                if zlib.crc32(payload) != crc:
                    raise IOError(f"{self.path}: CRC mismatch")
                yield payload

    def read_range_packed(self, start: int, end: int):
        """Records [start, end) as one PackedRecords (bulk C++ read + CRC on
        the ingest hot path; Python fallback when the native lib is absent).
        See data/packed.py for why the hot path avoids per-record objects."""
        from elasticdl_tpu.data.packed import PackedRecords

        offsets = self.index()
        end = min(end, len(offsets))
        if start >= end:
            return PackedRecords(
                np.empty((0,), np.uint8), np.zeros((1,), np.int64)
            )
        try:
            from elasticdl_tpu.ps.host_store import recordio_read_native

            buf, cum = recordio_read_native(
                self.path,
                np.asarray(offsets, np.int64),
                start,
                end,
                os.path.getsize(self.path),
            )
            return PackedRecords(buf, cum)
        except (RuntimeError, ImportError):
            return PackedRecords.from_records(list(self.read_range(start, end)))


def write_records(path: str, records: Sequence[bytes]) -> int:
    with RecordIOWriter(path) as w:
        for r in records:
            w.write(r)
        return w.count
