"""Table data reader — the ODPS/MaxCompute-table parity path.

Reference parity (SURVEY.md §2 #14 [U — mount empty at survey time]): the
reference ships an ODPS table reader next to RecordIO/CSV — a columnar
source addressed by (table, start-row, end-row) ranges with optional column
selection, exactly the shape its dynamic sharding needs.  The rebuild keeps
the same contract against SQLite (stdlib, zero deps): a local ``.db`` file
stands in for the remote table service, rows are addressed by rank (dense
``rowid`` order), and selected columns are serialized to CSV bytes so the
model-zoo ``feed`` functions parse table records and file records
identically.  Swapping in a real remote table service later only means
reimplementing this class's two methods.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Sequence

from elasticdl_tpu.data.reader import AbstractDataReader, Shard, _range_shards

SQLITE_MAGIC = b"SQLite format 3\x00"


def _connect(path: str) -> sqlite3.Connection:
    # URI mode=ro keeps workers from ever locking the table for writers.
    conn = sqlite3.connect(f"file:{path}?mode=ro", uri=True, check_same_thread=False)
    return conn


class TableDataReader(AbstractDataReader):
    """Range-addressable rows of one SQLite table.

    ``data_path`` is the database file.  ``table`` defaults to the single
    table in the file (error if ambiguous).  ``columns`` selects/orders the
    fields serialized into each record (default: schema order).
    ``delimiter`` joins fields (default ``,`` to match the CSV feeds).

    Shard names are ``<path>#<table>`` so a CompositeDataReader can route
    between several tables (or tables and files) in one job.
    """

    def __init__(
        self,
        data_path: str,
        table: str = "",
        columns: Optional[Sequence[str]] = None,
        delimiter: str = ",",
        **_,
    ):
        if not os.path.isfile(data_path):
            raise FileNotFoundError(f"table database not found: {data_path}")
        self._path = data_path
        self._delim = delimiter
        # One connection per thread: workers read shards from executor threads.
        self._local = threading.local()
        conn = self._conn()
        tables = [
            r[0]
            for r in conn.execute(
                "SELECT name FROM sqlite_master WHERE type='table' "
                "AND name NOT LIKE 'sqlite_%' ORDER BY name"
            )
        ]
        if not tables:
            raise ValueError(f"{data_path}: no tables")
        if table:
            if table not in tables:
                raise ValueError(
                    f"{data_path}: no table {table!r} (has {tables})"
                )
            self._table = table
        elif len(tables) == 1:
            self._table = tables[0]
        else:
            raise ValueError(
                f"{data_path} holds several tables {tables}; pass "
                "data_reader_params 'table=...'"
            )
        schema = [r[1] for r in conn.execute(f'PRAGMA table_info("{self._table}")')]
        if columns:
            unknown = [c for c in columns if c not in schema]
            if unknown:
                raise ValueError(f"unknown columns {unknown} (schema: {schema})")
            self._columns = list(columns)
        else:
            self._columns = schema
        count, lo, hi = conn.execute(
            f'SELECT COUNT(*), MIN(rowid), MAX(rowid) FROM "{self._table}"'
        ).fetchone()
        self._count = count
        # Dense rowids (no deletions) let shards read via an index-backed
        # rowid BETWEEN — O(log n + rows) instead of OFFSET's O(start) skip
        # walk, which would make a full epoch quadratic in table size.
        self._dense_rowids = count > 0 and (hi - lo + 1 == count)
        self._rowid_base = lo if self._dense_rowids else 0

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = _connect(self._path)
            self._local.conn = conn
        return conn

    @property
    def source_name(self) -> str:
        return f"{self._path}#{self._table}"

    def create_shards(self, records_per_shard: int) -> List[Shard]:
        return _range_shards({self.source_name: self._count}, records_per_shard)

    def read_records(self, shard: Shard) -> Iterator[bytes]:
        cols = ", ".join(f'"{c}"' for c in self._columns)
        if self._dense_rowids:
            # Index-backed seek: rank r lives at rowid base+r.
            rows = self._conn().execute(
                f'SELECT {cols} FROM "{self._table}" '
                "WHERE rowid BETWEEN ? AND ? ORDER BY rowid",
                (self._rowid_base + shard.start, self._rowid_base + shard.end - 1),
            )
        else:
            # Sparse rowids (table had deletions): fall back to OFFSET
            # pagination, which scans past `start` rows.
            rows = self._conn().execute(
                f'SELECT {cols} FROM "{self._table}" ORDER BY rowid '
                "LIMIT ? OFFSET ?",
                (shard.end - shard.start, shard.start),
            )
        for row in rows:
            yield self._delim.join(
                "" if v is None else str(v) for v in row
            ).encode()

    def sources(self) -> List[str]:
        return [self.source_name]


def write_table(
    path: str,
    rows: Sequence[Sequence],
    columns: Sequence[str],
    table: str = "records",
) -> None:
    """Create/replace a table from rows — test fixtures and the synthetic
    data generators' table flavor."""
    conn = sqlite3.connect(path)
    try:
        cols = ", ".join(f'"{c}"' for c in columns)
        conn.execute(f'DROP TABLE IF EXISTS "{table}"')
        conn.execute(f'CREATE TABLE "{table}" ({cols})')
        marks = ", ".join("?" for _ in columns)
        conn.executemany(f'INSERT INTO "{table}" VALUES ({marks})', rows)
        conn.commit()
    finally:
        conn.close()
