"""Synthetic dataset generators for the four BASELINE model families.

Used by tests, the chaos/integration suite, and ``elasticdl train`` dry runs
when no real dataset is mounted (this image has no network).  Labels are
generated from a hidden linear rule so models can demonstrably learn.
"""

from __future__ import annotations

import os

import numpy as np

from elasticdl_tpu.data import codecs
from elasticdl_tpu.data.recordio import RecordIOWriter


def synthetic_mnist(path: str, n: int, seed: int = 0) -> str:
    rng = np.random.default_rng(seed)
    with RecordIOWriter(path) as w:
        for _ in range(n):
            label = int(rng.integers(0, 10))
            img = rng.integers(0, 256, (28, 28, 1), dtype=np.uint8)
            # Stamp a label-dependent bright block so the task is learnable.
            r, c = divmod(label, 4)
            img[4 + r * 6 : 8 + r * 6, 4 + c * 6 : 8 + c * 6] = 255
            w.write(codecs.encode_image_example(img, label))
    return path


def synthetic_cifar10(path: str, n: int, seed: int = 0) -> str:
    rng = np.random.default_rng(seed)
    with RecordIOWriter(path) as w:
        for _ in range(n):
            label = int(rng.integers(0, 10))
            img = rng.integers(0, 256, (32, 32, 3), dtype=np.uint8)
            img[:, :, label % 3] = np.minimum(255, img[:, :, label % 3] + 25 * label)
            w.write(codecs.encode_image_example(img, label))
    return path


def synthetic_criteo(
    path: str, n: int, seed: int = 0, container: str = "text"
) -> str:
    """Criteo-Kaggle-shaped TSV with a planted CTR rule.

    ``container="text"`` writes newline-delimited TSV (the Kaggle dump's own
    shape); ``"recordio"`` wraps each line in the recordio framing the
    reference stores training data in (SURVEY.md §2 #14) — the e2e bench
    uses this to exercise the native bulk-read path.
    """
    rng = np.random.default_rng(seed)
    sink = RecordIOWriter(path) if container == "recordio" else open(path, "wb")
    with sink as out:
        for _ in range(n):
            dense = rng.integers(0, 1000, 13)
            cats = rng.integers(0, 1 << 20, 26)
            score = 0.002 * dense[0] - 0.001 * dense[1] + ((cats[0] % 7) - 3) * 0.3
            label = int(rng.random() < 1 / (1 + np.exp(-score)))
            rec = codecs.encode_criteo_example(label, dense.tolist(), cats.tolist())
            if container == "recordio":
                out.write(rec)
            else:
                out.write(rec + b"\n")
    return path


_CENSUS_VOCAB = [
    ["private", "gov", "self_emp", "none"],
    ["hs", "college", "bachelors", "masters", "phd"],
    ["married", "single", "divorced"],
    ["tech", "sales", "admin", "exec", "service"],
    ["husband", "wife", "own_child", "unmarried"],
    ["white", "black", "asian", "other"],
    ["male", "female"],
    ["us", "mexico", "other"],
    ["a", "b", "c"],
]


def synthetic_census(path: str, n: int, seed: int = 0) -> str:
    rng = np.random.default_rng(seed)
    with open(path, "wb") as f:
        for _ in range(n):
            dense = [
                float(rng.integers(17, 80)),  # age
                float(rng.integers(1, 16)),  # education_num
                float(rng.choice([0, 0, 0, 5000, 15000])),  # capital_gain
                float(rng.choice([0, 0, 0, 1500])),  # capital_loss
                float(rng.integers(10, 80)),  # hours_per_week
            ]
            cats = [v[rng.integers(0, len(v))] for v in _CENSUS_VOCAB]
            score = (
                0.04 * (dense[0] - 40)
                + 0.3 * (dense[1] - 9)
                + 0.0002 * dense[2]
                + (1.0 if cats[2] == "married" else -0.5)
            )
            label = int(rng.random() < 1 / (1 + np.exp(-score)))
            f.write(codecs.encode_census_example(label, dense, cats))
            f.write(b"\n")
    return path


def synthetic_lm(
    path: str, n: int, seed: int = 0, seq_len: int = 256, vocab: int = 8192
) -> str:
    """Token sequences from a noisy affine next-token rule, so a causal LM
    demonstrably learns (loss falls well below uniform log-vocab)."""
    rng = np.random.default_rng(seed)
    # Vectorized across records: one RNG draw per position for all n
    # sequences (a per-token Python loop costs minutes at dataset scale).
    toks = np.empty((n, seq_len + 1), np.int32)
    toks[:, 0] = rng.integers(0, vocab, size=n)
    for t in range(1, seq_len + 1):
        noise = rng.random(n) < 0.1  # 10% noise keeps entropy positive
        toks[:, t] = np.where(
            noise,
            rng.integers(0, vocab, size=n),
            (toks[:, t - 1] * 31 + 7) % vocab,
        )
    with RecordIOWriter(path) as w:
        for i in range(n):
            w.write(codecs.encode_lm_example(toks[i]))
    return path


_GENERATORS = {
    "mnist": synthetic_mnist,
    "cifar10": synthetic_cifar10,
    "criteo": synthetic_criteo,
    "census": synthetic_census,
    "lm": synthetic_lm,
}


def generate(family: str, path: str, n: int, seed: int = 0, **kwargs) -> str:
    if family not in _GENERATORS:
        raise ValueError(f"unknown family {family!r}, pick from {sorted(_GENERATORS)}")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    return _GENERATORS[family](path, n, seed, **kwargs)
