"""Fleet-wide live metrics: the master's aggregated view + the
goodput/SLO computer.

Workers and PS shards ship their ``gauge.Registry.snapshot()`` on the
heartbeat/report channel (the additive ``gauge`` envelope, common/rpc.py
— same carrier as the r12 trace slices).  This module banks those
snapshots per worker and turns them, at SCRAPE time, into the numbers
the paper's elastic design is judged on while the job still runs:

- **fleet examples/sec** — summed per-worker rate over a sliding window
  of each worker's cumulative ``edl_examples_trained_total`` (restart-
  tolerant: a counter that went backwards re-anchors its worker);
- **goodput-under-churn** — the live twin of ``chaos_bench``'s stamped
  ratio.  The bench divides a faulted run's examples/sec by a
  shape-matched fault-free baseline; a live job has no parallel
  baseline, so the stand-in denominator is the PEAK windowed rate this
  very job has sustained (``edl_fleet_examples_per_sec_peak``) — during
  a kill/stall the ratio dips exactly as the bench's does, and a healthy
  steady state reads ~1.0.  When a committed device-ceiling record is
  readable (``bench.py``'s artifact), ``edl_goodput_vs_ceiling`` is
  stamped beside it — the "live examples/sec vs the device-ceiling
  record" view;
- **per-rank gang-arrival lag** — seconds each rank trails the gang
  head's lockstep arrival (the r13 deadline's own signal, read live
  instead of post-hoc from a skip event);
- **gang-wait share** — each worker's ``lease_wait`` share of its
  critical-path seconds (from the banked PhaseTimers snapshots): the
  straggler-report skew input, as a live gauge.

Everything here is PULL-model: ``record_envelope`` (the hot-path side)
is a dict assignment + one RateWindow append; all aggregation runs in
the registry collector at scrape/snapshot time — the split the
``gauge-discipline`` lint rule enforces.

jax-free (the master control plane contract).
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Optional

from elasticdl_tpu.common import gauge, locksan
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.metrics import critical_path_seconds

logger = get_logger("master.fleet_metrics")

#: Where the committed bench records live (best-effort; absent on a
#: deployed master, present in the repo checkout the benches run from).
#: ``device_step_examples_per_sec_per_chip`` is bench.py's measured
#: device ceiling — the denominator of the e2e-vs-ceiling story in
#: docs/perf.md.
ARTIFACTS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "artifacts",
)

_BENCH_REV = re.compile(r"^bench_r(\d+)(?:_latest)?\.json$")


def read_device_ceiling(artifacts_dir: str = ARTIFACTS_DIR) -> Optional[float]:
    """The NEWEST committed device-step ceiling (examples/sec/chip), or
    None.  Scans ``bench_r<NN>[_latest].json`` and takes the highest
    revision carrying the key — pinning a filename would silently keep
    dividing by an old record after the next bench round moves the
    ceiling.  Best-effort by design: a live job without the repo's
    artifacts still serves every other family."""
    try:
        names = os.listdir(artifacts_dir)
    except OSError:
        return None
    best: Optional[float] = None
    best_rev = -1
    for name in names:
        m = _BENCH_REV.match(name)
        if not m or int(m.group(1)) < best_rev:
            continue
        try:
            with open(os.path.join(artifacts_dir, name)) as f:
                record = json.load(f)
        except (OSError, ValueError):
            continue
        v = record.get("device_step_examples_per_sec_per_chip")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            rev = int(m.group(1))
            if rev > best_rev or (rev == best_rev and float(v) > best):
                best, best_rev = float(v), rev
    return best


class FleetMetrics:
    """Per-worker envelope bank + the master's scrape-time aggregator.

    ``servicer`` supplies the master-side state (dispatcher counts, gang
    arrivals, phase snapshots, standby depth); the registry the
    collector writes into is ``self.registry`` and the full exposition —
    master families THEN the merged per-worker view — comes from
    ``render()``.
    """

    def __init__(
        self,
        servicer,
        registry: Optional[gauge.Registry] = None,
        window_s: float = 30.0,
        ceiling: Optional[float] = None,
    ):
        self._servicer = servicer
        self.registry = registry or gauge.Registry()
        self.registry.add_collector(self._collect)
        self._lock = locksan.lock("FleetMetrics._lock", leaf=True)  # lock-order: leaf
        # worker_id -> latest families snapshot (remote input: shape-
        # checked at render, never trusted).  Insertion order tracks
        # most-recently-updated (move-to-end on every envelope), which is
        # what the departed-worker bound prunes on.
        self._envelopes: Dict[str, dict] = {}  # guarded-by: _lock
        self._rates = gauge.RateWindow(window_s=window_s)
        self._peak_rate = 0.0  # guarded-by: _lock
        self._ceiling = (
            ceiling if ceiling is not None else read_device_ceiling()
        )

    # -- hot-path side (rides every Heartbeat/Report: bank, never walk) --

    def record_envelope(self, worker_id: str, payload) -> None:
        """Bank one gauge envelope.  Shape-checked and never coerced —
        telemetry riding a heartbeat must not be able to crash the
        heartbeat (the r12 ``_record_trace`` stance)."""
        if not worker_id or not isinstance(payload, dict):
            return
        families = payload.get("families")
        if not isinstance(families, dict):
            return
        with self._lock:
            # Move-to-end so dict order is update recency (the
            # departed-worker bound in fleet_snapshot prunes oldest).
            self._envelopes.pop(worker_id, None)
            self._envelopes[worker_id] = families
        total = _unlabeled_scalar(families, gauge.EXAMPLES_TRAINED)
        if total is not None:
            self._rates.update(worker_id, total)

    def jsonl_mirror(self, worker_id: str, payload) -> Optional[dict]:
        """The JSONL coexistence fix: the scalar families of ``payload``
        restricted to the one naming table (``JSONL_GAUGE_FAMILIES``),
        keyed by the SAME family names the live scrape serves — so the
        offline stream and the live endpoint cannot drift.  None when the
        envelope carries none of them."""
        if not isinstance(payload, dict):
            return None
        families = payload.get("families")
        if not isinstance(families, dict):
            return None
        out: Dict[str, float] = {}
        for name in gauge.JSONL_GAUGE_FAMILIES:
            v = _unlabeled_scalar(families, name)
            if v is not None:
                out[name] = v
        return out or None

    # -- scrape side --

    def _collect(self) -> None:
        """Registry collector: refresh every master family from the
        servicer's live state and the banked envelopes.  Runs per scrape
        / snapshot — never on the hot path (gauge-discipline)."""
        reg = self.registry
        s = self._servicer
        # Per-ENTITY labeled families are rebuilt from scratch each
        # scrape: workers die and gangs dissolve, and a series that is
        # no longer set must disappear rather than serve its last value
        # forever (a dead worker's frozen rate beside a live fleet total
        # would make the page disagree with itself).
        for name in (
            "edl_worker_examples_per_sec",
            "edl_gang_arrival_lag_seconds",
            "edl_gang_wait_share",
            "edl_skipped_ranks_total",
        ):
            reg.clear_family(name)
        counts = s.dispatcher.counts()
        for key in ("todo", "doing", "done", "abandoned", "skipped",
                    "duplicate_done", "epoch"):
            reg.gauge(
                f"edl_dispatcher_{key}",
                "task dispatcher state (see TaskDispatcher.counts)",
            ).set(float(counts.get(key, 0)))
        membership = s.rendezvous.membership()
        reg.gauge("edl_world_size", "registered worker count").set(
            float(membership.get("world_size", 0))
        )
        reg.gauge("edl_membership_version", "rendezvous version").set(
            float(membership.get("version", 0))
        )
        state = s.fleet_state_snapshot()
        phase_times = state["phase_times"]
        reg.gauge("edl_model_version", "max reported model version").set(
            float(state["model_version"])
        )
        for worker, n in state["skipped_ranks"].items():
            reg.gauge(
                "edl_skipped_ranks_total",
                "gang-deadline skips charged per rank (r13)",
                labels={"worker": worker},
            ).set(float(n))
        if state["standby_depth"] is not None:
            reg.gauge(
                "edl_standby_depth", "warm-standby pool depth"
            ).set(float(state["standby_depth"]))
        # Per-rank gang-arrival lag: seconds behind the gang head's
        # lockstep arrival — the deadline's own signal, live.
        for worker, lag in s.gang_lag_snapshot().items():
            reg.gauge(
                "edl_gang_arrival_lag_seconds",
                "seconds each rank trails the gang head's lockstep "
                "arrival (r13 deadline signal)",
                labels={"worker": worker},
            ).set(lag)
        # Gang-wait share per worker, from the banked phase snapshots.
        for worker, phases in phase_times.items():
            critical = critical_path_seconds(phases)
            if critical <= 0:
                continue
            share = float(phases.get("lease_wait", 0.0)) / critical
            reg.gauge(
                "edl_gang_wait_share",
                "lease_wait share of critical-path seconds per worker "
                "(the straggler-report skew input, live)",
                labels={"worker": worker},
            ).set(share)
        # The goodput computer.
        per_worker = self._rates.rates()
        fleet_rate = sum(per_worker.values())
        for worker, r in per_worker.items():
            reg.gauge(
                "edl_worker_examples_per_sec",
                "windowed examples/sec per worker",
                labels={"worker": worker},
            ).set(r)
        reg.gauge(
            "edl_fleet_examples_per_sec",
            "windowed fleet examples/sec (summed per-worker rates)",
        ).set(fleet_rate)
        with self._lock:
            self._peak_rate = max(self._peak_rate, fleet_rate)
            peak = self._peak_rate
        reg.gauge(
            "edl_fleet_examples_per_sec_peak",
            "peak windowed fleet rate this job (the live goodput "
            "denominator)",
        ).set(peak)
        reg.gauge(
            "edl_goodput_under_churn",
            "live fleet rate / peak fleet rate — the live twin of "
            "chaos_bench's faulted-over-baseline ratio (1.0 = healthy)",
        ).set(fleet_rate / peak if peak > 0 else 0.0)
        if self._ceiling:
            reg.gauge(
                "edl_device_ceiling_examples_per_sec",
                "committed device-step record (bench.py artifact)",
            ).set(self._ceiling)
            reg.gauge(
                "edl_goodput_vs_ceiling",
                "live fleet examples/sec over the committed device-step "
                "ceiling",
            ).set(fleet_rate / self._ceiling)

    #: Most-recently-updated DEPARTED workers whose envelopes stay
    #: servable (the r12 TRACE_DEPARTED_KEEP stance): a job-end or
    #: just-killed worker's final numbers remain readable, but memory and
    #: the fleet page track the current world size, not historical churn
    #: — every r13 kill-churn incarnation banking a full snapshot forever
    #: would be exactly the frozen-series lie the plane must not tell.
    DEPARTED_KEEP = 8

    def fleet_snapshot(self) -> Dict[str, dict]:
        """Merged per-worker families (``worker`` label per series):
        every CURRENT member's envelope plus the ``DEPARTED_KEEP``
        most-recently-updated departed workers'."""
        live = set(
            self._servicer.rendezvous.membership().get("workers") or []
        )
        with self._lock:
            departed = [w for w in self._envelopes if w not in live]
            for w in departed[: max(len(departed) - self.DEPARTED_KEEP, 0)]:
                del self._envelopes[w]
            envelopes = dict(self._envelopes)
        return gauge.merge_snapshots(envelopes)

    def render(self) -> str:
        """The master endpoint's /metrics body: the master's own
        families (collector-refreshed) and the merged per-worker view in
        ONE exposition.  Folded into one family dict before rendering —
        a family present on both sides (edl_membership_version lives on
        the master AND in every worker envelope) must render under ONE
        HELP/TYPE block, or a spec-strict Prometheus parser rejects the
        whole scrape on the duplicate TYPE line."""
        families = self.registry.snapshot()
        for name, fam in self.fleet_snapshot().items():
            slot = families.setdefault(
                name,
                {"type": fam.get("type", "gauge"),
                 "help": fam.get("help", ""), "samples": []},
            )
            slot["samples"].extend(fam.get("samples") or [])
        return gauge.render_families(families)

    def health(self) -> dict:
        """/healthz payload: identity + the headline numbers."""
        counts = self._servicer.dispatcher.counts()
        with self._lock:
            workers = sorted(self._envelopes)
        return {
            "role": "master",
            "workers_reporting": workers,
            "tasks": {k: counts.get(k) for k in ("todo", "doing", "done")},
            "fleet_examples_per_sec": round(self._rates.rate(), 1),
        }


def _unlabeled_scalar(families: dict, name: str) -> Optional[float]:
    """The unlabeled series value of a scalar family in a snapshot-shaped
    dict, or None (absent / malformed / labeled-only / histogram)."""
    fam = families.get(name)
    if not isinstance(fam, dict):
        return None
    for s in fam.get("samples") or []:
        if not isinstance(s, dict) or s.get("labels"):
            continue
        v = s.get("value")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return None
