"""Master control plane.

Reference parity (SURVEY.md §1-§3 [U/D]): one master per job owning
- dynamic data sharding (``TaskDispatcher``: todo/doing/done queues, requeue
  on worker death — the fault-tolerance core),
- elastic membership (``RendezvousServer``: versioned worker list; in the TPU
  rebuild a version bump triggers mesh re-formation instead of a Horovod
  communicator rebuild),
- an RPC service workers poll between shards (``MasterServicer`` over gRPC),
- evaluation scheduling/aggregation (``EvaluationService``),
- pod lifecycle (``PodManager``, pluggable backend).
"""

from elasticdl_tpu.master.task_dispatcher import Task, TaskDispatcher  # noqa: F401
from elasticdl_tpu.master.rendezvous import RendezvousServer  # noqa: F401
from elasticdl_tpu.master.evaluation_service import EvaluationService  # noqa: F401
