"""Dynamic data sharding: the fault-tolerance core.

Reference parity (SURVEY.md §2 #3, §3.2 [U — mount empty at survey time]):
the master splits the dataset into shard-sized "tasks"; workers pull tasks
over RPC and report results; tasks of a dead/slow worker are requeued so a
preemption loses at most the in-flight shards.  Epochs are implemented by
refilling the todo queue when a pass completes.

Thread-safe: the RPC servicer calls from gRPC threads, the pod watcher from
its own thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from elasticdl_tpu.common import locksan, trace
from elasticdl_tpu.data.reader import Shard

TASK_TRAINING = "training"
TASK_EVALUATION = "evaluation"
TASK_PREDICTION = "prediction"


class JournalReplayError(RuntimeError):
    """A journal event does not fit the state being rebuilt — the WAL
    describes a different job (or is corrupt past its header's job-shape
    guard).  The restarting master falls back to the coarse watermark."""


@dataclasses.dataclass(frozen=True)
class Task:
    task_id: int
    shard: Shard
    type: str = TASK_TRAINING
    epoch: int = 0

    def to_dict(self) -> dict:
        return {
            "task_id": self.task_id,
            "shard": dataclasses.asdict(self.shard),
            "type": self.type,
            "epoch": self.epoch,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Task":
        return cls(
            task_id=d["task_id"],
            shard=Shard(**d["shard"]),
            type=d["type"],
            epoch=d["epoch"],
        )


@dataclasses.dataclass
class _Doing:
    task: Task
    worker_id: str
    handed_at: float


class TaskDispatcher:
    """todo/doing/done task queues with requeue-on-failure semantics."""

    def __init__(
        self,
        shards: List[Shard],
        num_epochs: int = 1,
        task_type: str = TASK_TRAINING,
        task_timeout_s: float = 600.0,
        max_task_retries: int = 3,
        task_skip_budget: int = 2,
        clock: Callable[[], float] = time.monotonic,
        resume: Optional[dict] = None,
        restore: Optional[dict] = None,
        journal=None,
    ):
        if num_epochs < 1:
            raise ValueError("num_epochs must be >= 1")
        self._shards = list(shards)
        self._num_epochs = num_epochs
        self._task_type = task_type
        self._timeout = task_timeout_s
        self._max_retries = max_task_retries
        self._skip_budget = task_skip_budget
        self._clock = clock

        # Callbacks (_fire_epoch_end) and callers' locks stay outside this
        # one by design; nothing is ever acquired under it.
        self._lock = locksan.lock("TaskDispatcher._lock", leaf=True)  # lock-order: leaf
        self._todo: deque = deque()
        self._doing: Dict[int, _Doing] = {}
        self._done_count = 0
        self._abandoned = 0
        self._failed_counts: Dict[int, int] = {}
        # Deadline-skip accounting (r13, the gang boundary's safety proof):
        # per-task skip counts and a counter of late SUCCESS reports
        # REJECTED — the explicit zero-double-train check the chaos
        # artifact stamps.
        self._skip_counts: Dict[int, int] = {}
        self._skipped_events = 0
        self._duplicate_done = 0
        self._next_task_id = 0
        self._epoch = -1  # _refill brings it to 0
        self._finished = not self._shards
        self._stopped = False  # stop(): draining, nothing requeues
        # Done shards of the CURRENT epoch, for ``progress()`` — the durable
        # watermark a restarted master resumes from (SURVEY §5 "restore on
        # master restart").
        self._done_in_epoch: set = set()
        # Epoch-boundary events: (epoch, is_final) pairs queued under the
        # lock by _refill and delivered OUTSIDE it (the callback may start an
        # eval round, which has its own locks).  The master wires the
        # callback for the reference's "eval at epoch end" mode
        # (--evaluation_steps=0).
        self._on_epoch_end: Optional[Callable[[int, bool], None]] = None
        self._pending_epoch_end: List[Tuple[int, bool]] = []
        # Durable control-plane journal (r18, master/journal.py): every
        # queue mutation records one event under this lock, in mutation
        # order, so a restarted master replays to the EXACT pre-crash
        # state (not the coarse watermark's "skip finished epochs").
        # None = no journal (tests, eval/predict jobs); attached after
        # construction on the replay path (replay must not re-record).
        self._journal = journal  # guarded-by: _lock
        if restore is not None:
            # Journal-replay restore: the full pre-crash state, bit for
            # bit — supersedes the watermark resume below.
            self._restore_snapshot(restore)
        elif resume is not None and self._shards:
            self._resume(resume)
        else:
            self._refill()

    @staticmethod
    def _shard_key(shard: Shard) -> Tuple[str, int, int]:
        return (shard.name, shard.start, shard.end)

    def _resume(self, progress: dict) -> None:
        """Fast-forward to a persisted watermark: enter ``progress['epoch']``
        with its already-done shards excluded from the todo queue.  A
        watermark at/after the last epoch with everything done finishes
        immediately (the job was complete when the old master died)."""
        epoch = int(progress.get("epoch", 0))
        done_keys = {tuple(k) for k in progress.get("done_shards", [])}
        self._done_count = int(progress.get("done_count", 0))
        if epoch >= self._num_epochs:
            self._finished = True
            return
        self._epoch = epoch
        known = {self._shard_key(s) for s in self._shards}
        self._done_in_epoch = done_keys & known
        for shard in self._shards:
            if self._shard_key(shard) in self._done_in_epoch:
                continue
            self._todo.append(
                Task(self._next_task_id, shard, self._task_type, self._epoch)
            )
            self._next_task_id += 1
        if not self._todo:
            # Every shard of the watermark epoch was done: move on (or
            # finish, if it was the last).
            self._done_in_epoch = set()
            if self._epoch + 1 >= self._num_epochs:
                self._finished = True
            else:
                self._refill()
        # Epoch-end events generated while fast-forwarding describe epochs
        # that ended BEFORE the crash — their eval rounds already ran; firing
        # them again would emit duplicate metric rows.
        self._pending_epoch_end.clear()

    def progress(self) -> dict:
        """The durable watermark: epoch + done shards within it + cumulative
        done count.  Linear in done shards; the master persists it
        atomically after reports (master/main.py)."""
        with self._lock:
            return {
                "epoch": max(self._epoch, 0),
                "done_shards": sorted(self._done_in_epoch),
                "done_count": self._done_count,
                "num_epochs": self._num_epochs,
                "num_shards": len(self._shards),
            }

    def set_epoch_end_callback(self, fn: Callable[[int, bool], None]) -> None:
        self._on_epoch_end = fn

    # -- durable journal (r18): snapshot / restore / event replay --

    def attach_journal(self, journal) -> None:
        """Wire the WAL after construction (the replay path builds the
        dispatcher journal-less, then attaches the rotated journal)."""
        with self._lock:
            self._journal = journal

    def _j(self, ev: dict) -> None:  # guarded-by: _lock
        """Record one journal event.  Called under ``_lock`` immediately
        after the mutation it describes, so the WAL's physical order IS
        the mutation order (the replay contract)."""
        if self._journal is not None:
            self._journal.record(ev)

    def rotate_journal(self, extras: dict) -> None:
        """Compaction inner half: snapshot + WAL swap in ONE critical
        section of this lock, so no dispatcher event can land between the
        snapshot and the new file (it would be lost from both).  The
        caller (MasterServicer.rotate_journal) holds the group + servicer
        locks across this call, excluding ITS writers the same way."""
        with self._lock:
            if self._journal is None:
                return
            base = dict(extras)
            base["dispatcher"] = self._snapshot_locked()
            self._journal.rotate(base)

    def snapshot(self) -> dict:
        """The FULL dispatcher state, JSON-safe — the journal's base
        record.  Everything ``counts()``/``progress()`` summarize plus the
        queues themselves, so a restore is bit-identical (pinned by
        test), not a watermark approximation."""
        with self._lock:
            return self._snapshot_locked()

    def _snapshot_locked(self) -> dict:  # guarded-by: _lock
        return {
            "epoch": self._epoch,
            "todo": [t.to_dict() for t in self._todo],
            "doing": [
                {"task": d.task.to_dict(), "worker": d.worker_id}
                for d in self._doing.values()
            ],
            "done_count": self._done_count,
            "done_in_epoch": sorted(list(k) for k in self._done_in_epoch),
            "failed_counts": {
                str(k): v for k, v in self._failed_counts.items()
            },
            "skip_counts": {str(k): v for k, v in self._skip_counts.items()},
            "skipped_events": self._skipped_events,
            "duplicate_done": self._duplicate_done,
            "abandoned": self._abandoned,
            "next_task_id": self._next_task_id,
            "finished": self._finished,
            "stopped": self._stopped,
            "num_epochs": self._num_epochs,
            "num_shards": len(self._shards),
            "task_type": self._task_type,
        }

    def _restore_snapshot(self, snap: dict) -> None:
        """Adopt a ``snapshot()`` verbatim (journal-replay restore).  The
        job-shape guard lives in master/journal.py's replay — by the time
        a snapshot reaches here it describes THIS job."""
        self._epoch = int(snap["epoch"])
        self._todo = deque(Task.from_dict(t) for t in snap["todo"])
        # handed_at resets to now: the pre-crash lease ages died with the
        # old master's clock, and restarting the timeout window is the
        # conservative choice (a requeue fires late, never spuriously).
        now = self._clock()
        self._doing = {
            d["task"]["task_id"]: _Doing(
                Task.from_dict(d["task"]), d["worker"], now
            )
            for d in snap["doing"]
        }
        self._done_count = int(snap["done_count"])
        self._done_in_epoch = {tuple(k) for k in snap["done_in_epoch"]}
        self._failed_counts = {
            int(k): v for k, v in snap["failed_counts"].items()
        }
        self._skip_counts = {int(k): v for k, v in snap["skip_counts"].items()}
        self._skipped_events = int(snap["skipped_events"])
        self._duplicate_done = int(snap["duplicate_done"])
        self._abandoned = int(snap["abandoned"])
        self._next_task_id = int(snap["next_task_id"])
        self._finished = bool(snap["finished"])
        self._stopped = bool(snap["stopped"])

    def replay_event(self, ev: dict) -> None:
        """Apply one journaled event (master/journal.py's replay loop).
        Only the nondeterministic inputs were journaled — hand-out
        choices, reports, requeues — and every derived transition (epoch
        refill, retry/skip budgets, poison abandons) re-derives through
        the same code that produced it, so replayed state is bit-exact.
        Runs with the journal DETACHED (events must not re-record)."""
        kind = ev["kind"]
        if kind == "handout":
            with self._lock:
                for td in ev["tasks"]:
                    task_id = td["task_id"]
                    entry = None
                    for i, t in enumerate(self._todo):
                        if t.task_id == task_id:
                            entry = t
                            del self._todo[i]
                            break
                    if entry is None:
                        raise JournalReplayError(
                            f"handout of task {task_id} not in todo — the "
                            "journal does not describe this job"
                        )
                    self._doing[task_id] = _Doing(
                        entry, ev["worker"], self._clock()
                    )
        elif kind == "report":
            self.report(
                int(ev["task_id"]), bool(ev["success"]),
                ev.get("worker", ""),
                requeue_only=bool(ev.get("requeue", False)),
            )
        elif kind == "recover":
            self.recover_tasks(ev["worker"])
        elif kind == "skip":
            self.skip_tasks(ev["worker"])
        elif kind == "timeout":
            with self._lock:
                self._requeue_specific_locked(ev["tasks"])
        elif kind == "reconcile":
            self.reconcile_leases(ev["worker"], set(ev["held"]))
        elif kind == "stop":
            self.stop()
        else:
            raise JournalReplayError(f"unknown journal event kind {kind!r}")

    def _requeue_specific_locked(self, task_ids) -> None:  # guarded-by: _lock
        """Replay a timeout requeue: the journaled ids move doing -> todo
        (front), exactly as ``_requeue_timed_out`` moved them."""
        for tid in task_ids:
            entry = self._doing.pop(tid, None)
            if entry is not None and not self._stopped:
                self._todo.appendleft(entry.task)

    def reconcile_leases(self, worker_id: str, held_ids: set):
        """Lease reconciliation (r18): the re-register handshake after a
        master restart.  ``held_ids`` is what the worker still holds; any
        ``doing`` entry of this worker NOT held was a handout lost in
        flight during the crash — requeue it NOW (budget-free, the r9
        requeue_only stance) instead of after task_timeout_s.  Returns
        ``(requeued_tasks, stale_ids)``: stale ids are held tasks this
        dispatcher no longer attributes to the worker (already reported,
        or re-leased after a double restart) — the worker must drop them
        unstarted or their records would train twice."""
        held = {int(h) for h in held_ids}
        with self._lock:
            lost = [
                d.task for d in self._doing.values()
                if d.worker_id == worker_id and d.task.task_id not in held
            ]
            for task in lost:
                del self._doing[task.task_id]
                if not self._stopped:
                    self._todo.appendleft(task)
            stale = sorted(
                h for h in held
                if h not in self._doing
                or self._doing[h].worker_id != worker_id
            )
            self._j({
                "kind": "reconcile", "worker": worker_id,
                "held": sorted(held),
            })
            self._refill()
        if lost or stale:
            trace.instant(
                "lease:reconcile", cat="lease", worker=worker_id,
                requeued=[t.task_id for t in lost], stale=stale,
            )
        self._fire_epoch_end()
        return lost, stale

    # -- internal --

    def _refill(self) -> None:
        """Start the next epoch if the current one is exhausted."""
        if self._finished or self._todo or self._doing:
            return
        prev = self._epoch
        if self._epoch + 1 >= self._num_epochs:
            self._finished = True
            if prev >= 0:
                self._pending_epoch_end.append((prev, True))
            return
        if prev >= 0:
            self._pending_epoch_end.append((prev, False))
        self._epoch += 1
        self._done_in_epoch = set()
        for shard in self._shards:
            self._todo.append(
                Task(self._next_task_id, shard, self._task_type, self._epoch)
            )
            self._next_task_id += 1

    def _fire_epoch_end(self) -> None:
        """Deliver queued epoch-boundary events (call with the lock RELEASED)."""
        while True:
            with self._lock:
                if not self._pending_epoch_end:
                    return
                epoch, final = self._pending_epoch_end.pop(0)
            if self._on_epoch_end is not None:
                self._on_epoch_end(epoch, final)

    # -- worker-facing API (via servicer) --

    # hot-path: behind every worker GetTask poll
    def get_task(self, worker_id: str) -> Optional[Task]:
        """Hand out the next task, or None if nothing is available.

        None with ``finished() == False`` means "in-flight tasks remain;
        poll again" (their failure may requeue work).
        """
        tasks = self.get_tasks(worker_id, 1)
        return tasks[0] if tasks else None

    # hot-path: behind every (batched) worker GetTask poll
    def get_tasks(self, worker_id: str, n: int) -> List[Task]:
        """Lease up to ``n`` tasks to ``worker_id`` in one locked pass (the
        batched-lease RPC, r9).  Every handed-out task enters ``doing``
        individually, so the existing elasticity machinery — timeout
        requeue, ``recover_tasks`` on worker loss, at-least-once reports —
        covers leased-but-unstarted tasks with no new state: a lost worker's
        whole lease requeues exactly once through the same path as its
        in-flight task.  Epoch refill semantics are unchanged: a batch
        never crosses an epoch boundary mid-call (the refill only fires
        when todo AND doing are both empty)."""
        with self._lock:
            self._requeue_timed_out()
            self._refill()
            tasks: List[Task] = []
            while self._todo and len(tasks) < n:
                task = self._todo.popleft()
                self._doing[task.task_id] = _Doing(
                    task, worker_id, self._clock()
                )
                tasks.append(task)
            if tasks:
                # The WHICH of the hand-out is the nondeterministic input
                # replay cannot re-derive (full task dicts: the replayed
                # doing set must not depend on todo ordering assumptions).
                self._j({
                    "kind": "handout", "worker": worker_id,
                    "tasks": [t.to_dict() for t in tasks],
                })
        if tasks:
            # Lease lifecycle, instant-event form (non-blocking ring append
            # — hot-path legal): handout -> report/requeue/recover, so the
            # merged trace shows which worker held which task when.
            trace.instant(
                "lease:handout", cat="lease", worker=worker_id,
                tasks=[t.task_id for t in tasks],
            )
        self._fire_epoch_end()
        return tasks

    # hot-path: behind every task report
    def report(
        self,
        task_id: int,
        success: bool,
        worker_id: str = "",
        requeue_only: bool = False,
        seq: Optional[int] = None,
    ) -> bool:
        """Record a task result; requeue on failure.  Returns False for an
        unknown/stale id (e.g. a task already requeued by the timeout path —
        the late result is ignored, matching at-least-once semantics).

        ``requeue_only`` (r9): the task was returned UNSTARTED (a worker
        giving back a buffered lease or an undispatched prep on preemption
        or membership change) — requeue it without touching the retry
        budget.  Counting these as failures would let routine elastic churn
        poison-abandon a healthy task: with batched leases a task can sit
        in some worker's buffer across max_task_retries separate scale
        events without ever having run."""
        trace.instant(
            "lease:report", cat="lease", task=task_id, worker=worker_id,
            success=success, requeue=requeue_only,
        )
        with self._lock:
            # Journaled BEFORE the branch so the rejected-late-success
            # accounting (duplicate_done) replays identically too; ``seq``
            # rides along so replay rebuilds the per-worker dedup ledger
            # from the same record (master/journal.py).
            self._j({
                "kind": "report", "task_id": task_id, "success": success,
                "worker": worker_id, "requeue": requeue_only,
                **({"seq": seq} if seq is not None else {}),
            })
            entry = self._doing.pop(task_id, None)
            if entry is None:
                if success:
                    # A late SUCCESS for a task no longer in flight: a
                    # duplicate of an already-counted result, or — the
                    # double-train hazard — a task that was requeued
                    # (timeout/skip raced the report) and whose records
                    # will train again.  Either way the rejection is
                    # counted, so the chaos artifact's zero-double-train
                    # check is an observable number, not an assumption.
                    self._duplicate_done += 1
                return False
            if success:
                self._done_count += 1
                if entry.task.epoch == self._epoch:
                    self._done_in_epoch.add(self._shard_key(entry.task.shard))
            elif self._stopped:
                # Draining past --max_steps: a failed in-flight task is
                # dropped, not requeued — requeueing would re-open dispatch
                # and train past the configured limit.
                self._abandoned += 1
            elif requeue_only:
                self._todo.appendleft(entry.task)
            else:
                fails = self._failed_counts.get(task_id, 0) + 1
                self._failed_counts[task_id] = fails
                if fails <= self._max_retries:
                    self._todo.appendleft(entry.task)
                else:
                    # Poison task: a shard that fails deterministically (bad
                    # data, codec mismatch) must not stall the job forever.
                    self._abandoned += 1
            self._refill()
        self._fire_epoch_end()
        return True

    # -- elasticity hooks --

    def recover_tasks(self, worker_id: str) -> List[Task]:
        """Requeue every in-flight task of a dead worker (PodManager calls
        this on a pod-failure event; §3.2 'elasticity core').  After stop()
        the tasks are released but NOT requeued (draining)."""
        with self._lock:
            lost = [d.task for d in self._doing.values() if d.worker_id == worker_id]
            for task in lost:
                del self._doing[task.task_id]
                if not self._stopped:
                    self._todo.appendleft(task)
            if lost:
                self._j({"kind": "recover", "worker": worker_id})
        if lost:
            trace.instant(
                "lease:recover", cat="lease", worker=worker_id,
                tasks=[t.task_id for t in lost],
            )
        return lost

    def skip_tasks(self, worker_id: str) -> List[Task]:
        """Deadline-skip requeue (r13, the gang boundary's accounting):
        requeue every in-flight task of ``worker_id`` — the lockstep
        group pseudo worker whose gang just skipped a straggler — with
        BOUNDED skip accounting.  The first ``task_skip_budget`` skips of
        a task requeue free (elastic churn must not poison a healthy
        shard, the r9 requeue_only stance); past the budget a skip is
        charged like a FAILURE, so a shard that deterministically stalls
        a rank flows into the existing poison-task abandon path instead
        of ping-ponging the gang through skip-reform cycles forever.
        Exactly-once is preserved by construction: a skipped task left
        ``doing`` unreported, so it requeues exactly once here and its
        eventual success is counted once (the duplicate-done counter
        proves the claim at run time)."""
        with self._lock:
            lost = [
                d.task for d in self._doing.values()
                if d.worker_id == worker_id
            ]
            if lost:
                self._j({"kind": "skip", "worker": worker_id})
            for task in lost:
                del self._doing[task.task_id]
                self._skipped_events += 1
                if self._stopped:
                    continue  # draining: skipped work must not retrain
                skips = self._skip_counts.get(task.task_id, 0) + 1
                self._skip_counts[task.task_id] = skips
                if skips <= self._skip_budget:
                    self._todo.appendleft(task)
                    continue
                fails = self._failed_counts.get(task.task_id, 0) + 1
                self._failed_counts[task.task_id] = fails
                if fails <= self._max_retries:
                    self._todo.appendleft(task)
                else:
                    self._abandoned += 1
            self._refill()
        for task in lost:
            trace.instant(
                "lease:skip", cat="lease", task=task.task_id,
                worker=worker_id,
            )
        self._fire_epoch_end()
        return lost

    def _requeue_timed_out(self) -> None:
        now = self._clock()
        stale = [
            tid
            for tid, d in self._doing.items()
            if now - d.handed_at > self._timeout
        ]
        if stale:
            # Clock-driven, hence invisible to replay unless journaled.
            self._j({"kind": "timeout", "tasks": list(stale)})
        for tid in stale:
            task = self._doing.pop(tid).task
            if not self._stopped:
                self._todo.appendleft(task)
            trace.instant("lease:timeout", cat="lease", task=tid)

    def stop(self) -> None:
        """Stop handing out new tasks (reference: --max_steps reached).
        In-flight tasks still report normally; ``finished()`` turns True once
        they drain.  Sticky: no refill, and failed/timed-out/recovered tasks
        do not requeue afterwards."""
        with self._lock:
            self._j({"kind": "stop"})
            self._todo.clear()
            self._finished = True
            self._stopped = True

    # -- introspection --

    def finished(self) -> bool:
        with self._lock:
            return self._finished and not self._todo and not self._doing

    def counts(self) -> dict:
        with self._lock:
            return {
                "todo": len(self._todo),
                "doing": len(self._doing),
                "done": self._done_count,
                "abandoned": self._abandoned,
                "epoch": self._epoch,
                # r13 tail-tolerance accounting: total deadline-skip events,
                # per-task skip counts, and the explicit zero-double-train
                # counter (rejected late SUCCESS reports).
                "skipped": self._skipped_events,
                "skip_counts": dict(self._skip_counts),
                "duplicate_done": self._duplicate_done,
                "finished": self._finished and not self._todo and not self._doing,
            }
