"""PodManager — the elasticity engine.

Reference parity (SURVEY.md §2 #4 [U — mount empty at survey time; capability
[D]: "worker preemption + scale 4→8→4" is a BASELINE.json config): the
reference's master watches Kubernetes pod events, relaunches failed worker
pods up to a restart budget, and honors scale-up/down requests; the
TaskDispatcher requeues a dead pod's tasks and the RendezvousServer bumps the
membership version so the collective re-forms.

TPU rebuild: the same slot/relaunch/scale state machine over a pluggable
``PodBackend``:

- ``FakePodBackend`` — in-memory, with test-injectable phase events (the
  reference's decisive mock-k8s unit-test pattern, SURVEY.md §4).
- ``ProcessPodBackend`` — local worker subprocesses (``python -m
  elasticdl_tpu.worker.main``), each one host of the job; exit code drives
  SUCCEEDED/FAILED events.  This is the no-cluster deployment used by the
  ``elasticdl train`` CLI's local mode and by chaos tests (kill -9 a worker).
- ``KubernetesPodBackend`` — renders TPU-pod manifests (``google.com/tpu``
  resources on a node pool selector) and drives them through the kubernetes
  client if one is installed; the manifest renderer is importable/testable
  without a cluster.

Pod death flows OUT of the manager through listeners (master main wires
``RendezvousServer.remove``, which cascades into task requeue via the
servicer's membership listener); it never reaches into dispatcher state
itself.
"""

from __future__ import annotations

import dataclasses
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common import durable, locksan, racesan, trace
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("master.pod_manager")


class PodPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    DELETED = "Deleted"
    # Worker asked to be restarted (multihost elastic re-join, exit code 3);
    # relaunched WITHOUT consuming the slot's failure budget.
    RESTART = "Restart"
    # An ADOPTED pod (a pre-restart orphan this master re-attached to,
    # r18) disappeared.  Its exit code is unknowable — it was never this
    # process's child — so the backend cannot tell a clean job-end exit
    # from a crash; PodManager._on_event resolves LOST to SUCCEEDED when
    # the job is already finished, else FAILED (relaunch path).  Never
    # reaches listeners unresolved.
    LOST = "Lost"

    TERMINAL = (SUCCEEDED, FAILED, DELETED, RESTART, LOST)


# Exit code the worker main uses to request a budget-free relaunch
# (worker.worker.RESTART_EXIT_CODE; duplicated to keep this module
# importable without jax).
WORKER_RESTART_EXIT_CODE = 3

#: The pod reattach registry's filename under checkpoint_dir (r18): the
#: ONE spelling Master's wiring, the whole-job-restart probe and the
#: masterfail bench all reference.
REGISTRY_FILENAME = "pod_registry.json"  # durable-file


def proc_cmdline(pid: int) -> Optional[str]:
    """Best-effort /proc cmdline fingerprint (None off-Linux or for a
    vanished pid): the pid-reuse guard for every registry-pid probe."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return (
                f.read().replace(b"\0", b" ").decode(errors="replace").strip()
            )
    except OSError:
        return None


def pid_alive(pid: int, cmdline: Optional[str] = None) -> bool:
    """THE pid-liveness probe for reattach decisions (r18) — one
    definition so the adoption check, the whole-job-restart probe and the
    bench cannot drift.  ``kill(pid, 0)`` alone lies twice: a ZOMBIE
    (exited, unreaped) still answers it, and a RECYCLED pid answers for a
    stranger.  /proc state 'Z' filters the first (best-effort; off-Linux
    the zombie case cannot arise for the processes this guards — adopted
    orphans reparent to init and are reaped there); a ``cmdline``
    fingerprint, when the caller recorded one, filters the second."""
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            # Field 3 (after the parenthesized comm, which may itself
            # contain spaces): the process state.
            state = f.read().rpartition(")")[2].split()[0]
        if state == "Z":
            return False
    except (OSError, IndexError):
        pass  # no /proc: fall through to the kill(0) verdict
    if cmdline:
        have = proc_cmdline(pid)
        if have is not None and have != cmdline:
            return False  # pid recycled by an unrelated process
    return True


@dataclasses.dataclass
class PodInfo:
    name: str
    slot: int
    phase: str = PodPhase.PENDING
    relaunches: int = 0  # relaunch generation of this slot


# Listener signature: fn(pod_name: str, phase: str)
PodListener = Callable[[str, str], None]


class PodBackend:
    """Starts/stops pods and reports phase transitions via a callback."""

    def set_event_callback(self, cb: PodListener) -> None:
        self._cb = cb

    def _emit(self, name: str, phase: str) -> None:
        cb = getattr(self, "_cb", None)
        if cb is not None:
            cb(name, phase)

    def start_pod(self, name: str, env: Dict[str, str]) -> None:
        raise NotImplementedError

    def delete_pod(self, name: str) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FakePodBackend(PodBackend):
    """In-memory backend; tests inject pod events (mock-k8s pattern)."""

    def __init__(self, auto_run: bool = True):
        self.pods: Dict[str, str] = {}  # name -> phase; guarded-by: _lock
        self.start_log: List[str] = []  # guarded-by: _lock
        self._auto_run = auto_run
        self._lock = locksan.lock("FakePodBackend._lock", leaf=True)  # lock-order: leaf

    def start_pod(self, name: str, env: Dict[str, str]) -> None:
        with self._lock:
            self.pods[name] = PodPhase.PENDING
            self.start_log.append(name)
        if self._auto_run:
            self.set_phase(name, PodPhase.RUNNING)

    def delete_pod(self, name: str) -> None:
        self.set_phase(name, PodPhase.DELETED)

    # -- test injection --

    def set_phase(self, name: str, phase: str) -> None:
        with self._lock:
            if name not in self.pods or self.pods[name] == phase:
                return
            if self.pods[name] in PodPhase.TERMINAL:
                return  # terminal phases are final, as in k8s
            self.pods[name] = phase
        self._emit(name, phase)

    def fail_pod(self, name: str) -> None:
        self.set_phase(name, PodPhase.FAILED)

    def succeed_pod(self, name: str) -> None:
        self.set_phase(name, PodPhase.SUCCEEDED)

    def running(self) -> List[str]:
        with self._lock:
            return [n for n, p in self.pods.items() if p == PodPhase.RUNNING]


class ProcessPodBackend(PodBackend):
    """Worker pods as local subprocesses; a watcher thread maps exit codes to
    pod events.  ``argv`` defaults to the worker main module; the serving
    fleet controller (serving/fleet.py, r19) runs the SAME backend with
    ``argv=[..., "-m", "elasticdl_tpu.serving.main"]`` — replicas speak the
    identical standby/adoption env contract (ELASTICDL_WORKER_ID/SLOT +
    go-file), so spawn, warm standby, crash relaunch and the r18 reattach
    registry all carry over to serving without a parallel implementation.

    ``warm_standby=True`` keeps a small POOL of pre-booted spares parked:
    processes that have already paid python + jax + framework imports
    (~13 s of the r4 25.7 s re-rendezvous, docs/perf.md) and wait on a
    go-file for their worker id (worker.main standby mode).  ``start_pod``
    adopts a spare when its environment matches and immediately refills
    the pool, so a relaunch boots in restore+compile time instead of
    import time.  ``standby_pool`` sizes it: 1 covers a lone failure; a
    peer-death recovery relaunches TWO processes (the dead pod plus the
    survivor's RESTART), so fleets that want both warm park 2.  A failure
    burst beyond the pool falls back to cold spawns — spares are a latency
    optimization, never a correctness dependency."""

    def __init__(
        self,
        argv: Optional[List[str]] = None,
        poll_interval_s: float = 0.2,
        inherit_env: bool = True,
        warm_standby: bool = False,
        standby_pool: int = 1,
        log_dir: Optional[str] = None,
    ):
        self._argv = argv or [sys.executable, "-m", "elasticdl_tpu.worker.main"]
        self._procs: Dict[str, subprocess.Popen] = {}  # guarded-by: _lock
        self._lock = locksan.lock("ProcessPodBackend._lock", leaf=True)  # lock-order: leaf
        self._poll = poll_interval_s
        self._inherit = inherit_env
        self._stop = threading.Event()
        self._watcher: Optional[threading.Thread] = None  # guarded-by: _lock
        self._warm = warm_standby
        self._pool_size = max(1, standby_pool)
        # Per-pod log capture (the process-backend analog of kubectl logs):
        # each pod's stdout+stderr goes to {log_dir}/{name}.log.  Pod names
        # are already unique per incarnation (PodManager's -rN suffix), so
        # no extra counter is needed.  None = inherit the parent's stdio.
        self._log_dir = log_dir
        # Parked spares: [(proc, go_file, env_signature)].
        self._standby: List[tuple] = []  # guarded-by: _lock
        self._standby_dir: Optional[str] = None  # guarded-by: _lock
        self._standby_seq = 0  # guarded-by: _lock
        # Adopted orphans (r18 master restart): name -> pid of a worker
        # process a PREVIOUS master spawned that this one re-attached to
        # (PodManager reattach registry).  Not our children — liveness is
        # kill(pid, 0) polling in the watcher, exit codes are unknowable
        # (PodPhase.LOST), teardown is signal-based.
        self._adopted: Dict[str, int] = {}  # guarded-by: _lock

    def _pod_stdio(self, name: str):
        if self._log_dir is None:
            return None
        os.makedirs(self._log_dir, exist_ok=True)
        return open(os.path.join(self._log_dir, f"{name}.log"), "w")

    #: Per-pod identity env: excluded from the spawn-time signature and
    #: delivered via the go file at adoption instead, so ONE spare serves a
    #: relaunch of ANY slot/id of the job (review r5: including
    #: ELASTICDL_WORKER_SLOT in the signature silently limited adoption to
    #: the last-started slot and churned the spare on every other launch).
    _IDENTITY_KEYS = ("ELASTICDL_WORKER_ID", "ELASTICDL_WORKER_SLOT")

    @classmethod
    def _env_sig(cls, full_env: Dict[str, str]) -> tuple:
        return tuple(
            sorted(
                (k, v)
                for k, v in full_env.items()
                if k not in cls._IDENTITY_KEYS + ("ELASTICDL_STANDBY_GO_FILE",)
            )
        )

    @staticmethod
    def _reap(proc) -> None:
        """wait() a killed process so it doesn't linger as a zombie."""
        try:
            proc.wait(timeout=5)
        except Exception:  # pragma: no cover — SIGKILL'd procs reap fast
            pass

    def _prune_spares_locked(self, sig) -> None:  # guarded-by: _lock
        """Drop dead spares; kill + drop spares whose job env changed."""
        keep = []
        for proc, go_file, s in self._standby:
            if proc.poll() is not None:
                continue
            if s != sig:
                proc.kill()
                self._reap(proc)
                continue
            keep.append((proc, go_file, s))
        self._standby = keep

    def _adopt_standby(self, name: str, full_env: Dict[str, str]):
        """Hand a parked spare its identity; None if no matching spare.

        Only a WARMED spare is adoptable: the standby writes a
        ``<go_file>.ready`` marker once its imports are paid (worker.main
        ``_park_as_standby``), and a spare still booting is skipped —
        adopting it would be a cold boot with extra moving parts, and the
        whole point of the pool is that the relaunch's wall is
        restore+compile, not imports.  Back-to-back failures beyond the
        warmed depth therefore degrade to cold spawns (and the pool
        refills behind them) — spares stay a latency optimization, never
        a correctness dependency."""
        sig = self._env_sig(full_env)
        with self._lock:
            self._prune_spares_locked(sig)
            chosen = None
            for i, (proc_i, go_i, _s) in enumerate(self._standby):
                if os.path.exists(go_i + ".ready"):
                    chosen = i
                    break
            if chosen is None:
                return None
            proc, go_file, _ = self._standby.pop(chosen)
        # Atomic publish: the standby polls for existence, so the content
        # must be complete the moment the path appears.
        payload = {
            "worker_id": name,
            "env": {
                k: full_env[k]
                for k in self._IDENTITY_KEYS
                if k in full_env and k != "ELASTICDL_WORKER_ID"
            },
        }
        durable.atomic_publish_json(go_file, payload)
        if self._log_dir is not None:
            # The spare's stdio was bound at spawn (it cannot be
            # redirected now); keep the per-pod-life log contract by
            # symlinking the pod name to the spare's file — the relaunch's
            # log is the one an operator needs most (review r5).
            spare_log = f"standby.{os.path.basename(go_file)}.log"
            link = os.path.join(self._log_dir, f"{name}.log")
            try:
                os.symlink(spare_log, link)
            except OSError:
                logger.warning("could not link %s -> %s", link, spare_log)
        logger.info("adopted warm standby (pid %d) as %s", proc.pid, name)
        # Two instants, one moment: the standby lifecycle event and the
        # splice-timeline stage chaos_bench decomposes recovery over
        # (detect -> adopt -> reformed, docs/robustness.md).
        trace.instant("standby:adopt", cat="standby", pod=name, pid=proc.pid)
        trace.instant(
            "elastic:splice", cat="elastic", stage="adopt",
            pod=name, pid=proc.pid,
        )
        return proc

    def _fill_standby_pool(
        self, full_env: Dict[str, str], reason: str = "spawn"
    ) -> None:
        """Top the pool up to ``standby_pool`` live same-env spares.
        ``reason`` tags the lifecycle instant: ``spawn`` for the initial
        fill, ``refill`` when replacing an adopted spare."""
        import tempfile

        sig = self._env_sig(full_env)
        while True:
            with self._lock:
                if self._stop.is_set():
                    # close() may already have reaped the pool and removed
                    # the scratch dir; refilling now would park a fresh
                    # jax-loaded spare forever (the orphan self-reap only
                    # fires on parent-PID change, and the parent lives).
                    return
                self._prune_spares_locked(sig)
                if len(self._standby) >= self._pool_size:
                    return
                if self._standby_dir is None:
                    self._standby_dir = tempfile.mkdtemp(
                        prefix="edl_standby_"
                    )
                self._standby_seq += 1
                go_file = os.path.join(
                    self._standby_dir, f"go.{self._standby_seq}"
                )
            env = {
                k: v
                for k, v in full_env.items()
                if k not in self._IDENTITY_KEYS
            }
            env["ELASTICDL_STANDBY_GO_FILE"] = go_file
            log = self._pod_stdio(f"standby.{os.path.basename(go_file)}")
            try:
                proc = subprocess.Popen(
                    self._argv, env=env, stdout=log,
                    stderr=subprocess.STDOUT if log else None,
                )
            finally:
                if log is not None:
                    log.close()  # the child keeps its own fd
            with self._lock:
                # Popen ran outside the lock, so a concurrent start_pod
                # (scale() on the main thread racing a relaunch on the
                # watcher thread) may have topped the pool up meanwhile —
                # an over-full pool would orphan the extras (review r5).
                # Same for a concurrent close(): the spare must die, not
                # park in a scratch dir close() already removed.
                self._prune_spares_locked(sig)
                if self._stop.is_set() or len(self._standby) >= self._pool_size:
                    proc.kill()  # lost the race; pool full or closing
                    self._reap(proc)
                    return
                self._standby.append((proc, go_file, sig))
                depth = len(self._standby)
            logger.info("warm standby parked (pid %d)", proc.pid)
            trace.instant(
                f"standby:{reason}", cat="standby", pid=proc.pid, depth=depth
            )

    def start_pod(self, name: str, env: Dict[str, str]) -> None:
        full_env = dict(os.environ) if self._inherit else {}
        full_env.update(env)
        proc = self._adopt_standby(name, full_env) if self._warm else None
        adopted = proc is not None
        if proc is None:
            log = self._pod_stdio(name)
            try:
                proc = subprocess.Popen(
                    self._argv, env=full_env, stdout=log,
                    stderr=subprocess.STDOUT if log else None,
                )
            finally:
                if log is not None:
                    log.close()
        if self._warm:
            self._fill_standby_pool(
                full_env, reason="refill" if adopted else "spawn"
            )
        with self._lock:
            self._procs[name] = proc
            if self._watcher is None:
                self._watcher = threading.Thread(
                    target=self._watch, name="pod-watcher", daemon=True
                )
                self._watcher.start()
        self._emit(name, PodPhase.RUNNING)

    def adopt_pod(self, name: str, pid: int) -> None:
        """Re-attach to a live orphan of a previous master (r18 crash
        survivability): supervision continues — liveness via kill(0)
        polling, teardown via signals — WITHOUT spawning a duplicate
        worker next to the one riding out the restart.  The pod's worker
        process notices nothing: it re-registers with the new master
        through its own proxy reconnect."""
        with self._lock:
            self._adopted[name] = pid
            if self._watcher is None:
                self._watcher = threading.Thread(
                    target=self._watch, name="pod-watcher", daemon=True
                )
                self._watcher.start()
        logger.info("adopted orphan pod %s (pid %d)", name, pid)
        trace.instant("pod:adopt", cat="elastic", pod=name, pid=pid)
        self._emit(name, PodPhase.RUNNING)

    _pid_alive = staticmethod(pid_alive)

    #: SIGTERM->SIGKILL grace on delete: must exceed the worker's
    #: preemption-snapshot bound (worker.main PREEMPTION_EXIT_S = 15 s) or
    #: a scale-down would tear the snapshot it just triggered mid-write.
    #: wait() returns the moment the pod exits, so pods without state to
    #: save (PS shards, group members) still tear down in milliseconds.
    TERMINATE_GRACE_S = 20.0

    def delete_pod(self, name: str) -> None:
        with self._lock:
            proc = self._procs.pop(name, None)
            adopted_pid = self._adopted.pop(name, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=self.TERMINATE_GRACE_S)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5)
        elif adopted_pid is not None:
            # Not our child: no wait() — SIGTERM, poll liveness through
            # the same grace the child path gets, then SIGKILL.
            self._signal_adopted(adopted_pid)
        self._emit(name, PodPhase.DELETED)

    def _signal_adopted(self, pid: int) -> None:
        import signal

        try:
            os.kill(pid, signal.SIGTERM)
        except OSError:
            return  # already gone
        deadline = time.monotonic() + self.TERMINATE_GRACE_S
        while time.monotonic() < deadline:
            if not self._pid_alive(pid):
                return
            time.sleep(0.1)
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass

    def _watch(self) -> None:
        while not self._stop.is_set():
            try:
                done = []
                lost = []
                with self._lock:
                    for name, proc in self._procs.items():
                        rc = proc.poll()
                        if rc is not None:
                            done.append((name, rc))
                    for name, _ in done:
                        del self._procs[name]
                    for name, pid in list(self._adopted.items()):
                        if not self._pid_alive(pid):
                            lost.append((name, pid))
                            del self._adopted[name]
                for name, pid in lost:
                    # Exit code unknowable (never our child): LOST, which
                    # PodManager resolves against job state.
                    logger.info(
                        "adopted pod %s (pid %d) disappeared -> %s",
                        name, pid, PodPhase.LOST,
                    )
                    self._emit(name, PodPhase.LOST)
                for name, rc in done:
                    if rc == 0:
                        phase = PodPhase.SUCCEEDED
                    elif rc == WORKER_RESTART_EXIT_CODE:
                        phase = PodPhase.RESTART
                    else:
                        phase = PodPhase.FAILED
                    # The exit code is the only forensic a silently-dying
                    # pod leaves (negative = killed by that signal); the
                    # chaos work made clear the watcher must say it.
                    logger.info("pod %s exited rc=%s -> %s", name, rc, phase)
                    self._emit(name, phase)
            except Exception:
                # The watcher is the only observer of worker exits; it must
                # survive any emit-chain error or elasticity silently dies.
                logger.exception("pod watcher iteration failed")
            time.sleep(self._poll)

    def pid(self, name: str) -> Optional[int]:
        with self._lock:
            proc = self._procs.get(name)
            if proc is not None:
                return proc.pid
            return self._adopted.get(name)

    def standby_depth(self) -> Optional[int]:
        """Live parked spares right now (the Heartbeat/JobStatus gauge);
        None when warm standby is off — "no pool" and "drained pool" must
        not read the same."""
        if not self._warm:
            return None
        with self._lock:
            return sum(1 for p, _, _ in self._standby if p.poll() is None)

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
            procs.extend(p for p, _, _ in self._standby)
            self._standby = []
            adopted = list(self._adopted.values())
            self._adopted.clear()
            standby_dir, self._standby_dir = self._standby_dir, None
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                self._reap(proc)
        for pid in adopted:
            if self._pid_alive(pid):
                import signal

                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
        if standby_dir is not None:
            import shutil

            shutil.rmtree(standby_dir, ignore_errors=True)


def render_base_pod_manifest(
    job_name: str,
    pod_name: str,
    replica_type: str,
    image: str,
    command: List[str],
    env: Dict[str, str],
) -> dict:
    """Common V1Pod scaffold for master and worker pods (labels, restart
    policy, env plumbing).  Always injects ``MY_POD_IP`` via the downward
    API: the master advertises it to workers (Master._advertise_host), and
    having it everywhere keeps the two renderers from drifting."""
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod_name,
            "labels": {
                "app": "elasticdl-tpu",
                "elasticdl-job-name": job_name,
                "elasticdl-replica-type": replica_type,
            },
        },
        "spec": {
            "restartPolicy": "Never",  # relaunch policy lives in PodManager
            "containers": [
                {
                    "name": replica_type,
                    "image": image,
                    "command": command,
                    "env": [
                        {
                            "name": "MY_POD_IP",
                            "valueFrom": {
                                "fieldRef": {"fieldPath": "status.podIP"}
                            },
                        }
                    ]
                    + [{"name": k, "value": v} for k, v in sorted(env.items())],
                }
            ],
        },
    }


def render_worker_pod_manifest(
    config: JobConfig,
    pod_name: str,
    env: Dict[str, str],
    image: str = "elasticdl-tpu:latest",
    tpu_topology: str = "2x4",
    tpu_accelerator: str = "tpu-v5-lite-podslice",
    tpu_chips_per_host: int = 4,
) -> dict:
    """A Kubernetes V1Pod-shaped dict for one TPU worker host.

    Mirrors the reference's master-rendered worker pod spec (SURVEY.md §3.1),
    retargeted at GKE TPU node pools: the ``google.com/tpu`` resource plus the
    podslice node selectors replace the reference's GPU resource requests.
    """
    manifest = render_base_pod_manifest(
        config.job_name,
        pod_name,
        "worker",
        image,
        ["python", "-m", "elasticdl_tpu.worker.main"],
        env,
    )
    manifest["spec"]["nodeSelector"] = {
        "cloud.google.com/gke-tpu-accelerator": tpu_accelerator,
        "cloud.google.com/gke-tpu-topology": tpu_topology,
    }
    manifest["spec"]["containers"][0]["resources"] = {
        "requests": {"google.com/tpu": str(tpu_chips_per_host)},
        "limits": {"google.com/tpu": str(tpu_chips_per_host)},
    }
    return manifest


def render_ps_pod_manifest(
    config: JobConfig,
    pod_name: str,
    env: Dict[str, str],
    image: str = "elasticdl-tpu:latest",
) -> dict:
    """A V1Pod dict for one PS shard (ps/main.py): CPU-only — no TPU
    resources or node selectors — with the shard's memory dominated by its
    host-tier table slice.  Cross-pod reachability relies on a headless
    service named ``<job>-ps`` governing these pods (master/main.py renders
    shard addresses as ``<pod>.<job>-ps.<namespace>:2222``)."""
    manifest = render_base_pod_manifest(
        config.job_name,
        pod_name,
        "ps",
        image,
        ["python", "-m", "elasticdl_tpu.ps.main"],
        env,
    )
    # Per-pod DNS under the headless service needs BOTH hostname and
    # subdomain on the pod spec.  The hostname is derived from the SHARD
    # slot, not the pod name: a relaunched shard gets a fresh pod name
    # (slot-gen suffix, PodManager._new_pod_locked) but must keep answering
    # at the address the master advertised to workers at job start.
    slot = env.get("ELASTICDL_WORKER_SLOT", "0")
    manifest["spec"]["hostname"] = f"{config.job_name}-ps-{slot}"
    manifest["spec"]["subdomain"] = f"{config.job_name}-ps"
    return manifest


class KubernetesPodBackend(PodBackend):
    """Drives rendered manifests through the kubernetes python client.

    Import-gated: constructing it without the ``kubernetes`` package raises —
    the manifest renderer above stays testable anywhere.  ``renderer`` picks
    the manifest shape (worker TPU pods by default; ``render_ps_pod_manifest``
    for PS shards).
    """

    def __init__(
        self,
        config: JobConfig,
        namespace: str = "default",
        renderer: Callable[..., dict] = render_worker_pod_manifest,
        **render_kwargs,
    ):
        try:
            import kubernetes  # type: ignore
        except ImportError as e:  # pragma: no cover - not installed in image
            raise RuntimeError(
                "KubernetesPodBackend requires the 'kubernetes' package; "
                "use ProcessPodBackend for local jobs"
            ) from e
        kubernetes.config.load_incluster_config()
        self._core = kubernetes.client.CoreV1Api()
        self._ns = namespace
        self._config = config
        self._renderer = renderer
        self._render_kwargs = render_kwargs
        self._stop = threading.Event()
        self._watcher = threading.Thread(
            target=self._watch, name="k8s-watcher", daemon=True
        )
        self._watcher.start()

    def start_pod(self, name: str, env: Dict[str, str]) -> None:  # pragma: no cover
        manifest = self._renderer(
            self._config, name, env, **self._render_kwargs
        )
        self._core.create_namespaced_pod(self._ns, manifest)

    def delete_pod(self, name: str) -> None:  # pragma: no cover
        self._core.delete_namespaced_pod(name, self._ns)
        self._emit(name, PodPhase.DELETED)

    def _watch(self) -> None:  # pragma: no cover — raw API calls only
        import kubernetes  # type: ignore

        watch = kubernetes.watch.Watch()
        selector = f"elasticdl-job-name={self._config.job_name}"

        def stream():
            return watch.stream(
                self._core.list_namespaced_pod,
                self._ns,
                label_selector=selector,
                timeout_seconds=30,
            )

        run_watch_loop(stream, self._emit, self._stop)

    def close(self) -> None:  # pragma: no cover
        self._stop.set()


def map_watch_event(event) -> tuple:
    """One k8s watch event -> (pod_name, PodPhase) for the slot table.

    k8s has no 'Restart' phase: a worker exiting with
    WORKER_RESTART_EXIT_CODE (multihost elastic re-join) shows as Failed —
    map it back to RESTART from the container's terminated exit code so
    membership changes don't consume the slot's relaunch budget.  Unit-
    tested against synthetic events (tests/test_pod_manager.py); the
    in-cluster path differs only in where events come from.
    """
    pod = event["object"]
    phase = pod.status.phase
    if phase == PodPhase.FAILED:
        try:
            statuses = pod.status.container_statuses or []
            term = (
                statuses[0].state.terminated
                if statuses and statuses[0].state
                else None
            )
            if term is not None and term.exit_code == WORKER_RESTART_EXIT_CODE:
                phase = PodPhase.RESTART
        except Exception:
            logger.exception(
                "could not read exit code of failed pod %s", pod.metadata.name
            )
    return pod.metadata.name, phase


def run_watch_loop(stream_factory, emit, stop, backoff_s: float = 1.0) -> None:
    """Drive watch events into ``emit`` until ``stop`` is set.

    ``stream_factory`` opens a fresh event stream each round; it raising
    (410 Gone on resourceVersion expiry, transient apiserver errors) just
    re-establishes the watch after ``backoff_s`` instead of killing the
    thread — the reference master's pod-watch loop survives the same way.
    """
    while not stop.is_set():
        try:
            for event in stream_factory():
                emit(*map_watch_event(event))
                if stop.is_set():
                    return
        except Exception:
            logger.exception("k8s watch stream failed; re-watching")
            stop.wait(backoff_s)


# racesan (r16): fleet state lives under _lock; _listeners is
# append-at-wiring (master main, before scale()) and iterated on
# watcher threads — single-op atomic by declaration, like the
# rendezvous listener list.
@racesan.instrument(atomic=("_listeners",))
class PodManager:
    """Slot-based worker fleet: start, watch, relaunch, scale.

    Each of the ``desired`` slots holds at most one live pod.  A FAILED pod is
    relaunched into its slot (fresh pod name, as k8s would) while its relaunch
    budget lasts; SUCCEEDED/DELETED pods retire their slot's current pod
    without relaunch.  ``scale(n)`` adds slots or deletes the highest ones —
    the 4→8→4 elasticity path.
    """

    #: Canonical registry filename (module constant re-exported where the
    #: wiring already has the class in hand).
    REGISTRY_FILENAME = REGISTRY_FILENAME

    def __init__(
        self,
        backend: PodBackend,
        config: JobConfig,
        worker_env: Optional[Dict[str, str]] = None,
        name_prefix: Optional[str] = None,
        state_path: Optional[str] = None,
    ):
        self._backend = backend
        self._config = config
        self._env = dict(worker_env or {})
        self._prefix = name_prefix or f"{config.job_name}-worker"
        self._lock = locksan.lock("PodManager._lock", leaf=True)  # lock-order: leaf
        self._slots: Dict[int, Optional[PodInfo]] = {}  # guarded-by: _lock
        self._by_name: Dict[str, PodInfo] = {}  # guarded-by: _lock
        # Pod reattach registry (r18 master crash survivability): the
        # per-slot (name, pid, gen, cmdline) of every live pod, persisted
        # to ``state_path`` so supervision OUTLIVES this master process —
        # a restarted master ADOPTS the still-running orphans (backend
        # adopt_pod: kill(0)-polled liveness, signal teardown) instead of
        # spawning a duplicate fleet beside the workers riding out the
        # restart.  None = no persistence (pre-r18 behavior).
        self._state_path = state_path
        self._reattach: Dict[str, dict] = self._load_registry()  # guarded-by: _lock
        # Resolves an adopted pod's unknowable exit (PodPhase.LOST): the
        # master wires servicer.job_finished here — a disappearance after
        # the job is done is a clean exit, before it is a crash.
        self._job_finished_fn: Optional[Callable[[], bool]] = None
        # Per-slot launch generation, NEVER reset (survives scale-down/up
        # cycles): every pod a slot ever gets has a unique name, so late
        # events for a retired pod can't resolve to its successor and a k8s
        # backend can't hit a name conflict with a terminating pod.
        self._slot_gen: Dict[int, int] = {}  # guarded-by: _lock
        self._desired = 0  # guarded-by: _lock
        self._listeners: List[PodListener] = []
        self._retry_timers: List[threading.Timer] = []  # guarded-by: _lock
        self._relaunch = config.relaunch_on_worker_failure
        self._max_relaunch = config.max_worker_relaunch
        backend.set_event_callback(self._on_event)

    # -- listeners (master main wires rendezvous.remove here) --

    def add_listener(self, fn: PodListener) -> None:
        self._listeners.append(fn)

    def set_job_finished_fn(self, fn: Callable[[], bool]) -> None:
        """Wire the LOST-resolution probe (see _on_event); called at
        wiring time, before any pod events flow."""
        self._job_finished_fn = fn

    # -- reattach registry (r18) --

    # recovery-path
    def _load_registry(self) -> Dict[str, dict]:
        if not self._state_path or not os.path.exists(self._state_path):
            return {}
        data = durable.read_json_tolerant(self._state_path)
        if not isinstance(data, dict):
            logger.warning(
                "unreadable pod registry %s; ignoring", self._state_path
            )
            return {}
        try:
            return {
                str(k): dict(v) for k, v in (data.get("slots") or {}).items()
            }
        except (TypeError, ValueError, AttributeError):
            logger.warning(
                "malformed pod registry %s; ignoring", self._state_path
            )
            return {}

    _proc_cmdline = staticmethod(proc_cmdline)

    # recovery-path
    @staticmethod
    def scan_registry(state_path: Optional[str]) -> dict:
        """One-shot registry liveness scan (r18): ``{"recorded": n,
        "alive": [pids], "dead": [pids]}`` with the SAME adoptability
        probe ``_adoptable_locked`` applies (zombie + cmdline-fingerprint
        guarded pid_alive) — Master's whole-job-restart decision and any
        tool read the fleet's fate through this one definition."""
        out = {"recorded": 0, "alive": [], "dead": []}
        if not state_path or not os.path.exists(state_path):
            return out
        data = durable.read_json_tolerant(state_path)
        if not isinstance(data, dict):
            return out
        try:
            slots = (data.get("slots") or {}).values()
        except AttributeError:
            return out
        for s in slots:
            if not isinstance(s, dict):
                continue
            pid = s.get("pid")
            if not isinstance(pid, int) or pid <= 0:
                continue
            out["recorded"] += 1
            bucket = (
                "alive" if pid_alive(pid, cmdline=s.get("cmdline")) else "dead"
            )
            out[bucket].append(pid)
        return out

    def _persist_registry(self) -> None:
        """Atomically persist the live-pod table.  Reads pids OUTSIDE the
        manager lock (the backend takes its own): the registry is
        advisory — a torn race loses one adoption opportunity, never
        correctness (the unmatched orphan is simply not adopted and the
        slot cold-spawns beside it only if its pid probe failed, i.e. it
        was already gone)."""
        if not self._state_path:
            return
        with self._lock:
            live = [
                (i.slot, i.name, i.relaunches, self._slot_gen.get(i.slot, 0))
                for i in self._slots.values()
                if i is not None and i.phase not in PodPhase.TERMINAL
            ]
        pid_fn = getattr(self._backend, "pid", None)
        slots = {}
        for slot, name, relaunches, gen in live:
            pid = pid_fn(name) if pid_fn is not None else None
            if pid is None:
                continue
            slots[str(slot)] = {
                "name": name, "pid": pid, "relaunches": relaunches,
                "gen": gen, "cmdline": self._proc_cmdline(pid),
            }
        try:
            # durable.atomic_publish's thread-unique temp matters HERE: the
            # watcher thread's terminal-event persist can race a
            # scale()/launch persist IN THIS PROCESS — a shared pid-only
            # temp name would let them interleave writes and os.replace
            # corrupt JSON into the registry, which the next master's scan
            # would read as "no evidence" and pick a FULL replay for a
            # genuinely dead fleet.  (It also adds the fsyncs the old
            # hand-rolled copy skipped.)
            durable.atomic_publish_json(
                self._state_path, {"slots": slots}, sort_keys=True
            )
        except OSError:
            # Advisory state: a failed write costs the NEXT master its
            # adoption shortcut, never this one its launch.
            logger.exception("pod registry write failed (%s)", self._state_path)

    def _adoptable_locked(self, entry: dict) -> bool:  # guarded-by: _lock
        if not hasattr(self._backend, "adopt_pod"):
            return False
        pid = entry.get("pid")
        if not isinstance(pid, int) or pid <= 0:
            return False
        return pid_alive(pid, cmdline=entry.get("cmdline"))

    def _notify(self, name: str, phase: str) -> None:
        for fn in self._listeners:
            try:
                fn(name, phase)
            except Exception:
                # Listeners run on backend watcher threads; see _on_event.
                logger.exception("pod listener failed for %s/%s", name, phase)

    # -- fleet control --

    def start(self, num_workers: Optional[int] = None) -> None:
        self.scale(num_workers or self._config.num_workers)

    def scale(self, n: int) -> None:
        """Grow or shrink the fleet to ``n`` worker slots."""
        if n < 0:
            raise ValueError("cannot scale below 0 workers")
        to_start: List[PodInfo] = []
        to_delete: List[str] = []
        to_adopt: List[tuple] = []
        with self._lock:
            old = self._desired
            self._desired = n
            for slot in range(old, n):  # grow
                # Reattach first (r18): a live orphan of the pre-restart
                # master fills the slot WITHOUT a duplicate spawn — the
                # worker in it is already riding out the restart on its
                # proxy reconnect.  The registry entry is one-shot; a
                # dead/reused pid falls through to a normal launch.
                entry = self._reattach.pop(str(slot), None)
                if entry is not None:
                    # Seed the slot's generation from the registry EITHER
                    # way: a dead entry falls through to a fresh launch,
                    # and reusing the dead generation's exact pod name
                    # would break the every-pod-unique-name invariant
                    # (late events for the retired pod would resolve to
                    # its unrelated successor, and the successor's worker
                    # id would collide with the dead incarnation's).
                    gen = int(entry.get("gen", 0))
                    self._slot_gen[slot] = max(
                        self._slot_gen.get(slot, -1), gen
                    )
                if entry is not None and self._adoptable_locked(entry):
                    info = PodInfo(
                        name=entry["name"], slot=slot,
                        relaunches=int(entry.get("relaunches", 0)),
                    )
                    self._slots[slot] = info
                    self._by_name[info.name] = info
                    to_adopt.append((info, int(entry["pid"])))
                    continue
                info = self._new_pod_locked(slot, relaunches=0)
                to_start.append(info)
            for slot in range(n, old):  # shrink: retire highest slots
                info = self._slots.pop(slot, None)
                if info is not None and info.phase not in PodPhase.TERMINAL:
                    to_delete.append(info.name)
        for info, pid in to_adopt:
            self._backend.adopt_pod(info.name, pid)
        for info in to_start:
            self._launch(info)
        for name in to_delete:
            self._backend.delete_pod(name)
        if to_adopt or to_start or to_delete:
            self._persist_registry()
        if n != old:
            logger.info(
                "scaled worker fleet %d -> %d%s", old, n,
                f" ({len(to_adopt)} slot(s) re-attached to live orphans)"
                if to_adopt else "",
            )

    # How many times a single pod launch is retried against backend errors
    # (transient k8s API outages, fork failures) before the failure is
    # surfaced as a budget-consuming FAILED event.  The backoff schedule
    # (1+2+4+8+16+30+30 = ~91s) outlasts a ~1-minute apiserver outage.
    MAX_START_ATTEMPTS = 8

    def _launch(self, info: PodInfo, attempt: int = 0) -> None:
        """start_pod with bounded backoff retries for the SAME PodInfo.

        A launch that throws is retried directly — NOT turned into a FAILED
        pod event — so a ~1-minute transient k8s API outage doesn't eat the
        slot's relaunch budget (and budget-free RESTART relaunches stay
        budget-free).  Only after MAX_START_ATTEMPTS does it degrade to the
        normal failure path.
        """
        with self._lock:
            if self._slots.get(info.slot) is not info:
                return  # slot was scaled away or superseded while backing off
        try:
            self._backend.start_pod(info.name, self._pod_env(info))
            self._persist_registry()
        except Exception:
            logger.exception(
                "launch of %s failed (attempt %d/%d)",
                info.name, attempt + 1, self.MAX_START_ATTEMPTS,
            )
            if attempt + 1 >= self.MAX_START_ATTEMPTS:
                self._on_event(info.name, PodPhase.FAILED)
                return
            delay = min(2.0 ** attempt, 30.0)
            timer = threading.Timer(delay, self._launch, (info, attempt + 1))
            timer.daemon = True
            with self._lock:
                # Prune timers that already fired or were cancelled so the
                # list stays bounded.  `finished` (set after run or cancel)
                # is the right predicate: is_alive() is also False for
                # appended-but-not-yet-started timers, which must stay
                # cancellable by stop().
                self._retry_timers = [
                    t for t in self._retry_timers if not t.finished.is_set()
                ]
                self._retry_timers.append(timer)
            timer.start()

    def _new_pod_locked(self, slot: int, relaunches: int) -> PodInfo:  # guarded-by: _lock
        gen = self._slot_gen.get(slot, -1) + 1
        self._slot_gen[slot] = gen
        suffix = f"-r{gen}" if gen else ""
        info = PodInfo(
            name=f"{self._prefix}-{slot}{suffix}",
            slot=slot,
            relaunches=relaunches,
        )
        self._slots[slot] = info
        self._by_name[info.name] = info
        return info

    def _pod_env(self, info: PodInfo) -> Dict[str, str]:
        env = dict(self._env)
        env.update(self._config.to_env())
        env["ELASTICDL_WORKER_ID"] = info.name
        env["ELASTICDL_WORKER_SLOT"] = str(info.slot)
        return env

    def stop(self) -> None:
        with self._lock:
            self._desired = 0
            for timer in self._retry_timers:
                timer.cancel()
            self._retry_timers.clear()
            live = [
                i.name
                for i in self._slots.values()
                if i is not None and i.phase not in PodPhase.TERMINAL
            ]
            self._slots.clear()
        for name in live:
            self._backend.delete_pod(name)
        self._backend.close()
        if self._state_path:
            # A CLEAN stop tears the fleet down — leaving the registry
            # behind would point the next master at recycled pids.
            try:
                os.remove(self._state_path)
            except OSError:
                pass

    # -- event handling --

    def _on_event(self, name: str, phase: str) -> None:
        if phase == PodPhase.LOST:
            # Adopted-orphan disappearance: the exit code is unknowable
            # (never this process's child).  After the job is finished a
            # disappearance IS the worker's clean exit; before it, treat
            # as a crash so the relaunch/requeue machinery engages.
            fn = self._job_finished_fn
            phase = (
                PodPhase.SUCCEEDED
                if fn is not None and fn()
                else PodPhase.FAILED
            )
            logger.info(
                "adopted pod %s lost -> resolved %s (exit code "
                "unknowable for a re-attached orphan)", name, phase,
            )
        relaunch_info: Optional[PodInfo] = None
        with self._lock:
            info = self._by_name.get(name)
            if info is None:
                return
            info.phase = phase
            if phase == PodPhase.RESTART:
                # Requested restart (multihost elastic re-join): relaunch
                # into the slot without touching the failure budget.
                if self._slots.get(info.slot) is info:
                    relaunch_info = self._new_pod_locked(
                        info.slot, info.relaunches
                    )
            elif phase == PodPhase.FAILED:
                in_fleet = self._slots.get(info.slot) is info
                if (
                    in_fleet
                    and self._relaunch
                    and info.relaunches < self._max_relaunch
                ):
                    relaunch_info = self._new_pod_locked(
                        info.slot, info.relaunches + 1
                    )
                elif in_fleet:
                    self._slots[info.slot] = None
                    logger.warning(
                        "pod %s failed with relaunch budget exhausted", name
                    )
            elif phase in (PodPhase.SUCCEEDED, PodPhase.DELETED):
                if self._slots.get(info.slot) is info:
                    self._slots[info.slot] = None
        if phase == PodPhase.FAILED:
            # The splice timeline's t0: the master KNOWS the pod is gone.
            # chaos_bench decomposes recovery as detect -> adopt ->
            # reformed -> trained-again from these master-clock instants
            # (the dying worker's own chaos:kill instant never ships —
            # its buffer dies with it).
            trace.instant(
                "elastic:splice", cat="elastic", stage="detect", pod=name,
                slot=info.slot,
                relaunch=relaunch_info.name if relaunch_info else None,
            )
        self._notify(name, phase)
        if relaunch_info is not None:
            logger.info(
                "relaunching failed pod %s as %s (relaunch %d/%d)",
                name, relaunch_info.name,
                relaunch_info.relaunches, self._max_relaunch,
            )
            # _launch retries transient backend errors for this same PodInfo
            # without unwinding into the watcher thread (the only thread
            # observing pod events) and without consuming relaunch budget.
            self._launch(relaunch_info)
        elif phase in PodPhase.TERMINAL:
            # A retired pod must leave the reattach registry NOW: a later
            # master adopting its recycled pid would supervise a stranger.
            self._persist_registry()

    # -- introspection --

    def live_pods(self) -> List[str]:
        with self._lock:
            return sorted(
                i.name
                for i in self._slots.values()
                if i is not None and i.phase not in PodPhase.TERMINAL
            )

    def desired(self) -> int:
        with self._lock:
            return self._desired

    def pod_info(self, name: str) -> Optional[PodInfo]:
        with self._lock:
            return self._by_name.get(name)

    def standby_depth(self) -> Optional[int]:
        """Warm-standby pool depth, or None when the backend has no pool
        (fake/kubernetes backends, warm standby off)."""
        fn = getattr(self._backend, "standby_depth", None)
        return fn() if fn is not None else None

    def counts(self) -> Dict[str, int]:
        """Fleet-state scalars for the live metrics plane (the master's
        /metrics collector, master/main.py): desired slots, live pods, and
        the summed relaunch generations — churn made a readable number."""
        with self._lock:
            infos = [i for i in self._slots.values() if i is not None]
            return {
                "desired": self._desired,
                "live": sum(
                    1 for i in infos if i.phase not in PodPhase.TERMINAL
                ),
                "relaunches": sum(i.relaunches for i in infos),
            }

    def all_finished(self) -> bool:
        """True when every slot's pod has reached a terminal phase."""
        with self._lock:
            return all(
                i is None or i.phase in PodPhase.TERMINAL
                for i in self._slots.values()
            )
