"""Durable control-plane journal (r18) — master crash survivability.

Before r18 the master was the repo's last single point of failure: the
dispatcher's only durable state was the coarse task-progress watermark
(``job_progress.json``), persisted ONLY at model-checkpoint reports — a
master crash lost every hand-out, report, requeue, gang-log entry and
skip-budget charge since, and a restarted master could do no better than
"skip finished epochs, lose the in-flight shards".  This module is the
fsync'd append-only WAL that closes the gap: every control-plane mutation
records one JSON line, and a restarted master replays the file to the
EXACT pre-crash dispatcher/servicer state (bit-identical, pinned by
tests/test_master_restart.py), then reconciles reconnecting workers'
leases against it.

File format (``<checkpoint_dir>/master_journal.wal``)::

    {"kind": "base", "dispatcher": <full snapshot, incl. the job-shape
     guard: num_shards/num_epochs/task_type>, "group_version": v|null,
     "group_log": [...], "model_version": n, "membership_version": n,
     "report_seqs": {...}, "restarts": k}
    {"kind": "handout", "worker": w, "tasks": [<task dict>, ...]}
    {"kind": "report", "task_id": i, "success": b, "worker": w,
     "requeue": b, "seq": n?}
    {"kind": "recover"|"skip", "worker": w}
    {"kind": "timeout", "tasks": [ids]}
    {"kind": "reconcile", "worker": w, "held": [ids]}
    {"kind": "stop"}
    {"kind": "group_entry", "seq": i, "entry": {...}}
    {"kind": "group_version", "version": v|null}
    {"kind": "membership", "version": n}
    {"kind": "model_version", "version": n}
    {"kind": "report_seq", "worker": w, "seq": n}
    {"kind": "incarnation", "worker": w, "incarnation": s}
    {"kind": "restart"}

Durability/appends: records go through ONE ``os.write`` on an
``O_APPEND`` fd (atomic appends — writers in different lock domains
cannot interleave partial lines) followed by ``fsync``; no journal-level
lock exists, because every recording site already holds its own
subsystem lock and rotation holds ALL of them (see
``MasterServicer.rotate_journal``), which serializes the fd swap against
every writer.

Compaction: the WAL is rotated — a fresh file whose ``base`` record is
the CURRENT full state — every time the coarse watermark persists (the
checkpoint-coupled ``Master._persist_progress``), so the journal stays
bounded by the control-plane traffic of one checkpoint interval and the
two durable artifacts can never disagree for long.  The watermark file
stays: it is the fallback when the journal is missing or corrupt, and
the consistency anchor tying task progress to the restorable model step.

Torn tails (the r12 MetricsWriter stance): a crash mid-append may leave a
torn FINAL line — replay tolerates exactly that (the event was never
acknowledged to anyone).  Garbage MID-file is corruption, not a crash
tail, and raises ``JournalError`` so the master falls back to the
watermark loudly instead of replaying half a history.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

from elasticdl_tpu.common import durable
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.master.task_dispatcher import (
    JournalReplayError,
    TaskDispatcher,
)

logger = get_logger("master.journal")

JOURNAL_FILENAME = "master_journal.wal"  # durable-file


class JournalError(RuntimeError):
    """The journal file is unusable (mid-file corruption, missing/broken
    base record).  Restart falls back to the coarse watermark."""


class MasterJournal:
    """Append-only fsync'd writer over one O_APPEND fd.

    ``record`` is safe from any thread that holds ITS OWN subsystem lock
    (dispatcher/servicer/group): the single-``os.write`` append is atomic
    at the file level, and ``rotate`` — the only fd swap — runs with all
    of those locks held (MasterServicer.rotate_journal), so no recording
    can straddle a rotation.  ``fsync=False`` exists for tests that
    measure everything but the disk."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self._fsync = fsync
        self._fd: Optional[int] = None

    def _open(self) -> None:
        self._fd = durable.open_append(self.path)

    def record(self, ev: dict) -> None:
        """Append one event line and make it durable before returning —
        a mutation acknowledged to a worker must survive the crash that
        follows it."""
        if self._fd is None:
            self._open()
        data = json.dumps(ev, sort_keys=True) + "\n"
        try:
            durable.append_durable(
                self._fd, data, fsync=self._fsync, path=self.path
            )
        except durable.ShortWriteError as e:
            # A short write (signal mid-progress, disk full) left a torn
            # line that later appends would bury MID-file — which replay
            # rightly treats as corruption.  durable.append_durable
            # already refused to finish the line (finishing would
            # interleave with other lock domains' appends); surface it as
            # the journal's own error class: the caller's RPC errors, the
            # worker retries, and the record commits whole or not at all.
            raise JournalError(str(e)) from e

    def rotate(self, base: dict) -> None:
        """Compaction: atomically replace the WAL with a fresh file whose
        only record is ``base`` (the CURRENT full state).  The
        durable.atomic_publish commit — a crash mid-rotate leaves either
        the complete old journal or the complete new one."""
        payload = json.dumps(dict(base, kind="base"), sort_keys=True) + "\n"
        durable.atomic_publish(self.path, payload)
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
        self._open()

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


# recovery-path
def read_journal(path: str):
    """Parse the WAL into ``(base, events, torn_tail)``.

    A torn FINAL line is tolerated (crash mid-append; the event was never
    acknowledged); unparseable content anywhere else raises
    ``JournalError`` — corruption must fall back loudly, never replay a
    partial history as if it were whole.  The tolerance itself is the
    shared reader (durable.read_wal) so the stance cannot drift per WAL."""
    try:
        records, torn = durable.read_wal(path)
    except durable.CorruptWalError as e:
        raise JournalError(str(e)) from e
    if not records or not isinstance(records[0], dict) or (
        records[0].get("kind") != "base"
    ):
        raise JournalError(
            f"journal {path} has no base record — refusing to replay"
        )
    return records[0], records[1:], torn


@dataclasses.dataclass
class ReplayResult:
    """Everything a restarted master adopts from the WAL."""

    dispatcher: TaskDispatcher
    group_version: Optional[int]
    group_log: List[dict]
    model_version: int
    membership_version: int
    report_seqs: Dict[str, int]
    incarnations: Dict[str, str]
    restarts: int
    events_applied: int
    torn_tail: bool


def replay(
    path: str,
    shards,
    *,
    num_epochs: int,
    task_type: str,
    task_timeout_s: float,
    max_task_retries: int = 3,
    task_skip_budget: int = 2,
    base_only: bool = False,
) -> ReplayResult:
    """Rebuild the control plane from the WAL: restore the base snapshot
    into a fresh (journal-less) TaskDispatcher, then re-apply every event
    THROUGH the dispatcher's own mutation code (``replay_event``) so all
    derived transitions — epoch refills, retry/skip budgets, poison
    abandons, duplicate-done accounting — re-derive bit-exactly.  Raises
    ``JournalError``/``JournalReplayError`` when the file is corrupt or
    describes a different job; the caller falls back to the watermark.

    ``base_only`` restores the base snapshot and IGNORES the events: the
    whole-job-restart mode (Master._replay_journal).  The base is written
    at checkpoint-coupled rotation points, so it is consistent with the
    restorable MODEL; the events after it describe progress whose
    gradient updates lived only in worker memory — when the workers died
    with the master, replaying them would mark shards done that the
    restored model never saw (silent data loss).  Skipped-but-journaled
    work simply re-trains: at-least-once, the pre-r18 contract."""
    base, events, torn = read_journal(path)
    if base_only:
        events = []
    job = base.get("dispatcher") or {}
    if (
        job.get("num_shards") != len(shards)
        or job.get("num_epochs") != num_epochs
        or job.get("task_type") != task_type
    ):
        raise JournalReplayError(
            f"journal {path} is for a different job shape "
            f"({job.get('num_shards')} shards x {job.get('num_epochs')} "
            f"epochs, {job.get('task_type')!r} vs {len(shards)} x "
            f"{num_epochs}, {task_type!r})"
        )
    dispatcher = TaskDispatcher(
        shards,
        num_epochs=num_epochs,
        task_type=task_type,
        task_timeout_s=task_timeout_s,
        max_task_retries=max_task_retries,
        task_skip_budget=task_skip_budget,
        restore=base["dispatcher"],
    )
    group_version = base.get("group_version")
    group_log = list(base.get("group_log") or [])
    model_version = int(base.get("model_version") or 0)
    membership_version = int(base.get("membership_version") or 0)
    report_seqs = {
        str(w): int(s) for w, s in (base.get("report_seqs") or {}).items()
    }
    incarnations = {
        str(w): str(i) for w, i in (base.get("incarnations") or {}).items()
    }
    restarts = int(base.get("restarts") or 0)
    applied = 0
    for ev in events:
        kind = ev.get("kind")
        if kind == "group_version":
            group_version = ev["version"]
            group_log = []
        elif kind == "incarnation":
            # A fresh worker incarnation RESETS its seq ledger: without
            # replaying this, the base's dead-incarnation high seq would
            # max() back over the fresh process's low seqs and wrongly
            # dedup its reports.
            incarnations[ev["worker"]] = ev["incarnation"]
            report_seqs.pop(ev["worker"], None)
        elif kind == "restart":
            # A rotation-free restart (full replay keeps the old base):
            # counted on top of the base's restarts.
            restarts += 1
        elif kind == "group_entry":
            if int(ev["seq"]) != len(group_log):
                raise JournalReplayError(
                    f"group log gap: entry seq {ev['seq']} onto a log of "
                    f"{len(group_log)}"
                )
            group_log.append(ev["entry"])
        elif kind == "membership":
            membership_version = max(membership_version, int(ev["version"]))
        elif kind == "model_version":
            model_version = max(model_version, int(ev["version"]))
        elif kind == "report_seq":
            w = ev["worker"]
            report_seqs[w] = max(report_seqs.get(w, 0), int(ev["seq"]))
        else:
            if kind == "report" and ev.get("seq") is not None and ev.get(
                "worker"
            ):
                w = ev["worker"]
                report_seqs[w] = max(report_seqs.get(w, 0), int(ev["seq"]))
            dispatcher.replay_event(ev)
        applied += 1
    return ReplayResult(
        dispatcher=dispatcher,
        group_version=group_version,
        group_log=group_log,
        model_version=model_version,
        membership_version=membership_version,
        report_seqs=report_seqs,
        incarnations=incarnations,
        restarts=restarts,
        events_applied=applied,
        torn_tail=torn,
    )
