"""Elastic membership: the versioned worker list.

Reference parity (SURVEY.md §2 #5 [D: RendezvousServer named in BASELINE
north_star]): the reference's master hosts a rendezvous server from which
elastic Horovod re-initializes its NCCL/Gloo communicator after membership
changes.  TPU rebuild: the version bump is the signal for workers to rebuild
the ``jax.sharding.Mesh`` (parallel/mesh.MeshManager.reform) and re-place
state from the latest checkpoint — see worker/main loop and SURVEY.md §3.5.

Ranks are assigned deterministically (sorted worker ids) so every worker
derives the same mesh layout from the same membership version without extra
coordination.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common import locksan, racesan, trace


# racesan (r16): every mutable field lives under _lock; _listeners is
# append-at-wiring (before events flow) and list iteration/append are
# single-op atomic, so it is declared atomic rather than locked.
@racesan.instrument(atomic=("_listeners",))
class RendezvousServer:
    def __init__(
        self,
        heartbeat_timeout_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        # Listeners fire OUTSIDE this lock (see _notify) precisely so no
        # other lock is ever acquired under it.
        self._lock = locksan.lock("RendezvousServer._lock", leaf=True)  # lock-order: leaf
        self._workers: Dict[str, float] = {}  # worker_id -> last heartbeat
        # worker_id -> advertised host (multi-host: seeds the rank-0
        # jax.distributed coordinator; empty for single-host workers)
        self._addresses: Dict[str, str] = {}
        # worker_id -> latest membership version the worker has CONFIRMED
        # applying (via registration or a version-carrying heartbeat).  The
        # lockstep task log uses this to withhold collective tasks until the
        # whole world agrees on the topology — a member acting on a stale
        # view would leave its peers wedged inside a collective.
        self._confirmed: Dict[str, int] = {}
        self._version = 0
        self._timeout = heartbeat_timeout_s
        self._clock = clock
        self._listeners: List[Callable[[int, List[str]], None]] = []
        # Versions whose membership has been FULLY confirmed at least once
        # (every live member heartbeat/registered at that version) — each
        # gets one ``elastic:reformed`` instant, the splice timeline's
        # "the gang is whole again" stage (r13, docs/robustness.md).
        self._reformed: set = set()  # guarded-by: _lock
        # DESIRED world size (the pod manager's fleet target; 0 = unknown).
        # Workers' multihost settle loop forms the world the moment the
        # full expected gang is registered instead of heuristically waiting
        # for version stability — without it, staggered relaunches after a
        # failure form worlds one member at a time, and every late
        # registration restarts everyone who already formed (measured: a
        # 2-pod fleet recovery churned for 54 s on the 1-core harness).
        self._expected = 0

    def add_listener(self, fn: Callable[[int, List[str]], None]) -> None:
        """fn(version, sorted_worker_ids) fires on every membership change."""
        self._listeners.append(fn)

    def _notify(self, version: int, members: List[str]) -> None:
        for fn in self._listeners:
            fn(version, members)

    def register(self, worker_id: str, address: str = "", confirmed: bool = True) -> int:
        """Worker joins (or re-joins). Returns the new membership version.

        A re-registration with a CHANGED address also bumps the version:
        peers cache the coordinator address from the membership view, and a
        worker restarted on a new host within the heartbeat window would
        otherwise never be re-discovered.

        ``confirmed=False`` is the heartbeat-revival path: the worker is
        alive but has NOT (re)applied the current membership — stamping it
        confirmed would let the lockstep task log issue collective work to a
        world one member hasn't actually joined (split-brain).
        """
        with self._lock:
            changed = worker_id not in self._workers or (
                bool(address) and self._addresses.get(worker_id) != address
            )
            self._workers[worker_id] = self._clock()
            if address:
                self._addresses[worker_id] = address
            if not changed:
                if confirmed:
                    self._confirmed[worker_id] = self._version
                    self._maybe_reformed_locked()
                return self._version
            self._version += 1
            if confirmed:
                # Registration hands the worker this very version, so it
                # counts as confirmed; everyone else re-confirms by heartbeat.
                self._confirmed[worker_id] = self._version
            else:
                self._confirmed.pop(worker_id, None)
            self._maybe_reformed_locked()
            members = sorted(self._workers)
            version = self._version
        self._notify(version, members)
        return version

    def remove(self, worker_id: str) -> int:
        with self._lock:
            if worker_id not in self._workers:
                return self._version
            del self._workers[worker_id]
            self._addresses.pop(worker_id, None)
            self._confirmed.pop(worker_id, None)
            self._version += 1
            version, members = self._version, sorted(self._workers)
        self._notify(version, members)
        return version

    def heartbeat(self, worker_id: str, version: Optional[int] = None) -> int:
        """Refresh liveness; re-registers a worker the reaper evicted.

        ``version`` (when the caller sends one) records the membership
        version this worker has confirmed applying — see ``all_confirmed``.
        """
        with self._lock:
            if worker_id in self._workers:
                self._workers[worker_id] = self._clock()
                if version is not None:
                    self._confirmed[worker_id] = int(version)
                    self._maybe_reformed_locked()
                return self._version
        # Revival of an evicted worker: alive, but its address was dropped at
        # eviction and it has not applied the post-revival membership — so it
        # must NOT count as confirmed (the returned version differs from the
        # worker's own, which makes it re-read membership / restart).
        return self.register(worker_id, confirmed=False)

    def _maybe_reformed_locked(self) -> None:  # guarded-by: _lock
        """One ``elastic:reformed`` instant per version, the moment EVERY
        live member has confirmed it — the splice timeline's end of the
        membership transition (trace.instant is a lock-free ring append,
        so emitting under this leaf lock acquires nothing)."""
        v = self._version
        if v in self._reformed or not self._workers:
            return
        if all(self._confirmed.get(w) == v for w in self._workers):
            self._reformed.add(v)
            trace.instant(
                "elastic:reformed", cat="elastic", version=v,
                world=len(self._workers),
            )

    def all_confirmed(self, version: int) -> bool:
        """True iff ``version`` is current and every live member has
        confirmed it (registration or heartbeat)."""
        with self._lock:
            return version == self._version and all(
                self._confirmed.get(w) == self._version for w in self._workers
            )

    def reap_dead(self) -> List[str]:
        """Evict workers whose heartbeat is stale. Returns the evicted ids."""
        with self._lock:
            now = self._clock()
            dead = [
                w for w, t in self._workers.items() if now - t > self._timeout
            ]
            if not dead:
                return []
            for w in dead:
                del self._workers[w]
                self._addresses.pop(w, None)
                self._confirmed.pop(w, None)
            self._version += 1
            version, members = self._version, sorted(self._workers)
        self._notify(version, members)
        return dead

    def seed_version(self, version: int) -> None:
        """Continue version numbering from a journal-replayed pre-crash
        value (r18 master restart).  Monotone and wiring-time only (no
        members yet): a reconnecting worker's re-registration must see a
        version strictly ABOVE anything its pre-crash view held — a
        reused number would read as "nothing changed" to a stale peer."""
        with self._lock:
            self._version = max(self._version, int(version))

    def set_expected(self, n: int) -> None:
        """Record the fleet's desired size (master wires scale() here)."""
        with self._lock:
            self._expected = max(0, int(n))

    def membership(self) -> dict:
        """The worker-visible view: version + deterministic rank assignment."""
        with self._lock:
            members = sorted(self._workers)
            return {
                "version": self._version,
                "workers": members,
                "ranks": {w: i for i, w in enumerate(members)},
                "world_size": len(members),
                "expected": self._expected,
                # Per-member confirmed version (registration or versioned
                # heartbeat).  The settle loop forms the jax.distributed
                # world only when every member confirms the CURRENT
                # version: a stale incarnation (live but about to restart)
                # can't confirm, so fresh relaunches wait for each other
                # instead of forming worlds with members that are leaving.
                "confirmed": {
                    w: self._confirmed[w]
                    for w in members
                    if w in self._confirmed
                },
                "addresses": {
                    w: self._addresses[w] for w in members if w in self._addresses
                },
            }

    def version(self) -> int:
        with self._lock:
            return self._version
