"""The master RPC service: task hand-out, result/metric reports, rendezvous.

Reference parity (SURVEY.md §2 #2, §3.2 [U]; RPC names follow the upstream
Master service — GetTask / ReportTaskResult / ReportVersion — plus the
rendezvous and checkpoint surface the north star requires).  Handlers are
plain methods taking/returning dicts, so unit tests call them directly with
no network (the reference's decisive test pattern, SURVEY.md §4); ``serve()``
exposes the same handlers over gRPC for real deployments.

Method table (the wire contract):

  GetTask            {worker_id, lease?}               -> {task?, tasks?, finished}
  GetGroupTask       {worker_id, seq, version, lease?} -> {task?, finished, stale,
                                                          entries?}
  ReportTaskResult   {worker_id, task_id, success,
                      metrics?, weight?, model_version?} -> {accepted}
  ReportVersion      {worker_id, model_version}        -> {}
  RegisterWorker     {worker_id, address?, proto?}     -> membership
                      (proto != PROTOCOL_VERSION -> FAILED_PRECONDITION)
  DeregisterWorker   {worker_id}                       -> {version}
  Heartbeat          {worker_id}                       -> {version}
  GetMembership      {}                                -> membership
  GetCheckpoint      {}                                -> {path?, step}
  ReportCheckpoint   {path, step}                      -> {}
  JobStatus          {}                                -> counts + metrics
  DumpTrace          {}                                -> per-process trace
                                                         buffers + master's

Every method additionally accepts the optional ``trace`` envelope
(common/rpc.py): span context from the caller, and — on Heartbeat/Report
methods — bounded slices of the worker's trace ring buffer, which the
master accumulates per worker for DumpTrace (the live-job introspection
pull that tools/trace_dump.py merges into one Chrome trace).  Since r14
the same three methods carry the optional ``gauge`` envelope (a worker's
live-metrics registry snapshot); the master banks them per worker and
its /metrics endpoint serves the fleet-aggregated view plus the derived
goodput/SLO gauges (master/fleet_metrics.py, docs/observability.md).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent import futures
from typing import Dict, Optional

import grpc

from elasticdl_tpu import chaos
from elasticdl_tpu.common import locksan, trace
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.rpc import (
    GRPC_MESSAGE_OPTIONS,
    MASTER_SCHEMAS,
    PROTOCOL_VERSION,
    SERVICE_NAME,
    SchemaError,
    make_generic_handler,
)
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.task_dispatcher import (
    TASK_EVALUATION,
    TaskDispatcher,
)

logger = get_logger("master.servicer")


class MasterServicer:
    def __init__(
        self,
        dispatcher: TaskDispatcher,
        rendezvous: Optional[RendezvousServer] = None,
        evaluation: Optional[EvaluationService] = None,
        final_eval: bool = False,
        metrics_writer=None,
        max_steps: int = 0,
        epoch_end_eval: bool = False,
        gang_deadline_ms: float = 0.0,
        clock=time.monotonic,
    ):
        self.dispatcher = dispatcher
        self.rendezvous = rendezvous or RendezvousServer()
        self.evaluation = evaluation
        self.metrics_writer = metrics_writer
        # --max_steps: stop dispatching once the model version reaches it
        # (0 = until tasks exhausted).  Enforced in _bump_version.
        self._max_steps = max_steps
        self._max_steps_hit = False  # guarded-by: _lock
        # --evaluation_steps=0 ("eval at epoch end only"): an eval round at
        # every epoch boundary, driven by the dispatcher's epoch-end events.
        # Boundaries that fire while a round is in flight queue here
        # (FIFO of is_final flags) and retry from GetTask.
        self._pending_epoch_evals: list = []  # guarded-by: _lock
        self._epoch_end_eval = (
            epoch_end_eval and evaluation is not None and evaluation.enabled()
        )
        if self._epoch_end_eval:
            dispatcher.set_epoch_end_callback(self._on_epoch_end)
        self._written_eval_rounds = 0  # guarded-by: _lock
        self._lock = locksan.lock("MasterServicer._lock")
        self._model_version = 0  # guarded-by: _lock
        self._checkpoint: Dict[str, object] = {"path": None, "step": 0}  # guarded-by: _lock
        # Latest per-worker task-loop phase decomposition (cumulative
        # seconds; common/metrics.py PhaseTimers) — snapshots ride
        # ReportTaskResult/ReportCheckpoint and JobStatus republishes them,
        # so the train-job tool can attribute job-vs-bench throughput gaps
        # to named phases (VERDICT r5 Weak #1: the 5.4x gap was guessed).
        self._phase_times: Dict[str, dict] = {}  # guarded-by: _lock
        # Per-phase entry COUNTS (PhaseTimers.counts), beside the seconds:
        # sums alone cannot answer "how long is one lease RPC on average" —
        # counts make per-phase means computable from the same artifact.
        self._phase_counts: Dict[str, dict] = {}  # guarded-by: _lock
        # Per-worker trace buffers (bounded ring each, like the worker's
        # own): Heartbeat/Report-borne slices land here; DumpTrace reads
        # them.  clock_offset_us is the worker's RTT-midpoint estimate of
        # (master clock - worker clock), shipped alongside its events.
        self._trace_buffers: Dict[str, dict] = {}  # guarded-by: _lock
        # master wires _persist_progress here
        self._on_checkpoint = None  # guarded-by: _lock
        # final_eval: run one last eval round after the training tasks drain,
        # BEFORE reporting the job finished (the reference's end-of-job eval).
        # Triggered inside GetTask so workers can't race past the job end.
        # A shard-less eval service could never satisfy the trigger, so it
        # must not hold the job open.
        self._final_eval = (
            final_eval and evaluation is not None and evaluation.enabled()
        )
        self._final_eval_done = False  # guarded-by: _lock
        # A dead worker's tasks must be requeued in BOTH dispatchers.
        self.rendezvous.add_listener(self._on_membership_change)
        # Mutated by RegisterWorker (gRPC pool threads) AND the rendezvous
        # membership listener (reaper/watcher threads).
        self._known_workers: set = set()  # guarded-by: _lock
        # Multi-host lockstep task log (GetGroupTask): every process of a
        # jax.distributed world must execute the SAME task sequence, because
        # the jitted step is a collective across all their devices —
        # independent GetTask polls would deadlock the mesh (SURVEY.md §3.5;
        # VERDICT r2 Missing #2).  Entry ``seq`` is materialized through the
        # ordinary GetTask logic by whichever process asks first, attributed
        # to a per-membership-version pseudo worker so a world change
        # requeues the group's in-flight tasks.
        # GetGroupTask materializes entries through GetTask while holding
        # this lock, so it orders strictly before the state lock.
        self._group_lock = locksan.lock("MasterServicer._group_lock", before=("_lock",))  # lock-order: before(_lock)
        self._group_version: Optional[int] = None  # guarded-by: _group_lock
        self._group_log: list = []  # guarded-by: _group_lock
        # Deadline-bounded gang boundary (r13, --gang_deadline_ms): per-rank
        # lockstep ARRIVAL progress.  Heartbeats carry each rank's
        # ``gang_seq`` (entries whose dispatch it has BEGUN — see
        # _note_gang_progress_locked for why arrival, not consumption);
        # the head is the newest arrival any rank has shown plus the time
        # the gang's FRONT reached it.  A rank lagging the head past the
        # deadline is the straggler: its in-flight gang tasks requeue
        # through the dispatcher's skip accounting and the rank is
        # evicted so the gang re-forms without waiting out the full
        # task/heartbeat timeouts.  0 disables (pre-r13 behavior).
        self._gang_deadline_s = max(0.0, gang_deadline_ms) / 1e3
        self._clock = clock
        self._gang_arrivals: Dict[str, tuple] = {}  # guarded-by: _group_lock
        self._gang_head: tuple = (0, None)  # (seq, first-ask t)  guarded-by: _group_lock
        self._skipped_ranks: Dict[str, int] = {}  # guarded-by: _lock
        # r15 in-collective exclusions: newest cumulative count per
        # worker, heartbeat-borne (the in-step layer of the same
        # bounded-skip story _skipped_ranks tracks at the boundary).
        self._collective_skips: Dict[str, int] = {}  # guarded-by: _lock
        # Ranks maybe_skip_straggler evicted whose processes are still
        # alive: their background liveness beats keep arriving, and the
        # rendezvous heartbeat would REVIVE an unknown worker — re-adding
        # an unconfirmable wedged rank to the very membership the skip
        # just cut it from.  Heartbeat refuses the revival while a rank
        # is marked here; a deliberate RegisterWorker (the restart path)
        # clears the mark.  Bounded by the job's historical rank count.
        self._deadline_evicted: set = set()  # guarded-by: _lock
        # Warm-standby pool introspection (r13): master main wires the
        # PodManager's depth here; Heartbeat/JobStatus republish it so a
        # DRAINED pool is visible before the next failure needs it.
        self._standby_depth_fn = None  # guarded-by: _lock
        # Durable control-plane journal (r18, master/journal.py): the
        # servicer records its OWN nondeterministic inputs — lockstep
        # group-log entries, membership/model-version advances, the
        # per-worker report-seq ledger — beside the dispatcher's queue
        # events, all into one WAL.  None until the master wires it
        # (before the server starts); the REFERENCE is then read-only —
        # single-op reads from any handler thread — and rotation swaps
        # the fd INSIDE the journal while holding every recording lock
        # (rotate_journal), never this reference.
        self._journal = None  # single-writer: main
        # Per-worker highest report seq accepted (r18): the exactly-once
        # dedup ledger.  A worker's proxy retries a report whose first
        # attempt a dying master may or may not have applied; the seq
        # makes the retry idempotent — journaled with the report, so the
        # ledger survives the restart the retry is riding out.
        self._report_seqs: Dict[str, int] = {}  # guarded-by: _lock
        # Stale (seq-deduped) reports rejected since start — the
        # observable half of "rejects a stale pre-restart report exactly
        # once" (JobStatus republishes it).
        self._stale_reports = 0  # guarded-by: _lock
        # Last incarnation nonce each worker id registered with: a CHANGED
        # incarnation is a fresh process whose seq counter restarts at 1,
        # so its ledger entry resets — without this, a respawned worker
        # under a replayed ledger would have its first reports silently
        # deduped as pre-restart duplicates.  Deliberately NOT journaled:
        # a ride-through worker's retried report dedups BEFORE its
        # reconcile re-registration can reset anything (the task loop is
        # blocked inside that very call), and post-reset seqs only grow.
        self._worker_incarnations: Dict[str, str] = {}  # guarded-by: _lock
        # Journal replay stats stamped by a restarted master (JobStatus
        # republishes; the masterfail bench asserts on them).
        self._journal_stats: Dict[str, object] = {}  # guarded-by: _lock
        # graftgauge (r14): the fleet metrics plane.  Workers ship their
        # registry snapshot on the same heartbeat/report channel as the
        # trace slices (the additive ``gauge`` envelope); FleetMetrics
        # banks them and computes the aggregated view + goodput/SLO
        # gauges at SCRAPE time — the master's /metrics endpoint
        # (master/main.py) serves fleet.render().  Constructed here
        # unconditionally (stdlib, a dict bank: negligible without an
        # endpoint) so in-process tests and every master share one path.
        from elasticdl_tpu.master.fleet_metrics import FleetMetrics

        self.fleet = FleetMetrics(self)

    # -- rendezvous listener: requeue tasks of evicted workers --

    def _on_membership_change(self, version: int, members) -> None:
        # Runs on rendezvous reaper/watcher threads while RegisterWorker
        # mutates the set from the gRPC pool: snapshot-and-swap under the
        # lock, requeue outside it (the dispatchers take their own locks —
        # holding ours across their calls would couple lock orders).
        with self._lock:
            gone = self._known_workers - set(members)
            self._known_workers = set(members)
            self._bound_departed_trace_buffers(set(members))
        for worker_id in gone:
            lost = self.dispatcher.recover_tasks(worker_id)
            lost_eval = (
                self.evaluation.recover_tasks(worker_id) if self.evaluation else []
            )
            if lost or lost_eval:
                logger.info(
                    "requeued %d train + %d eval tasks of %s",
                    len(lost), len(lost_eval), worker_id,
                )
        # The lockstep group's in-flight tasks are attributed to a
        # per-version pseudo worker, invisible to the per-worker requeue
        # above.  Any version change orphans them (every member restarts),
        # and waiting for a NEW group to pull is not enough — after a
        # scale-to-one the successor runs single-host and never calls
        # GetGroupTask.  Requeue now.
        with self._group_lock:
            gv, self._group_version = self._group_version, None
            self._group_log = []
            # Gang-boundary progress is per-world: a new membership gets a
            # fresh deadline clock (stale arrivals from the old world must
            # not instantly "skip" a member of the new one).
            self._gang_arrivals = {}
            self._gang_head = (0, None)
            if gv is not None:
                self._journal_record({"kind": "group_version", "version": None})
        with self._lock:
            # Under _lock like every servicer-side record: rotation holds
            # it, so this membership advance cannot land on the old fd
            # after the base snapshot was composed (and then exist in
            # neither file — a lost version that a restarted master would
            # re-issue to stale peers).
            self._journal_record({"kind": "membership", "version": version})
        if gv is not None and gv != version:
            lost = self.dispatcher.recover_tasks(self.group_worker_id(gv))
            if self.evaluation is not None:
                lost += self.evaluation.recover_tasks(self.group_worker_id(gv))
            if lost:
                logger.info(
                    "requeued %d lockstep tasks of group v%d", len(lost), gv
                )

    # -- durable journal (r18) --

    def _journal_record(self, ev: dict) -> None:
        """Record one servicer-side journal event.  Callers hold the lock
        of the subsystem whose state the event describes (``_group_lock``
        for group entries, ``_lock`` for version/seq advances) — the same
        under-the-owning-lock ordering contract the dispatcher keeps, and
        what makes the no-lock fd append safe (master/journal.py)."""
        if self._journal is not None:
            self._journal.record(ev)

    def set_journal(self, journal) -> None:
        """Wire the WAL (master main, after construction/replay).  The
        dispatcher shares the same journal object (attach_journal)."""
        with self._lock:
            self._journal = journal

    def adopt_replayed(self, replayed) -> None:
        """Adopt a ``journal.ReplayResult``'s servicer half: the restored
        lockstep log (so a reconnecting gang can keep walking its seq),
        the model version, and the report-seq dedup ledger.  Called
        before the server starts — no concurrent handlers yet."""
        with self._group_lock:
            self._group_version = replayed.group_version
            self._group_log = list(replayed.group_log)
        with self._lock:
            self._model_version = max(
                self._model_version, replayed.model_version
            )
            self._report_seqs = dict(replayed.report_seqs)
            self._worker_incarnations = dict(replayed.incarnations)
            self._journal_stats = {
                "restarts": replayed.restarts + 1,
                "replayed_events": replayed.events_applied,
                "torn_tail": replayed.torn_tail,
            }

    def rotate_journal(self) -> None:
        """Compaction: swap the WAL for a fresh file whose base record is
        the CURRENT full control-plane state.  Holds ``_group_lock`` +
        ``_lock`` across the dispatcher-side rotate (which holds the
        dispatcher's own lock around its snapshot + the fd swap), so
        every journal writer — each records under one of those three
        locks — is excluded while the file changes hands: no event can
        land between the base snapshot and the swap and be lost."""
        with self._group_lock:
            with self._lock:
                if self._journal is None:
                    return
                extras = {
                    "group_version": self._group_version,
                    "group_log": [dict(e) for e in self._group_log],
                    "model_version": self._model_version,
                    "membership_version": self.rendezvous.version(),
                    "report_seqs": dict(self._report_seqs),
                    "incarnations": dict(self._worker_incarnations),
                    "restarts": int(
                        self._journal_stats.get("restarts", 0) or 0
                    ),
                }
                self.dispatcher.rotate_journal(extras)

    # -- handlers (dict in, dict out) --

    # hot-path: one call per worker poll interval; must never sleep/block
    def GetTask(self, req: dict) -> dict:
        worker_id = req["worker_id"]
        # Batched lease (r9): hand out up to ``lease`` training tasks in
        # one RPC — the response's "tasks" carries the whole batch and
        # "task" stays its first element for pre-lease consumers.  Eval
        # tasks are never batched: a round wants its tasks spread across
        # workers and scored against one model version, so an eval hand-out
        # preempts the batch exactly as it preempted the single task.
        lease = max(1, int(req.get("lease", 1)))
        if self._epoch_end_eval:
            self._drain_pending_epoch_evals()
        # Eval rounds preempt training tasks so metrics snapshot a consistent
        # model version quickly (reference behavior: eval tasks share the queue
        # with priority).
        if self.evaluation is not None:
            # _final_eval is set-once at construction; _final_eval_done is
            # re-checked under the lock below (the old unlocked fast-path
            # read raced the setter).
            if self._final_eval and self.dispatcher.finished():
                # The flag is only set once trigger() actually starts the
                # round; a False return (periodic round still in flight)
                # leaves it unset, so job_finished() stays False and the
                # final round is retried on a later GetTask.  The lock
                # serializes concurrent GetTask callers.
                with self._lock:
                    version = self._model_version
                    if not self._final_eval_done and self.evaluation.trigger(
                        version
                    ):
                        self._final_eval_done = True
            task = self.evaluation.get_task(worker_id)
            if task is not None:
                return {"task": task.to_dict(), "finished": False}
        tasks = self.dispatcher.get_tasks(worker_id, lease)
        if not tasks:
            return {"task": None, "finished": self.job_finished()}
        dicts = [t.to_dict() for t in tasks]
        return {"task": dicts[0], "tasks": dicts, "finished": False}

    @staticmethod
    def group_worker_id(version: int) -> str:
        return f"__group_v{version}__"

    # hot-path: every rank polls this each task boundary
    def GetGroupTask(self, req: dict) -> dict:
        """Lockstep task hand-out for a multi-host worker group.

        All processes of membership ``version`` walk the same ``seq``-indexed
        log; a response with ``stale`` means the caller's world is gone and it
        must re-check membership (which restarts it in multihost mode).  A
        transient ``{task: None, finished: False}`` is NOT logged — callers
        retry the same seq.

        ``lease`` (r9) batches the log walk: the response's ``entries``
        carries up to ``lease`` consecutive log entries starting at ``seq``
        (materializing through GetTask as needed), and ``task``/``finished``
        mirror the first entry for pre-lease consumers.  Batching is pure
        read-ahead of the shared log — whichever member asks first
        materializes, every member sees the identical sequence, and a
        membership change still invalidates the whole log (and requeues its
        in-flight tasks) exactly as before.
        """
        seq = int(req["seq"])
        version = int(req["version"])
        lease = max(1, int(req.get("lease", 1)))
        # The boundary polices its own deadline: every crossing checks for
        # a rank lagging the gang head (Heartbeat covers the wedged-gang
        # case where no rank polls the boundary at all).
        self.maybe_skip_straggler()
        stale = {"task": None, "finished": False, "stale": True}
        if version != self.rendezvous.version():
            return stale
        with self._group_lock:
            if self._group_version != version:
                if self._group_version is not None:
                    # New world: the old group's in-flight tasks can never be
                    # reported (every member restarts) — requeue them now
                    # rather than waiting out the task timeout.
                    old = self.group_worker_id(self._group_version)
                    self.dispatcher.recover_tasks(old)
                    if self.evaluation is not None:
                        self.evaluation.recover_tasks(old)
                self._group_version = version
                self._group_log = []
                self._gang_arrivals = {}
                self._gang_head = (0, None)
                self._journal_record(
                    {"kind": "group_version", "version": version}
                )
            if seq > len(self._group_log):
                # A process can only be at most one entry ahead of the log;
                # anything else is a protocol bug or a stale world — restart.
                logger.warning(
                    "GetGroupTask seq %d ahead of log %d (v%d)",
                    seq, len(self._group_log), version,
                )
                return stale
            entries = []
            s = seq
            while len(entries) < lease:
                if s < len(self._group_log):
                    entries.append(self._group_log[s])
                else:
                    if entries and self._under_drain_or_eval_pressure():
                        # Every materialized entry commits the WHOLE gang
                        # to training it (lockstep contract), so read-ahead
                        # under a max-steps drain or a pending eval round
                        # would widen the overshoot/skew by up to
                        # lease_batch-1 tasks — fall back to the pre-lease
                        # one-entry-per-call walk until the pressure
                        # clears.  Already-logged entries above still
                        # serve: the gang is committed to those.
                        break
                    if not self.rendezvous.all_confirmed(version):
                        # A member still holds (or may hold) an older
                        # topology view; issuing a collective task now would
                        # wedge the others inside the collective waiting for
                        # it.  Withhold until every member has confirmed
                        # this version (heartbeat/registration).
                        break
                    resp = self.GetTask(
                        {"worker_id": self.group_worker_id(version)}
                    )
                    if resp["task"] is None and not resp["finished"]:
                        break  # transient: not logged, caller retries seq
                    entry = {"task": resp["task"], "finished": resp["finished"]}
                    self._group_log.append(entry)
                    # Journaled at materialization: every rank of a
                    # reconnecting gang resumes the SAME seq walk against
                    # the replayed log (the whole-gang lockstep contract
                    # must survive the master, not just the dispatcher).
                    self._journal_record({
                        "kind": "group_entry",
                        "seq": len(self._group_log) - 1,
                        "entry": dict(entry),
                    })
                    entries.append(entry)
                s += 1
                if entries[-1]["finished"]:
                    break  # the job-end marker closes the log
            if not entries:
                return {"task": None, "finished": False, "stale": False}
            return dict(
                entries[0], stale=False, entries=[dict(e) for e in entries]
            )

    def _note_gang_progress_locked(self, worker_id: str, seq: int) -> None:  # guarded-by: _group_lock
        """Monotonic per-rank lockstep ARRIVAL progress, fed exclusively
        from the heartbeat's ``gang_seq`` — the count of group entries
        whose device dispatch the rank has BEGUN (Worker._gang_dispatched).
        That counter is the one signal that separates the straggler from
        its victims once the gang wedges: the ranks blocked INSIDE the
        collective have counted the entry (they arrived, then blocked)
        while the rank that never reached the boundary has not — and it
        rides the background liveness beat, which keeps flowing when
        every task loop in the gang is blocked.  Consumption signals
        (boundary ask seq, popped-entry counts) are deliberately NOT fed
        here: lease batching and prep-ahead freeze every rank's
        consumption at the same value the moment the gang wedges, which
        would mask the lag this deadline exists to see."""
        now = self._clock()
        prev = self._gang_arrivals.get(worker_id)
        if prev is None or seq > prev[0]:
            self._gang_arrivals[worker_id] = (seq, now)
        if seq > self._gang_head[0] or self._gang_head[1] is None:
            self._gang_head = (seq, now)

    def note_gang_progress(self, worker_id: str, seq: int, version) -> None:
        """Heartbeat-side progress feed (see _note_gang_progress_locked);
        version-gated so a beat from a stale world cannot seed the new
        world's deadline clock."""
        with self._group_lock:
            if self._group_version is None or version != self._group_version:
                return
            self._note_gang_progress_locked(worker_id, seq)

    # hot-path: rides every Heartbeat and GetGroupTask — the steady state
    # is a bounded dict scan under the group lock; the eviction branch
    # fires at most once per deadline window
    def maybe_skip_straggler(self) -> Optional[str]:
        """Deadline-bounded gang boundary (r13): when a rank lags the
        gang's newest lockstep seq past ``gang_deadline_ms``, SKIP it —
        requeue the gang's in-flight tasks through the dispatcher's
        bounded skip accounting, then evict the rank so the membership
        bump re-forms the gang without it (the straggler's own restart
        path re-joins it at the next reform).  Driven from Heartbeat as
        well as GetGroupTask because a wedged gang stops polling the
        boundary: the fast ranks are blocked inside the collective on the
        straggler, and only the background heartbeat threads still reach
        the master.  Returns the skipped worker id, or None."""
        if not self._gang_deadline_s:
            return None
        with self._group_lock:
            version = self._group_version
            head_seq, head_t = self._gang_head
            if version is None or head_t is None:
                return None
            now = self._clock()
            if now - head_t < self._gang_deadline_s:
                return None
            behind = [
                (s, w) for w, (s, _) in self._gang_arrivals.items()
                if s < head_seq
            ]
            if not behind:
                return None
            behind.sort()
            seq_behind, straggler = behind[0]
            # One eviction per deadline window: the clock restarts so a
            # second laggard gets its own full deadline against the
            # (re-formed) gang rather than being batch-evicted with the
            # first — skips must stay attributable one rank at a time.
            self._gang_head = (head_seq, now)
            self._gang_arrivals.pop(straggler, None)
        trace.instant(
            "gang:skip", cat="gang", worker=straggler, seq=seq_behind,
            head_seq=head_seq, version=version,
            deadline_ms=self._gang_deadline_s * 1e3,
        )
        with self._lock:
            self._skipped_ranks[straggler] = (
                self._skipped_ranks.get(straggler, 0) + 1
            )
            # Marked BEFORE rendezvous.remove below: a beat landing in the
            # gap would otherwise revive the rank the moment it is removed.
            self._deadline_evicted.add(straggler)
        # Skip-accounted requeue BEFORE the membership bump: the generic
        # invalidation path (_on_membership_change) would requeue the same
        # tasks without charging the skip budget, and unbounded free skips
        # are exactly what lets a poison shard wedge the gang forever.
        skipped = self.dispatcher.skip_tasks(self.group_worker_id(version))
        logger.warning(
            "gang deadline: rank %s lags boundary seq %d (gang head %d) "
            "past %.0f ms — skipping it (%d in-flight gang task(s) "
            "requeued with skip accounting)",
            straggler, seq_behind, head_seq, self._gang_deadline_s * 1e3,
            len(skipped),
        )
        self.rendezvous.remove(straggler)
        return straggler

    def _under_drain_or_eval_pressure(self) -> bool:
        """True when new lockstep-log entries should not be materialized
        ahead of need: the max-steps drain has begun, or an eval round has
        undispatched tasks (the group-mode twin of the worker-side
        draining/eval_pending heartbeat handling, which group workers
        deliberately skip — the log, not the worker, owns the gang's
        order)."""
        with self._lock:
            if self._max_steps_hit:
                return True
        return self.evaluation is not None and self.evaluation.tasks_pending()

    def job_finished(self) -> bool:
        """True when training tasks drained AND any pending/in-flight eval is done."""
        if not self.dispatcher.finished():
            return False
        if self.evaluation is None:
            return True
        with self._lock:
            if self._final_eval and not self._final_eval_done:
                return False
            if self._pending_epoch_evals:
                return False  # queued epoch-boundary rounds still owed
        return not self.evaluation.round_in_flight()

    # hot-path: rides every completed task's report RPC
    def ReportTaskResult(self, req: dict) -> dict:
        task_id = int(req["task_id"])
        success = bool(req.get("success", True))
        task_type = req.get("task_type", "")
        self._record_phase_times(req)
        self._record_trace(req)
        # stream=True: one JSONL "gauge" record per successful training
        # report, beside the "phase" record — the same crash-safe channel
        # and cadence.
        self._record_gauges(req, stream=True)
        # Report-seq dedup (r18): the worker numbers its reports, the
        # proxy's outage ride-through may RETRY one whose first attempt
        # the dying master already applied+journaled — the replayed seq
        # ledger rejects the duplicate here, before any counter moves, so
        # exactly-once holds across the restart without inflating
        # duplicate_done (that counter keeps meaning what r13 defined:
        # late success for a task requeued by timeout/skip).
        seq = req.get("seq")
        worker_id = req.get("worker_id", "")
        if seq is not None and worker_id:
            seq = int(seq)
            # CHECK here, ADVANCE only after the report has applied (and
            # therefore journaled, inside dispatcher.report's critical
            # section).  Advancing first opened a crash window where a
            # rotation between ledger update and report journal persisted
            # a base whose ledger was AHEAD of its task state — the
            # retried report then deduped against work the replay never
            # counted (silent double-train).  With check-then-apply-then-
            # advance, the worst interleaving is the mirror image — a
            # base with the report counted but the ledger behind — and a
            # replayed retry lands in the r13 late-success path instead:
            # rejected, observable in duplicate_done, nothing retrained.
            # Per-worker seqs arrive serialized (one task loop, and the
            # preemption hand-off parks it), so check-then-later-advance
            # does not race itself.
            with self._lock:
                stale = seq <= self._report_seqs.get(worker_id, 0)
                if stale:
                    self._stale_reports += 1
            if stale:
                trace.instant(
                    "lease:dedup", cat="lease", worker=worker_id,
                    task=task_id, seq=seq,
                )
                logger.info(
                    "deduplicated stale report seq %d from %s (task %d) — "
                    "already applied before the restart", seq, worker_id,
                    task_id,
                )
                return {"accepted": True, "duplicate": True}
        if task_type == TASK_EVALUATION and self.evaluation is not None:
            # Metrics BEFORE report_task: completing the round's last task
            # snapshots the aggregate.
            eval_metrics = req.get("metrics")
            if success and eval_metrics:
                self.evaluation.report_metrics(
                    # Scalars coerce to float; histogram metrics (streaming
                    # AUC) arrive as lists and aggregate elementwise.
                    {
                        k: v if isinstance(v, (list, tuple)) else float(v)
                        for k, v in eval_metrics.items()
                    },
                    float(req.get("weight", 1.0)),
                )
            accepted = self.evaluation.report_task(task_id, success)
            self._maybe_write_eval_metrics()
            if seq is not None and worker_id:
                # Eval rounds are not journal-replayed (a restart re-runs
                # them), but the seq LEDGER must still survive or a
                # retried eval report could double-apply after a restart.
                with self._lock:
                    self._journal_record(
                        {"kind": "report_seq", "worker": worker_id,
                         "seq": seq}
                    )
                    self._report_seqs[worker_id] = max(
                        self._report_seqs.get(worker_id, 0), seq
                    )
        else:
            accepted = self.dispatcher.report(
                task_id, success, req.get("worker_id", ""),
                requeue_only=bool(req.get("requeue", False)),
                seq=seq if worker_id else None,
            )
            if seq is not None and worker_id:
                # Advance AFTER the apply+journal (see the check above).
                with self._lock:
                    self._report_seqs[worker_id] = max(
                        self._report_seqs.get(worker_id, 0), seq
                    )
            train_metrics = req.get("metrics")
            if success and accepted and train_metrics and self.metrics_writer:
                with self._lock:
                    fallback_version = self._model_version
                self.metrics_writer.write(
                    "train",
                    int(req.get("model_version", fallback_version)),
                    train_metrics,
                )
        model_version = req.get("model_version")
        if model_version is not None:
            self._bump_version(int(model_version))
        # graftchaos (r18): kill:target=master,step=N fires HERE, after
        # the report is applied AND journaled — the crash the masterfail
        # bench injects lands exactly where a real one is hardest: a
        # worker whose acked-but-unanswered report must dedup, not
        # double-train, across the restart.  ``step`` is the dispatcher's
        # cumulative done count; gated so the unarmed path never pays the
        # counts() lock.
        if chaos.enabled():
            chaos.hook(
                "master:report", step=self.dispatcher.counts()["done"]
            )
        return {"accepted": accepted}

    # hot-path: called from every report AND every heartbeat
    def _record_phase_times(self, req: dict, stream: bool = True) -> None:
        """Keep the newest phase snapshot per worker (cumulative, so latest
        wins) and mirror it to the metrics stream when one is configured —
        one "phase" JSONL record per successful training report, the same
        crash-safe channel the train/eval scalars use.  ``stream=False``
        updates only the in-memory slot (heartbeat-borne snapshots arrive
        every poll interval; mirroring each would flood the JSONL)."""
        phases = req.get("phase_times")
        if not phases:
            return
        worker_id = req.get("worker_id", "")
        if not worker_id:
            # A snapshot that cannot be keyed to its worker would sit
            # beside the same worker's real entry and double-count in any
            # consumer summing across workers (the timers are cumulative).
            return
        counts = req.get("phase_counts")
        with self._lock:
            self._phase_times[worker_id] = dict(phases)
            if counts:
                self._phase_counts[worker_id] = dict(counts)
            fallback_version = self._model_version
        if (
            stream
            and self.metrics_writer is not None
            and req.get("success", True)
            and req.get("task_type", "") not in (TASK_EVALUATION,)
        ):
            try:
                self.metrics_writer.write(
                    "phase",
                    int(req.get("model_version", fallback_version)),
                    {k: float(v) for k, v in phases.items()},
                )
            except Exception:  # malformed values must not fail the report
                logger.exception("phase_times metrics write failed")

    #: Bound on each worker's master-side trace ring (events).  A straggler
    #: hunt wants the RECENT window, so overwrite-oldest per worker — the
    #: same policy as the worker's own ring.
    TRACE_BUFFER_EVENTS = 65536

    #: How many DEPARTED workers' trace rings the master retains (most
    #: recently updated win).  Keeping some is deliberate — a finished
    #: worker's job-end tail is dumped AFTER it exits, and a crashed
    #: straggler's final window is exactly what an investigation wants —
    #: but each ring is up to ~10 MB, so without a cap a long elastic job
    #: would grow memory with HISTORICAL membership, not current world
    #: size.  (The per-worker phase_times/phase_counts dicts stay for all
    #: departed workers on purpose: they are a few floats each, and the
    #: gang artifacts read them after the fleet exits.)
    TRACE_DEPARTED_KEEP = 8

    def _bound_departed_trace_buffers(self, members: set) -> None:  # guarded-by: _lock
        # Plain loop, no sort-key closure: a lambda would not inherit the
        # caller-holds-lock annotation (lock-discipline's closure rule).
        by_age = []
        for w, buf in self._trace_buffers.items():
            if w not in members:
                by_age.append((buf["updated"], w))
        by_age.sort()  # oldest-updated first
        for _, w in by_age[: max(0, len(by_age) - self.TRACE_DEPARTED_KEEP)]:
            del self._trace_buffers[w]

    # hot-path: rides every report and heartbeat — a bounded deque extend
    # under the state lock, never an RPC or an export
    def _record_trace(self, req: dict) -> None:
        """Bank a Heartbeat/Report-borne trace slice into the sender's
        master-side ring.  Slices are DRAINED from the worker's buffer, so
        this is the sole surviving copy — DumpTrace republishes it."""
        payload = req.get("trace")
        if not isinstance(payload, dict):
            return
        events = payload.get("events")
        if not events:
            return
        worker_id = req.get("worker_id", "")
        if not worker_id:
            return  # unattributable events cannot merge into a per-process view
        with self._lock:
            buf = self._trace_buffers.get(worker_id)
            if buf is None:
                buf = self._trace_buffers[worker_id] = {
                    "events": deque(maxlen=self.TRACE_BUFFER_EVENTS),
                    "clock_offset_us": None,
                    "dropped": 0,
                    "updated": 0.0,
                }
            buf["updated"] = trace.now_us()
            buf["events"].extend(e for e in events if isinstance(e, dict))
            # Type-checked, never coerced: telemetry riding a heartbeat
            # must not be able to crash the heartbeat — a peer shipping a
            # malformed offset would otherwise never beat again and time
            # out of the membership.
            offset = payload.get("clock_offset_us")
            if isinstance(offset, (int, float)) and not isinstance(offset, bool):
                buf["clock_offset_us"] = float(offset)
            dropped = payload.get("dropped")
            if isinstance(dropped, int) and not isinstance(dropped, bool):
                buf["dropped"] = dropped

    def DumpTrace(self, req: dict) -> dict:
        """The live-job introspection pull: every process's shipped trace
        window plus the master's own recorder.  Non-draining — operators
        dump a RUNNING job without perturbing what the next dump sees
        (beyond the rings' natural overwrite)."""
        with self._lock:
            processes = {
                w: {
                    "events": list(b["events"]),
                    "clock_offset_us": b["clock_offset_us"],
                    "dropped": b["dropped"],
                }
                for w, b in self._trace_buffers.items()
            }
        return {
            "processes": processes,
            # The master's own spans (rpc.server, dispatcher lease events)
            # — already on the reference clock every offset aims at.
            "master_events": trace.default().export(),
            "master_dropped": trace.default().dropped,
            "master_now_us": trace.now_us(),
        }

    # hot-path: rides every report and heartbeat — a dict-bank assignment
    # plus one rate-window append, never an aggregation walk (that is
    # scrape-side work, the gauge-discipline split)
    def _record_gauges(self, req: dict, stream: bool = False) -> None:
        """Bank a Heartbeat/Report-borne gauge envelope into the fleet
        view.  ``stream=True`` (checkpoint reports — bounded frequency,
        the phase-mirror stance inverted: heartbeats arrive every poll
        interval and would flood the JSONL) also mirrors the envelope's
        ``JSONL_GAUGE_FAMILIES`` scalars into the metrics stream under
        the SAME family names the live scrape serves — the one naming
        table, so offline JSONL analysis and live scrapes cannot
        drift."""
        payload = req.get("gauge")
        if not isinstance(payload, dict):
            return
        worker_id = req.get("worker_id", "")
        if not worker_id:
            return  # unattributable families cannot join a per-worker view
        self.fleet.record_envelope(worker_id, payload)
        if stream and (
            not req.get("success", True)
            or req.get("task_type", "") == TASK_EVALUATION
        ):
            stream = False  # the phase-mirror gating, same reasons
        if stream and self.metrics_writer is not None:
            mirror = self.fleet.jsonl_mirror(worker_id, payload)
            if mirror:
                with self._lock:
                    version = self._model_version
                try:
                    self.metrics_writer.write("gauge", version, mirror)
                except Exception:  # malformed values must not fail the RPC
                    logger.exception("gauge metrics write failed")

    def gang_lag_snapshot(self) -> Dict[str, float]:
        """Per-rank seconds behind the gang head's lockstep arrival
        (r13's deadline signal, read live for the metrics plane).  Ranks
        at the head read 0.0; a trailing rank reads ``now - head_t`` —
        the exact clock ``maybe_skip_straggler`` judges against (time
        since the head arrived with this rank still absent), NOT time
        since the rank's own previous arrival, which would overstate lag
        by a full step even on a healthy gang.  Empty outside group
        mode."""
        with self._group_lock:
            head_seq, head_t = self._gang_head
            if self._group_version is None or head_t is None:
                return {}
            now = self._clock()
            return {
                w: (round(max(now - head_t, 0.0), 3) if seq < head_seq
                    else 0.0)
                for w, (seq, _t) in self._gang_arrivals.items()
            }

    def fleet_state_snapshot(self) -> dict:
        """The master-side state the fleet collector aggregates, read
        under the state lock in one place (FleetMetrics must not grope
        guarded attributes cross-class)."""
        with self._lock:
            state = {
                "model_version": self._model_version,
                "skipped_ranks": dict(self._skipped_ranks),
                "collective_skips": dict(self._collective_skips),
                "phase_times": {
                    w: dict(p) for w, p in self._phase_times.items()
                },
            }
            depth_fn = self._standby_depth_fn
        state["standby_depth"] = depth_fn() if depth_fn is not None else None
        return state

    def _maybe_write_eval_metrics(self) -> None:
        """Record each completed eval round's aggregate exactly once.  The
        check-and-set runs under the lock: ReportTaskResult handlers run on
        the gRPC thread pool, and two workers finishing a round's last tasks
        concurrently must not both (or neither) write it."""
        if self.metrics_writer is None or self.evaluation is None:
            return
        with self._lock:
            rounds = self.evaluation.completed_rounds()
            if rounds <= self._written_eval_rounds:
                return
            self._written_eval_rounds = rounds
            version = self._model_version
            # Snapshot INSIDE the lock: if round N+1 completes while this
            # thread is descheduled, a late read would record N+1's
            # aggregate under N's slot and lose N's entirely.
            metrics = self.evaluation.latest_metrics()
        self.metrics_writer.write("eval", version, metrics)

    def ReportVersion(self, req: dict) -> dict:
        self._bump_version(int(req["model_version"]))
        return {}

    def _on_epoch_end(self, epoch: int, final: bool) -> None:
        """Epoch-boundary eval (--evaluation_steps=0).  A boundary whose
        round cannot start yet (previous round still in flight — routine,
        since eval and training tasks run concurrently) is QUEUED and
        retried from GetTask, never dropped; job_finished holds the job open
        until the queue drains.  The final epoch's round doubles as the
        end-of-job eval."""
        with self._lock:
            self._pending_epoch_evals.append(final)
        logger.info("epoch %d ended (final=%s): eval round queued", epoch, final)
        self._drain_pending_epoch_evals()

    def _drain_pending_epoch_evals(self) -> None:
        with self._lock:
            if not self._pending_epoch_evals:
                return
            version = self._model_version
            final = self._pending_epoch_evals[0]
        if not self.evaluation.trigger(version):
            return  # round in flight; retried on a later GetTask
        with self._lock:
            self._pending_epoch_evals.pop(0)
            if final:
                self._final_eval_done = True

    def _bump_version(self, version: int) -> None:
        with self._lock:
            advanced = version > self._model_version
            self._model_version = max(self._model_version, version)
            current = self._model_version
            if advanced:
                # The restored version seeds max_steps/eval triggers on
                # restart; monotone, so replay max()es duplicates away.
                self._journal_record(
                    {"kind": "model_version", "version": current}
                )
            # Check-and-set under the lock: two reports crossing max_steps
            # concurrently must not both win the "first to hit" test (the
            # log fired twice and dispatcher.stop() ran twice).
            hit = bool(
                self._max_steps
                and current >= self._max_steps
                and not self._max_steps_hit
            )
            if hit:
                self._max_steps_hit = True
        if hit:
            logger.info(
                "max_steps %d reached (version %d): draining task queue",
                self._max_steps, current,
            )
            self.dispatcher.stop()
        if self.evaluation is not None:
            self.evaluation.maybe_trigger(current)

    def RegisterWorker(self, req: dict) -> dict:
        # Wire-version negotiation: a mismatched worker is turned away HERE,
        # at its first RPC, with an error naming both versions — not N tasks
        # later with an opaque schema violation.  Absent field = accepted
        # (pre-versioning peer; proto3 unknown-field stance).
        proto = req.get("proto")
        if proto is not None and proto != PROTOCOL_VERSION:
            raise SchemaError(
                f"protocol version mismatch: worker speaks v{proto}, "
                f"master speaks v{PROTOCOL_VERSION} — upgrade the older side"
            )
        with self._lock:
            # A deliberate (re-)registration is the restart path out of a
            # deadline eviction — lift the Heartbeat revival block first so
            # the rank's beats count again the moment it is a member.
            self._deadline_evicted.discard(req["worker_id"])
        self.rendezvous.register(req["worker_id"], req.get("address", ""))
        with self._lock:
            self._known_workers.add(req["worker_id"])
        membership = self.rendezvous.membership()
        incarnation = req.get("incarnation")
        if incarnation:
            with self._lock:
                prev = self._worker_incarnations.get(req["worker_id"])
                if prev != incarnation:
                    self._worker_incarnations[req["worker_id"]] = incarnation
                    stale_ledger = self._report_seqs.pop(
                        req["worker_id"], None
                    )
                    # The reset is JOURNALED (under _lock, rotation-safe):
                    # without it a replay would max() the base's dead-
                    # incarnation seq back over the fresh incarnation's
                    # low seqs and wrongly dedup its in-flight retry — a
                    # second-order double-train window.
                    self._journal_record({
                        "kind": "incarnation",
                        "worker": req["worker_id"],
                        "incarnation": incarnation,
                    })
                else:
                    stale_ledger = None
            if stale_ledger is not None:
                logger.info(
                    "worker %s registered a fresh incarnation (%s): "
                    "report-seq ledger reset from %d (its counter "
                    "restarts at 1)",
                    req["worker_id"], incarnation, stale_ledger,
                )
        # Lease reconciliation (r18): a worker declaring what it HOLDS —
        # the reconnect handshake after a master restart (held = its
        # buffered leases + in-flight preps + pending report), and the
        # fresh-boot declaration (held = []), which requeues a dead
        # incarnation's leases NOW instead of after task_timeout_s.  The
        # response's stale_tasks names held work this master no longer
        # attributes to the worker; training it would double-train.
        held = req.get("held_tasks")
        if held is not None:
            requeued, stale_ids = self.dispatcher.reconcile_leases(
                req["worker_id"],
                {int(t) for t in held if isinstance(t, (int, float))},
            )
            if requeued or stale_ids:
                logger.info(
                    "reconciled %s (incarnation %s): requeued %d lost "
                    "lease(s) %s, %d stale held id(s) %s",
                    req["worker_id"], req.get("incarnation", "?"),
                    len(requeued), [t.task_id for t in requeued],
                    len(stale_ids), stale_ids,
                )
            membership = dict(membership, stale_tasks=stale_ids)
        return membership

    def DeregisterWorker(self, req: dict) -> dict:
        """Active leave.  A lockstep group member that failed a task calls
        this before restarting: the version bump makes every peer resync
        instead of wedging in a collective the failed member will never
        join (and requeues the member's in-flight tasks)."""
        with self._lock:
            self._deadline_evicted.discard(req["worker_id"])
        return {"version": self.rendezvous.remove(req["worker_id"])}

    # hot-path: every worker beats every poll interval
    def Heartbeat(self, req: dict) -> dict:
        # Group-mode non-rank-0 members attach their phase snapshot here
        # (their reports are rank-0-gated away); slot update only, no
        # metrics-stream mirror — heartbeats arrive every poll interval.
        self._record_phase_times(req, stream=False)
        # Trace slices ride the heartbeat (the pull path's supply side).
        self._record_trace(req)
        # Gauge envelopes too (r14): the beat is the one RPC still
        # flowing from a wedged gang, so the fleet view stays live
        # exactly when the operator needs it.  Bank-only — the JSONL
        # mirror rides checkpoint reports (bounded frequency).
        self._record_gauges(req)
        # In-collective skip ledger (r15): the worker's cumulative
        # in-step exclusions — newest value wins (the counter only
        # grows), banked beside the r13 per-rank boundary skips so
        # JobStatus serves both layers of the deadline story.
        cs = req.get("collective_skips")
        if cs is not None:
            with self._lock:
                self._collective_skips[req["worker_id"]] = int(cs)
        # Gang-deadline watchdog (r13): heartbeats are the only RPCs still
        # arriving when the whole gang is wedged in a collective on a
        # straggler — the beat both FEEDS the per-rank progress signal
        # (gang_seq, the dispatch counter boundary asks cannot carry) and
        # drives the skip decision on it.
        if self._gang_deadline_s:
            # Whole block gated: with the deadline off, _deadline_evicted
            # has no writer — non-gang jobs keep the pre-r13 per-beat cost.
            with self._lock:
                evicted = req["worker_id"] in self._deadline_evicted
            if not evicted:
                gang_seq = req.get("gang_seq")
                if gang_seq is not None:
                    self.note_gang_progress(
                        req["worker_id"], int(gang_seq), req.get("version")
                    )
                self.maybe_skip_straggler()
                # Re-check: the skip above can evict THIS rank — the
                # straggler's own beat is often the one that trips the
                # deadline — and a concurrent beat can evict it at any
                # point before the rendezvous call below.
                with self._lock:
                    evicted = req["worker_id"] in self._deadline_evicted
            if evicted:
                # A refused beat can be arbitrarily delayed between the
                # checks above and here while the rank deliberately
                # re-registers (clearing the mark): confirm the mark one
                # final time right before acting, so the remove below
                # cannot eject a legitimately re-joined member.  This
                # shrinks the raced-removal window from an arbitrary
                # handler delay to a few instructions (it cannot be zero:
                # holding _lock across the remove would invert against
                # the membership listener, which takes _lock).
                with self._lock:
                    evicted = req["worker_id"] in self._deadline_evicted
            if evicted:
                # Deadline-skipped rank whose process is still alive: its
                # beat must NOT feed gang progress (it is no longer a
                # member of the boundary) and must NOT reach
                # rendezvous.heartbeat, whose unknown-worker path would
                # re-register it unconfirmed — undoing the eviction and
                # wedging the reform on a rank that cannot confirm.  Two
                # self-healing undos cover the inherent check-then-act
                # races against a concurrent beat's eviction: drop any
                # stale arrival this rank's note_gang_progress re-seeded
                # after the skip popped it (left behind, it could fake a
                # SECOND eviction of the same stall a deadline later,
                # double-charging the skip budget), and the remove below
                # both reads the CURRENT version (a bump-free read when
                # the rank is already out, the steady state) and undoes a
                # raced revival.  The version mismatch drives the rank's
                # own restart (loop heartbeat → WorkerRestartRequired, or
                # the death-push grace); the relaunch re-registers
                # deliberately, clearing the mark.
                with self._group_lock:
                    self._gang_arrivals.pop(req["worker_id"], None)
                return {
                    "version": self.rendezvous.remove(req["worker_id"]),
                    "server_ts_us": trace.now_us(),
                }
            # Mark lifted while this beat was in flight: fall through to
            # the normal beat — the rank is a member again.
        resp = {
            "version": self.rendezvous.heartbeat(
                req["worker_id"], req.get("version")
            ),
            # Master clock stamp for the worker's RTT-midpoint clock-offset
            # estimate (clients measure t0/t1 locally around this RPC);
            # cheap enough to ride every beat unconditionally.
            "server_ts_us": trace.now_us(),
        }
        # Eval-preemption hint (r9): batched leases would otherwise let a
        # worker train up to lease_batch-1 buffered tasks before its next
        # GetTask sees a pending eval round, widening the round's
        # model-version skew — the hint makes lease-holding workers return
        # their buffer (immediate requeue) and pull the eval work instead.
        if self.evaluation is not None and self.evaluation.tasks_pending():
            resp["eval_pending"] = True
        # Standby-pool depth (r13): riding the beat keeps a DRAINED warm
        # pool visible to operators/benches before the next failure needs
        # a spare (the fn reads one leaf lock; None = no pool wired).
        with self._lock:
            depth_fn = self._standby_depth_fn
        if depth_fn is not None:
            depth = depth_fn()
            if depth is not None:
                resp["standby_pool"] = int(depth)
        # Drain hint (r9): past --max_steps the dispatcher stops, but it
        # cannot recall leases a worker already buffers — without the hint
        # the worker would train up to lease_batch-1 tasks beyond the
        # configured limit.  On seeing it the worker returns its buffer;
        # the STOPPED dispatcher drops the returned tasks (they must not
        # retrain), restoring the pre-lease overshoot bound.
        with self._lock:
            if self._max_steps_hit:
                resp["draining"] = True
        return resp

    def GetMembership(self, req: dict) -> dict:
        return self.rendezvous.membership()

    def GetCheckpoint(self, req: dict) -> dict:
        with self._lock:
            return dict(self._checkpoint)

    def ReportCheckpoint(self, req: dict) -> dict:
        self._record_phase_times(req)
        self._record_trace(req)
        self._record_gauges(req, stream=True)
        with self._lock:
            if int(req["step"]) >= int(self._checkpoint["step"] or 0):
                self._checkpoint = {"path": req["path"], "step": int(req["step"])}
            cb = self._on_checkpoint
        if cb is not None:
            # Master persists the task watermark HERE — coupled to the model
            # checkpoint, never ahead of it (a watermark newer than the
            # restorable model would skip shards whose updates the restored
            # model never saw).
            cb(int(req["step"]))
        return {}

    def set_checkpoint_callback(self, fn) -> None:
        with self._lock:
            self._on_checkpoint = fn

    def set_standby_depth(self, fn) -> None:
        """Wire a callable returning the warm-standby pool depth (master
        main passes PodManager.standby_depth); Heartbeat/JobStatus
        republish it."""
        with self._lock:
            self._standby_depth_fn = fn

    def JobStatus(self, req: dict) -> dict:
        status = self.dispatcher.counts()
        with self._lock:
            status["model_version"] = self._model_version
            status["phase_times"] = {
                w: dict(p) for w, p in self._phase_times.items()
            }
            status["phase_counts"] = {
                w: dict(c) for w, c in self._phase_counts.items()
            }
            # r13 tail tolerance: per-rank deadline-skip counts, beside
            # the dispatcher's per-task accounting already in ``status``.
            status["skipped_ranks"] = dict(self._skipped_ranks)
            # r15 graftreduce: in-collective exclusions per worker (the
            # in-step layer of the same bounded-skip accounting).
            status["collective_skips"] = dict(self._collective_skips)
            # r18 master crash survivability: seq-deduped stale reports
            # (the exactly-once proof's second counter, beside
            # duplicate_done) and the journal replay stats of a restarted
            # master (restarts / replayed_events / torn_tail).
            status["stale_reports"] = self._stale_reports
            if self._journal_stats:
                status["journal"] = dict(self._journal_stats)
            depth_fn = self._standby_depth_fn
        if depth_fn is not None:
            depth = depth_fn()
            if depth is not None:
                status["standby_pool"] = int(depth)
        if self.evaluation is not None:
            status["eval_metrics"] = self.evaluation.latest_metrics()
            status["eval_rounds"] = self.evaluation.completed_rounds()
        return status

    # -- wiring --

    def method_table(self) -> dict:
        return {
            name: getattr(self, name)
            for name in (
                "GetTask",
                "GetGroupTask",
                "ReportTaskResult",
                "ReportVersion",
                "RegisterWorker",
                "DeregisterWorker",
                "Heartbeat",
                "GetMembership",
                "GetCheckpoint",
                "ReportCheckpoint",
                "JobStatus",
                "DumpTrace",
            )
        }


class MasterServer:
    """gRPC server hosting a MasterServicer on ``port`` (0 = ephemeral)."""

    def __init__(
        self,
        servicer: MasterServicer,
        port: int = 0,
        max_workers: int = 32,
        advertise_host: str = "localhost",
    ):
        self.servicer = servicer
        # Message cap raised on both sides (GRPC_MESSAGE_OPTIONS): the
        # DumpTrace response can carry several full per-process trace
        # rings — far past the 4 MB control-plane default.
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=GRPC_MESSAGE_OPTIONS,
        )
        self._server.add_generic_rpc_handlers(
            (
                make_generic_handler(
                    SERVICE_NAME, servicer.method_table(), schemas=MASTER_SCHEMAS
                ),
            )
        )
        self.port = self._server.add_insecure_port(f"[::]:{port}")
        # The host workers dial; for cluster deployments this must be a
        # cross-pod-reachable address (pod IP / headless-service name), not
        # localhost — see Master._advertise_host.
        self.advertise_host = advertise_host

    @property
    def address(self) -> str:
        return f"{self.advertise_host}:{self.port}"

    def start(self) -> "MasterServer":
        self._server.start()
        logger.info("master gRPC service on %s", self.address)
        return self

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace)
