"""Evaluation scheduling + metric aggregation.

Reference parity (SURVEY.md §2 #6 [U — mount empty at survey time]): the
master schedules evaluation jobs at ``--evaluation_steps`` intervals (and at
epoch end), fans the validation set out as eval tasks through the same task
queue workers already poll, and aggregates the metrics workers report.

Metrics are aggregated as (sum, count) pairs so partial shards and unequal
batch sizes weight correctly.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from elasticdl_tpu.common import locksan
from elasticdl_tpu.data.reader import Shard
from elasticdl_tpu.master.task_dispatcher import (
    TASK_EVALUATION,
    Task,
    TaskDispatcher,
)


class EvaluationService:
    def __init__(
        self,
        eval_shards: List[Shard],
        evaluation_steps: int = 0,
        task_timeout_s: float = 600.0,
    ):
        self._shards = list(eval_shards)
        self._every = evaluation_steps
        self._task_timeout_s = task_timeout_s
        # Held while consulting the round dispatcher's finished()/counts —
        # so it orders before TaskDispatcher._lock, never after.
        self._lock = locksan.lock("EvaluationService._lock")
        self._dispatcher: Optional[TaskDispatcher] = None
        self._last_triggered_version = 0
        self._sums: Dict[str, float] = {}
        self._counts: Dict[str, float] = {}
        self._completed_rounds = 0
        self._latest: Dict[str, float] = {}

    # -- scheduling --

    def maybe_trigger(self, model_version: int) -> bool:
        """Called by the master as training progresses (model_version = global
        step).  Starts an eval round when the interval elapses."""
        if not self._shards or self._every <= 0:
            return False
        with self._lock:
            if self._dispatcher is not None and not self._dispatcher.finished():
                return False  # round in flight
            if model_version - self._last_triggered_version < self._every:
                return False
            self._start_round_locked(model_version)
            return True

    def trigger(self, model_version: int = 0) -> bool:
        """Unconditional round start (epoch end / final eval)."""
        if not self._shards:
            return False
        with self._lock:
            if self._dispatcher is not None and not self._dispatcher.finished():
                return False
            self._start_round_locked(model_version)
            return True

    def _start_round_locked(self, model_version: int) -> None:
        self._dispatcher = TaskDispatcher(
            self._shards,
            num_epochs=1,
            task_type=TASK_EVALUATION,
            task_timeout_s=self._task_timeout_s,
        )
        self._last_triggered_version = model_version
        self._sums, self._counts = {}, {}

    # -- task plumbing (master's get_task consults this first) --

    def get_task(self, worker_id: str) -> Optional[Task]:
        with self._lock:
            dispatcher = self._dispatcher
        if dispatcher is None:
            return None
        return dispatcher.get_task(worker_id)

    def report_task(self, task_id: int, success: bool) -> bool:
        with self._lock:
            dispatcher = self._dispatcher
        if dispatcher is None:
            return False
        ok = dispatcher.report(task_id, success)
        if ok and dispatcher.finished():
            with self._lock:
                self._completed_rounds += 1
                self._latest = self._result_locked()
        return ok

    def recover_tasks(self, worker_id: str) -> List[Task]:
        with self._lock:
            dispatcher = self._dispatcher
        return dispatcher.recover_tasks(worker_id) if dispatcher else []

    def tasks_pending(self) -> bool:
        """True while the in-flight round still has UNDISPATCHED tasks —
        the servicer's heartbeat hint (r9) that a worker holding buffered
        training leases should return them and pull the eval work, keeping
        the round's model-version skew at the pre-lease bound."""
        with self._lock:
            dispatcher = self._dispatcher
        return dispatcher is not None and dispatcher.counts()["todo"] > 0

    # -- metric aggregation --

    def report_metrics(self, metrics: Dict[str, float], weight: float) -> None:
        """Worker reports per-shard metric means with their example count.
        Histogram metrics (streaming AUC — lists) accumulate elementwise
        under the same weighting; histograms are linear, so the weighted
        mean of per-shard histograms IS the pooled histogram up to a scale
        the derived AUC is invariant to."""
        import numpy as np

        with self._lock:
            for name, value in metrics.items():
                value = np.asarray(value, np.float64)
                self._sums[name] = self._sums.get(name, 0.0) + value * weight
                self._counts[name] = self._counts.get(name, 0.0) + weight

    def _result_locked(self) -> Dict[str, float]:
        from elasticdl_tpu.common.metrics import finalize_metrics

        return finalize_metrics({
            name: self._sums[name] / max(self._counts[name], 1e-12)
            for name in self._sums
        })

    def latest_metrics(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._latest)

    def completed_rounds(self) -> int:
        with self._lock:
            return self._completed_rounds

    def enabled(self) -> bool:
        """False when there is no validation data to evaluate on."""
        return bool(self._shards)

    def round_in_flight(self) -> bool:
        with self._lock:
            return self._dispatcher is not None and not self._dispatcher.finished()
