"""Master pod entry point — job orchestration.

Reference parity (SURVEY.md §2 #2, §3.1-3.2 [U]): the master process wires
together the task dispatcher (dynamic sharding), the rendezvous server
(elastic membership), the evaluation service, the gRPC servicer, and the
PodManager (worker fleet), then supervises the job to completion:

- dead-worker reaping (stale heartbeats -> membership bump -> task requeue),
- pod failure events -> membership removal + relaunch (PodManager policy),
- end-of-job: final eval round, fleet teardown, job status summary.

Run as ``python -m elasticdl_tpu.master.main`` (the CLI's train/evaluate/
predict subcommands spawn exactly this), or embed via ``Master`` for tests.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional

from elasticdl_tpu.common.config import JobConfig, parse_args
from elasticdl_tpu.common.log_utils import get_logger

# Deliberately NO apply_platform_env() here: that helper imports jax when
# JAX_PLATFORMS is set, and the master is a pure control-plane process that
# must stay jax-free (graftlint import-hygiene; the runtime twin in
# tests/test_graftlint.py caught the old module-level call pulling jax —
# ~13 s of import on the relaunch path and a possible hang on the tunneled
# chip plugin, for a process that never runs a computation).  Worker/PS
# subprocesses assert their own platform at startup.
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.pod_manager import (
    PodBackend,
    PodManager,
    PodPhase,
    ProcessPodBackend,
)
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
from elasticdl_tpu.master.task_dispatcher import (
    TASK_EVALUATION,
    TASK_PREDICTION,
    TASK_TRAINING,
    TaskDispatcher,
)

logger = get_logger("master.main")

#: The coarse task-progress watermark under checkpoint_dir: the restart
#: fallback when the journal is missing/corrupt, and the consistency
#: anchor tying task progress to the restorable model step.
PROGRESS_FILENAME = "job_progress.json"  # durable-file


def _pick_free_ports(n: int) -> List[int]:
    """``n`` distinct currently-free localhost ports (bind-0 then release).
    Racy by nature — another process could grab one before the PS pod binds —
    but PS launch retries (PodManager relaunch policy) absorb the loss."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class Master:
    """One training/evaluation/prediction job, master side."""

    def __init__(
        self,
        config: JobConfig,
        pod_backend: Optional[PodBackend] = None,
        port: int = 0,
        heartbeat_timeout_s: float = 30.0,
        ps_backend: Optional[PodBackend] = None,
    ):
        config.validate()
        self.config = config
        if config.chaos:
            # graftchaos (r18): the master is now a fault TARGET too
            # (kill:target=master fires at the servicer's report hook).
            # Worker-addressed faults can never match master hook points,
            # so arming the whole plan here is safe.
            from elasticdl_tpu import chaos

            chaos.configure(config.chaos)
        if config.trace:
            # Master-side spans (rpc.server handlers, dispatcher lease
            # events) join the same merged trace the workers ship into —
            # and the master clock is the reference every worker offset
            # aims at (stdlib recorder: the control plane stays jax-free).
            from elasticdl_tpu.common import trace as _trace

            _trace.configure(
                enabled=True, capacity=config.trace_buffer_events
            )
        records_per_task = (
            config.minibatch_size * config.num_minibatches_per_task
        )

        # -- task queues from the job's datasets --
        if config.job_type == "training":
            primary, task_type = config.training_data, TASK_TRAINING
        elif config.job_type == "evaluation":
            primary, task_type = config.validation_data, TASK_EVALUATION
        else:
            primary, task_type = config.prediction_data, TASK_PREDICTION
        if not primary:
            raise ValueError(f"no data path configured for {config.job_type}")
        reader = create_data_reader(
            primary, config.parsed_data_reader_params()
        )
        shards = reader.create_shards(records_per_task)
        # Master-restart resume (SURVEY §5 "restore on master restart"): a
        # training job with a checkpoint_dir persists its task-progress
        # watermark (epoch + done shards); a restarted master skips finished
        # work instead of re-running the epoch from the top — model state
        # already resumes via the workers' checkpoint restore, so together a
        # master restart loses at most the in-flight shards.  Persisted
        # state is ignored when the job shape changed (different data/epoch
        # config — the watermark would skip the wrong shards).
        self._progress_path = (
            os.path.join(config.checkpoint_dir, PROGRESS_FILENAME)
            if config.job_type == "training" and config.checkpoint_dir
            else ""
        )
        self._last_progress: Optional[str] = None
        # Durable control-plane journal (r18, master/journal.py): the
        # fsync'd WAL of every hand-out/report/requeue/gang-log-entry
        # supersedes the coarse watermark on restart — a restarted master
        # resumes the EXACT pre-crash dispatcher state (in-flight leases
        # and all) and reconciles reconnecting workers against it.  The
        # watermark stays as the fallback (journal missing/corrupt) and
        # the model-checkpoint consistency anchor.
        from elasticdl_tpu.master.journal import JOURNAL_FILENAME

        self._journal = None
        self._journal_path = (
            os.path.join(config.checkpoint_dir, JOURNAL_FILENAME)
            if self._progress_path
            else ""
        )
        num_epochs = config.num_epochs if config.job_type == "training" else 1
        replayed = self._replay_journal(shards, num_epochs, task_type)
        if replayed is not None:
            self.dispatcher = replayed.dispatcher
        else:
            resume = self._load_progress(len(shards), config.num_epochs)
            self.dispatcher = TaskDispatcher(
                shards,
                num_epochs=num_epochs,
                task_type=task_type,
                task_timeout_s=config.task_timeout_s,
                task_skip_budget=config.gang_skip_budget,
                resume=resume,
            )
        self.evaluation: Optional[EvaluationService] = None
        if config.job_type == "training" and config.validation_data:
            eval_reader = create_data_reader(
                config.validation_data, config.parsed_data_reader_params()
            )
            self.evaluation = EvaluationService(
                eval_reader.create_shards(records_per_task),
                evaluation_steps=config.evaluation_steps,
                task_timeout_s=config.task_timeout_s,
            )

        # -- control plane --
        self.rendezvous = RendezvousServer(
            heartbeat_timeout_s=heartbeat_timeout_s
        )
        self.metrics_writer = None
        if config.metrics_dir:
            from elasticdl_tpu.common.metrics import MetricsWriter

            self.metrics_writer = MetricsWriter(config.metrics_dir)
        self.servicer = MasterServicer(
            self.dispatcher,
            rendezvous=self.rendezvous,
            evaluation=self.evaluation,
            final_eval=self.evaluation is not None,
            metrics_writer=self.metrics_writer,
            max_steps=config.max_steps,
            # --evaluation_steps=0 means "eval at each epoch end" (the
            # reference's semantics); >0 means interval-based rounds.
            epoch_end_eval=config.evaluation_steps == 0,
            # Deadline-bounded gang boundary (r13, docs/robustness.md).
            gang_deadline_ms=config.gang_deadline_ms,
        )
        # Task watermark persists when a model checkpoint is REPORTED — the
        # only moment the (model state, data progress) pair is consistent on
        # disk (see _persist_progress).
        self.servicer.set_checkpoint_callback(self._persist_progress)
        if replayed is not None and config.chaos:
            # A master kill must not crash-loop its own relaunch (the
            # worker-kill family's incarnation guard, mirrored): the
            # replayed dispatcher already satisfies step=N, so a restarted
            # master re-arming the same plan would die at its first
            # applied report, and the next, forever.  Master-targeted
            # kills disarm on any journal-replayed restart.
            from elasticdl_tpu import chaos
            from elasticdl_tpu.chaos.inject import parse_plan

            plan = parse_plan(config.chaos)
            kept = [
                f for f in plan
                if not (f.kind == "kill" and f.target == "master")
            ]
            if len(kept) != len(plan):
                logger.warning(
                    "disarming %d master-kill chaos fault(s) on a "
                    "restarted master (a kill must not crash-loop its "
                    "own relaunch)", len(plan) - len(kept),
                )
                chaos.configure(plan=kept)
        if replayed is not None:
            # Version numbering continues from the pre-crash world: a
            # reconnecting worker's re-registration must observe a BUMP
            # (never a reused number its stale view could mistake for its
            # own), and the replayed group log's version stays comparable.
            self.rendezvous.seed_version(replayed.membership_version)
            self.servicer.adopt_replayed(replayed)
            reg = self.servicer.fleet.registry
            reg.counter(
                "edl_master_restarts_total",
                "journal-replayed master restarts of this job",
            ).inc(replayed.restarts + 1)
            reg.gauge(
                "edl_master_journal_replay_ms",
                "wall time of the last journal replay",
            ).set(self._journal_replay_ms)
        if self._journal_path:
            from elasticdl_tpu.master.journal import MasterJournal

            self._journal = MasterJournal(self._journal_path)
            self.servicer.set_journal(self._journal)
            self.dispatcher.attach_journal(self._journal)
            if replayed is None or not replayed.events_applied:
                # Fresh job / watermark fallback / base-only restart:
                # start a clean WAL from the current (checkpoint-
                # consistent) state.
                self.servicer.rotate_journal()
            else:
                # FULL replay: deliberately NO rotation — the WAL's base
                # must stay the last CHECKPOINT-COUPLED snapshot.  A base
                # rotated here would bake the replayed post-checkpoint
                # progress (live only in the surviving workers' memory)
                # into the very record a LATER whole-node restart's
                # base-only mode trusts as checkpoint-consistent — the
                # rolled-forward-ledger hazard in a new coat.  Continued
                # events append to the existing file (replay chains
                # across master generations); the next checkpoint report
                # compacts as usual.
                logger.info(
                    "continuing the existing WAL (full replay): the base "
                    "stays checkpoint-coupled; next checkpoint compacts"
                )
                # The restart itself is an event (pre-server: no handler
                # threads yet, so no lock discipline applies) — replay
                # counts these on top of the base's restarts, keeping the
                # counter honest across rotation-free restart chains.
                self._journal.record({"kind": "restart"})
        self.server = MasterServer(
            self.servicer, port=port, advertise_host=self._advertise_host(config)
        )
        # Workers learn the master address through the config bus.
        config.master_addr = self.server.address

        # -- PS fleet (host-tier service shards, ps/service.py) --
        # Launched BEFORE workers so config.ps_addresses is on the worker
        # config bus; fixed size (id-mod-n table partition — resharding a
        # live fleet would remap every row's owner), pods relaunch on
        # failure and restore their slice from the newest snapshot at
        # startup (ps/main.py).  The reference's PS pods are likewise a
        # fixed, master-created fleet (SURVEY.md §2 #10 [U]).
        # The fleet runs for EVERY job type: evaluation/prediction over a
        # PS-trained checkpoint needs the shards serving their restored
        # slices (snapshots are per-shard files only the PS tier reads) —
        # without them the trainer would fall back to fresh local stores
        # and score re-initialized embeddings.
        self.ps_manager: Optional[PodManager] = None
        if config.num_ps_pods > 0:
            ps_env: Dict[str, str] = {}
            if config.pod_backend == "kubernetes":
                # Cross-pod DNS needs a governing headless service named
                # "<job>-ps" (documented deploy requirement); every shard
                # serves the fixed PS port.  Addresses use the pod's STABLE
                # per-slot hostname (render_ps_pod_manifest pins
                # spec.hostname to the slot, so relaunched shards keep
                # answering here) in the resolvable
                # <hostname>.<subdomain>.<ns>.svc form.
                port = 2222
                ps_env["ELASTICDL_PS_PORTS"] = ",".join(
                    str(port) for _ in range(config.num_ps_pods)
                )
                hosts = [
                    f"{config.job_name}-ps-{i}.{config.job_name}-ps."
                    f"{config.namespace}.svc:{port}"
                    for i in range(config.num_ps_pods)
                ]
            else:
                ports = _pick_free_ports(config.num_ps_pods)
                ps_env["ELASTICDL_PS_PORTS"] = ",".join(map(str, ports))
                hosts = [f"localhost:{p}" for p in ports]
            config.ps_addresses = ",".join(hosts)
            self.ps_manager = PodManager(
                ps_backend if ps_backend is not None
                else self._build_ps_backend(config),
                config,
                worker_env=ps_env,
                name_prefix=f"{config.job_name}-ps",
            )

        # -- worker fleet --
        self.pod_manager = PodManager(
            pod_backend if pod_backend is not None else self._build_backend(config),
            config,
            # Pod reattach registry (r18): persisted beside the journal so
            # worker supervision survives a master crash — the restarted
            # master ADOPTS the live orphans instead of spawning a second
            # fleet next to the workers riding out the restart.
            state_path=(
                os.path.join(
                    config.checkpoint_dir, PodManager.REGISTRY_FILENAME
                )
                if self._journal_path
                else None
            ),
        )
        self.pod_manager.add_listener(self._on_pod_event)
        # Resolves an adopted orphan's unknowable exit code: after the job
        # finished a disappearance is the worker's clean exit.
        self.pod_manager.set_job_finished_fn(self.servicer.job_finished)
        # Warm-standby pool depth rides Heartbeat/JobStatus (r13): a
        # drained pool must be visible BEFORE the next failure finds it
        # empty and pays a cold relaunch.
        self.servicer.set_standby_depth(self.pod_manager.standby_depth)

        # graftgauge (r14): the master's live /metrics endpoint serves the
        # fleet-aggregated view + goodput/SLO computer (servicer.fleet,
        # master/fleet_metrics.py) — workers ship their registry snapshots
        # on the heartbeat/report gauge envelope, this endpoint is where an
        # operator (or tools/watch_job.py) reads them DURING the job.  The
        # PodManager's fleet-churn scalars join as a collector, so the pod
        # plane is visible on the same page (stdlib HTTP: the control
        # plane stays jax-free).
        from elasticdl_tpu.common.metrics_http import maybe_start

        self.servicer.fleet.registry.add_collector(self._collect_pod_gauges)
        self.metrics_server = maybe_start(
            config.gauge_port,
            self.servicer.fleet.render,
            health_fn=self.servicer.fleet.health,
            registry=self.servicer.fleet.registry,
        )

    def _collect_pod_gauges(self) -> None:
        """Scrape-time collector: PodManager fleet churn (worker + PS
        fleets) into the master registry."""
        reg = self.servicer.fleet.registry
        for prefix, mgr in (("worker", self.pod_manager), ("ps", self.ps_manager)):
            if mgr is None:
                continue
            for key, v in mgr.counts().items():
                reg.gauge(
                    f"edl_pods_{key}",
                    "pod-fleet state (PodManager.counts)",
                    labels={"fleet": prefix},
                ).set(float(v))

    def _fleet_died_with_old_master(self) -> Optional[bool]:
        """Whole-job-restart probe: True when the pod reattach registry
        POSITIVELY shows the previous fleet dead (>= 1 recorded pid, none
        alive), False when at least one worker is riding the outage out,
        None when the registry offers no evidence (absent/empty — fake
        and k8s backends, in-process tests).  This is what decides
        whether the journal's post-checkpoint events are trustworthy: a
        surviving worker's in-memory model HAS those updates; a dead
        fleet restores from the checkpoint and does not.  Liveness runs
        through PodManager.scan_registry — the SAME zombie- and
        cmdline-guarded probe the adoption path uses, so a recycled pid
        cannot fake a live fleet and full-replay untrained shards away."""
        from elasticdl_tpu.master.pod_manager import PodManager

        scan = PodManager.scan_registry(
            os.path.join(
                self.config.checkpoint_dir, PodManager.REGISTRY_FILENAME
            )
        )
        if not scan["recorded"]:
            return None
        return not scan["alive"]

    def _replay_journal(self, shards, num_epochs: int, task_type: str):
        """Rebuild the pre-crash control plane from the WAL, or None to
        fall back (no journal / corrupt / different job shape / any
        unexpected shape skew — each falls back LOUDLY to the coarse
        watermark, never half-replays and never crash-loops the restart
        on a bad file)."""
        self._journal_replay_ms = 0.0
        if not self._journal_path or not os.path.exists(self._journal_path):
            return None
        from elasticdl_tpu.master import journal as journal_mod

        # Whole-job restart (fleet positively dead): the workers will
        # restore the MODEL from the last checkpoint, so control-plane
        # progress past the checkpoint-coupled journal BASE describes
        # gradient updates that died with them — replaying it would skip
        # shards the restored model never saw.  Base-only replay keeps
        # the checkpoint-consistency contract; the skipped tail simply
        # re-trains (at-least-once, the pre-r18 stance).  A live worker
        # (master-only crash) keeps the full, exact replay.
        base_only = self._fleet_died_with_old_master() is True
        if base_only:
            logger.warning(
                "previous worker fleet is gone: replaying the journal "
                "BASE only (checkpoint-consistent) — post-checkpoint "
                "control-plane progress re-trains rather than pairing a "
                "rolled-back model with a rolled-forward task ledger",
            )
        t0 = time.perf_counter()
        try:
            replayed = journal_mod.replay(
                self._journal_path,
                shards,
                num_epochs=num_epochs,
                task_type=task_type,
                task_timeout_s=self.config.task_timeout_s,
                task_skip_budget=self.config.gang_skip_budget,
                base_only=base_only,
            )
        except Exception:
            # Deliberately broad: a journal that PARSES but violates the
            # expected shape (format skew, partial corruption) surfaces
            # as KeyError/TypeError deep in the restore — any such file
            # must degrade to the watermark once, loudly, not crash-loop
            # every subsequent restart through the same exception.
            logger.exception(
                "journal %s unusable; falling back to the coarse "
                "watermark", self._journal_path,
            )
            return None
        self._journal_replay_ms = round((time.perf_counter() - t0) * 1e3, 2)
        counts = replayed.dispatcher.counts()
        logger.info(
            "master restart: replayed %d journal event(s) in %.1f ms — "
            "done=%d doing=%d todo=%d, group log %d entr%s, restart #%d%s",
            replayed.events_applied, self._journal_replay_ms,
            counts["done"], counts["doing"], counts["todo"],
            len(replayed.group_log),
            "y" if len(replayed.group_log) == 1 else "ies",
            replayed.restarts + 1,
            " (torn tail tolerated)" if replayed.torn_tail else "",
        )
        from elasticdl_tpu.common import trace as _trace

        # The masterfail bench's replay-stage clock (wall-anchored ts, so
        # cross-process decomposition needs no alignment).
        _trace.instant(
            "master:replay", cat="elastic",
            events=replayed.events_applied,
            replay_ms=self._journal_replay_ms,
            done=counts["done"], doing=counts["doing"],
            restarts=replayed.restarts + 1,
            torn_tail=replayed.torn_tail,
        )
        return replayed

    # recovery-path
    def _load_progress(self, num_shards: int, num_epochs: int):
        if not self._progress_path or not os.path.exists(self._progress_path):
            return None
        from elasticdl_tpu.common import durable

        progress = durable.read_json_tolerant(self._progress_path)
        if not isinstance(progress, dict):
            logger.warning("unreadable job progress file; starting fresh")
            return None
        if (
            progress.get("num_shards") != num_shards
            or progress.get("num_epochs") != num_epochs
        ):
            logger.warning(
                "job progress watermark is for a different job shape "
                "(%s shards x %s epochs vs %d x %d); starting fresh",
                progress.get("num_shards"), progress.get("num_epochs"),
                num_shards, num_epochs,
            )
            return None
        logger.info(
            "resuming task progress: epoch %s, %s shards done in it, "
            "%s tasks done total",
            progress.get("epoch"), len(progress.get("done_shards", [])),
            progress.get("done_count"),
        )
        return progress

    def _persist_progress(self, _step: int = 0) -> None:
        """Atomically write the dispatcher watermark when it changed.

        Called from the servicer's ReportCheckpoint hook (and once at job
        end) — NEVER on a timer: a watermark persisted ahead of the model
        checkpoint would make a restarted master skip shards whose gradient
        updates the restored (older) model never received.  Coupling the
        write to the checkpoint report keeps the pair consistent to within
        the report's network latency.
        """
        if not self._progress_path:
            return
        import json

        from elasticdl_tpu.common import durable

        payload = json.dumps(self.dispatcher.progress(), sort_keys=True)
        if payload == self._last_progress:
            return
        # The old hand-rolled temp+rename here skipped BOTH fsyncs: a
        # power loss after the rename could surface an empty/old watermark
        # under a newer checkpoint.  atomic_publish closes that.
        durable.atomic_publish(self._progress_path, payload)
        self._last_progress = payload
        # Journal compaction rides the same checkpoint-coupled cadence:
        # the WAL restarts from a fresh full-state base whenever the
        # watermark advances, so it stays bounded by one checkpoint
        # interval's control-plane traffic (master/journal.py).
        if self._journal is not None:
            self.servicer.rotate_journal()

    @staticmethod
    def _advertise_host(config: JobConfig) -> str:
        """The address workers dial.  Cross-pod backends need a reachable
        host: the pod IP via the downward API (``MY_POD_IP``) or this host's
        FQDN; local backends keep localhost."""
        if config.master_advertise_host:
            return config.master_advertise_host
        if config.pod_backend == "kubernetes":
            import socket

            return os.environ.get("MY_POD_IP") or socket.getfqdn()
        return "localhost"

    @staticmethod
    def _build_ps_backend(config: JobConfig) -> PodBackend:
        if config.pod_backend == "kubernetes":
            from elasticdl_tpu.master.pod_manager import (
                KubernetesPodBackend,
                render_ps_pod_manifest,
            )

            return KubernetesPodBackend(
                config, namespace=config.namespace,
                renderer=render_ps_pod_manifest, image=config.worker_image,
            )
        if config.pod_backend == "fake":
            from elasticdl_tpu.master.pod_manager import FakePodBackend

            return FakePodBackend()
        return ProcessPodBackend(
            argv=[sys.executable, "-m", "elasticdl_tpu.ps.main"]
        )

    def _wait_ps_ready(self, timeout_s: float = 60.0) -> None:
        """Block until every PS shard's channel is ready — workers launched
        against an unreachable PS fleet would crash-loop their relaunch
        budgets away."""
        if self.ps_manager is None or self.config.pod_backend == "fake":
            return
        import grpc

        from elasticdl_tpu.common.rpc import wait_channel_ready

        for addr in self.config.ps_addresses.split(","):
            channel = grpc.insecure_channel(addr)
            try:
                # Short probes under the shared backoff (r18): a shard
                # paying its startup restore keeps getting re-probed
                # instead of one hard wait, and the terminal error names
                # the shard.
                wait_channel_ready(
                    channel, service="ps", budget_s=timeout_s,
                    terminal=lambda e, n, t, addr=addr: RuntimeError(
                        f"PS shard at {addr} not reachable after {t:.0f}s"
                    ),
                )
            finally:
                channel.close()

    @staticmethod
    def _build_backend(config: JobConfig) -> PodBackend:
        if config.pod_backend == "kubernetes":
            from elasticdl_tpu.master.pod_manager import KubernetesPodBackend

            return KubernetesPodBackend(
                config, namespace=config.namespace, image=config.worker_image
            )
        if config.pod_backend == "fake":
            from elasticdl_tpu.master.pod_manager import FakePodBackend

            return FakePodBackend()
        return ProcessPodBackend(
            warm_standby=config.warm_worker_standby,
            standby_pool=config.standby_pool,
            log_dir=config.pod_log_dir or None,
        )

    # Pod death cascades: membership bump -> servicer listener requeues tasks.
    def _on_pod_event(self, pod_name: str, phase: str) -> None:
        if phase in PodPhase.TERMINAL:
            self.rendezvous.remove(pod_name)

    def scale(self, n: int) -> None:
        """Elastic resize (the 4->8->4 path): grow/shrink the worker fleet."""
        # The rendezvous learns the target FIRST so workers registering
        # during the resize wait for the full gang instead of forming
        # worlds one member at a time (worker.main settle loop).
        self.rendezvous.set_expected(n)
        self.pod_manager.scale(n)

    def run(self, poll_interval_s: float = 0.2, reap_every_s: float = 5.0) -> Dict:
        """Supervise the job to completion; returns the final job status."""
        self.server.start()
        last_reap = time.monotonic()
        try:
            if self.ps_manager is not None:
                # PS shards come up BEFORE workers dial them (launch order
                # is the readiness story the reference gets from k8s init
                # ordering).  Inside the try: a readiness timeout must still
                # tear down the pods already launched.
                self.ps_manager.start(self.config.num_ps_pods)
                self._wait_ps_ready()
            self.rendezvous.set_expected(self.config.num_workers)
            self.pod_manager.start()
            while not self.servicer.job_finished():
                now = time.monotonic()
                if now - last_reap >= reap_every_s:
                    dead = self.rendezvous.reap_dead()
                    if dead:
                        logger.warning("reaped stale workers: %s", dead)
                    last_reap = now
                if self.pod_manager.all_finished() and self.pod_manager.desired() > 0:
                    # Whole fleet exited (relaunch budgets burned) with work
                    # left: fail the job instead of spinning forever.
                    if not self.servicer.job_finished():
                        raise RuntimeError(
                            "all worker pods terminated before the job finished"
                        )
                time.sleep(poll_interval_s)
            self._persist_progress()  # final watermark: job complete
            # Grace period (--shutdown_grace_s): workers that just learned
            # the job is finished are still writing their FINAL checkpoint
            # (orbax + host-tier store snapshots); tearing the fleet down
            # immediately would kill them mid-write.  They exit on their own
            # right after, which ends the wait early.
            deadline = time.monotonic() + self.config.shutdown_grace_s
            while (
                not self.pod_manager.all_finished()
                and time.monotonic() < deadline
            ):
                time.sleep(poll_interval_s)
            status = self.servicer.JobStatus({})
            logger.info("job finished: %s", status)
            return status
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        if self.metrics_server is not None:
            self.metrics_server.stop()
        self.pod_manager.stop()
        if self.ps_manager is not None:
            # After workers: their final checkpoint fans a Save out to the
            # PS shards, which must still be serving.
            self.ps_manager.stop()
        self.server.stop()
        if self.metrics_writer is not None:
            self.metrics_writer.close()
        if self._journal is not None:
            self._journal.close()


def main(argv: Optional[List[str]] = None) -> int:
    try:
        config = JobConfig.from_env()
    except KeyError:
        config = parse_args(argv)
    from elasticdl_tpu.common.log_utils import set_level

    set_level(config.log_level)
    # --master_port (r18): a fixed port is what makes a master RESTART
    # transparent to the fleet — workers ride out the outage redialing
    # the address they already hold.  0 keeps the ephemeral-bind default.
    master = Master(config, port=config.master_port)
    status = master.run()
    return 0 if not status.get("abandoned") else 1


if __name__ == "__main__":
    sys.exit(main())
