"""Master pod entry point — job orchestration.

Reference parity (SURVEY.md §2 #2, §3.1-3.2 [U]): the master process wires
together the task dispatcher (dynamic sharding), the rendezvous server
(elastic membership), the evaluation service, the gRPC servicer, and the
PodManager (worker fleet), then supervises the job to completion:

- dead-worker reaping (stale heartbeats -> membership bump -> task requeue),
- pod failure events -> membership removal + relaunch (PodManager policy),
- end-of-job: final eval round, fleet teardown, job status summary.

Run as ``python -m elasticdl_tpu.master.main`` (the CLI's train/evaluate/
predict subcommands spawn exactly this), or embed via ``Master`` for tests.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional

from elasticdl_tpu.common.config import JobConfig, parse_args
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.platform import apply_platform_env

apply_platform_env()
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.pod_manager import (
    PodBackend,
    PodManager,
    PodPhase,
    ProcessPodBackend,
)
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.servicer import MasterServer, MasterServicer
from elasticdl_tpu.master.task_dispatcher import (
    TASK_EVALUATION,
    TASK_PREDICTION,
    TASK_TRAINING,
    TaskDispatcher,
)

logger = get_logger("master.main")


class Master:
    """One training/evaluation/prediction job, master side."""

    def __init__(
        self,
        config: JobConfig,
        pod_backend: Optional[PodBackend] = None,
        port: int = 0,
        heartbeat_timeout_s: float = 30.0,
    ):
        config.validate()
        self.config = config
        records_per_task = (
            config.minibatch_size * config.num_minibatches_per_task
        )

        # -- task queues from the job's datasets --
        if config.job_type == "training":
            primary, task_type = config.training_data, TASK_TRAINING
        elif config.job_type == "evaluation":
            primary, task_type = config.validation_data, TASK_EVALUATION
        else:
            primary, task_type = config.prediction_data, TASK_PREDICTION
        if not primary:
            raise ValueError(f"no data path configured for {config.job_type}")
        reader = create_data_reader(
            primary, config.parsed_data_reader_params()
        )
        self.dispatcher = TaskDispatcher(
            reader.create_shards(records_per_task),
            num_epochs=config.num_epochs if config.job_type == "training" else 1,
            task_type=task_type,
            task_timeout_s=config.task_timeout_s,
        )
        self.evaluation: Optional[EvaluationService] = None
        if config.job_type == "training" and config.validation_data:
            eval_reader = create_data_reader(
                config.validation_data, config.parsed_data_reader_params()
            )
            self.evaluation = EvaluationService(
                eval_reader.create_shards(records_per_task),
                evaluation_steps=config.evaluation_steps,
                task_timeout_s=config.task_timeout_s,
            )

        # -- control plane --
        self.rendezvous = RendezvousServer(
            heartbeat_timeout_s=heartbeat_timeout_s
        )
        self.metrics_writer = None
        if config.metrics_dir:
            from elasticdl_tpu.common.metrics import MetricsWriter

            self.metrics_writer = MetricsWriter(config.metrics_dir)
        self.servicer = MasterServicer(
            self.dispatcher,
            rendezvous=self.rendezvous,
            evaluation=self.evaluation,
            final_eval=self.evaluation is not None,
            metrics_writer=self.metrics_writer,
            max_steps=config.max_steps,
            # --evaluation_steps=0 means "eval at each epoch end" (the
            # reference's semantics); >0 means interval-based rounds.
            epoch_end_eval=config.evaluation_steps == 0,
        )
        self.server = MasterServer(
            self.servicer, port=port, advertise_host=self._advertise_host(config)
        )
        # Workers learn the master address through the config bus.
        config.master_addr = self.server.address

        # -- worker fleet --
        self.pod_manager = PodManager(
            pod_backend if pod_backend is not None else self._build_backend(config),
            config,
        )
        self.pod_manager.add_listener(self._on_pod_event)

    @staticmethod
    def _advertise_host(config: JobConfig) -> str:
        """The address workers dial.  Cross-pod backends need a reachable
        host: the pod IP via the downward API (``MY_POD_IP``) or this host's
        FQDN; local backends keep localhost."""
        if config.master_advertise_host:
            return config.master_advertise_host
        if config.pod_backend == "kubernetes":
            import socket

            return os.environ.get("MY_POD_IP") or socket.getfqdn()
        return "localhost"

    @staticmethod
    def _build_backend(config: JobConfig) -> PodBackend:
        if config.pod_backend == "kubernetes":
            from elasticdl_tpu.master.pod_manager import KubernetesPodBackend

            return KubernetesPodBackend(
                config, namespace=config.namespace, image=config.worker_image
            )
        if config.pod_backend == "fake":
            from elasticdl_tpu.master.pod_manager import FakePodBackend

            return FakePodBackend()
        return ProcessPodBackend()

    # Pod death cascades: membership bump -> servicer listener requeues tasks.
    def _on_pod_event(self, pod_name: str, phase: str) -> None:
        if phase in PodPhase.TERMINAL:
            self.rendezvous.remove(pod_name)

    def scale(self, n: int) -> None:
        """Elastic resize (the 4->8->4 path): grow/shrink the worker fleet."""
        self.pod_manager.scale(n)

    def run(self, poll_interval_s: float = 0.2, reap_every_s: float = 5.0) -> Dict:
        """Supervise the job to completion; returns the final job status."""
        self.server.start()
        self.pod_manager.start()
        last_reap = time.monotonic()
        try:
            while not self.servicer.job_finished():
                now = time.monotonic()
                if now - last_reap >= reap_every_s:
                    dead = self.rendezvous.reap_dead()
                    if dead:
                        logger.warning("reaped stale workers: %s", dead)
                    last_reap = now
                if self.pod_manager.all_finished() and self.pod_manager.desired() > 0:
                    # Whole fleet exited (relaunch budgets burned) with work
                    # left: fail the job instead of spinning forever.
                    if not self.servicer.job_finished():
                        raise RuntimeError(
                            "all worker pods terminated before the job finished"
                        )
                time.sleep(poll_interval_s)
            # Grace period (--shutdown_grace_s): workers that just learned
            # the job is finished are still writing their FINAL checkpoint
            # (orbax + host-tier store snapshots); tearing the fleet down
            # immediately would kill them mid-write.  They exit on their own
            # right after, which ends the wait early.
            deadline = time.monotonic() + self.config.shutdown_grace_s
            while (
                not self.pod_manager.all_finished()
                and time.monotonic() < deadline
            ):
                time.sleep(poll_interval_s)
            status = self.servicer.JobStatus({})
            logger.info("job finished: %s", status)
            return status
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        self.pod_manager.stop()
        self.server.stop()
        if self.metrics_writer is not None:
            self.metrics_writer.close()


def main(argv: Optional[List[str]] = None) -> int:
    try:
        config = JobConfig.from_env()
    except KeyError:
        config = parse_args(argv)
    from elasticdl_tpu.common.log_utils import set_level

    set_level(config.log_level)
    master = Master(config)
    status = master.run()
    return 0 if not status.get("abandoned") else 1


if __name__ == "__main__":
    sys.exit(main())
