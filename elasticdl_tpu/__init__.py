"""elasticdl_tpu — a TPU-native elastic distributed deep-learning framework.

A ground-up rebuild of the capabilities of ElasticDL (reference:
Kelang-Tian/elasticdl, a Kubernetes-native elastic training framework built on
TensorFlow/Horovod/gRPC parameter servers) designed TPU-first:

- Synchronous data parallelism is a single jitted train step with
  ``jax.lax.pmean`` gradient sync over an ICI ``jax.sharding.Mesh``
  (replacing the reference's Horovod/NCCL allreduce rings).
- The parameter-server sparse embedding layer becomes an HBM-sharded
  embedding table with collective lookups over the mesh (replacing the
  reference's gRPC pull_embedding_vectors/push_gradients round trips).
- Elastic worker join/leave re-forms the device mesh from a checkpoint
  (replacing the reference's Horovod elastic re-rendezvous).
- A master dynamically shards data into tasks dispatched over gRPC so a
  preempted worker loses no work (same control-plane design as the
  reference, reimplemented).

Layout:
- ``elasticdl_tpu.common``   — config/flags, logging, constants.
- ``elasticdl_tpu.models``   — model contract + model zoo (mnist, cifar10
  resnet, census wide&deep, criteo deepfm).
- ``elasticdl_tpu.ops``      — sharded embedding, pallas kernels.
- ``elasticdl_tpu.parallel`` — mesh management, trainers (AllReduce/PS-hybrid).
- ``elasticdl_tpu.master``   — task dispatcher, gRPC servicer, rendezvous,
  pod manager, evaluation service.
- ``elasticdl_tpu.worker``   — worker main loop.
- ``elasticdl_tpu.data``     — data readers (CSV, recordio-style, synthetic).
- ``elasticdl_tpu.ps``       — native C++ parameter-server store + kernels
  (host-side, for parity with the reference's Go PS).
"""

__version__ = "0.1.0"
