"""Online serving tier (r10): micro-batched inference RPC over the PS host
store, with hot-id embedding caching and zero-drop checkpoint hot reload.

Import surfaces are deliberately split so control-plane/bench processes can
dial the service without paying a jax import:

- jax-free: ``serving.client`` (ServingClient), ``serving.micro_batcher``,
  ``serving.embedding_cache``.
- jax-bound: ``serving.server`` (ServingServer — owns the jitted forward),
  ``serving.checkpoint_watcher`` (reads manifests via common/checkpoint).

This package namespace stays import-light on purpose: import the module
you need, not the package surface.
"""
