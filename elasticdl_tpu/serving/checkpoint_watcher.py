"""Checkpoint watcher — the serving tier's hot-reload trigger.

Polls the training job's published-checkpoint manifest
(``common/checkpoint.publish_manifest``: written by temp + atomic rename
AFTER the Orbax commit and host-store snapshot are both complete) and
invokes ``on_new_step(step, manifest)`` whenever the published step
changes.  Keying off the manifest — never directory listings — is what
makes a reload safe: a step directory exists from the moment Orbax starts
writing it, but the manifest names it only once it is whole, so the watcher
can never hand the server a half-written checkpoint.

The callback runs on the watcher thread; the server's reload
(serving/server.py) does the expensive restore there, CONCURRENT with
serving, and only the final reference swap touches the live path.  A
failing callback must not kill the watcher.  TRANSIENT failures (OSError:
a torn volume, an NFS hiccup mid-restore) retry immediately through the
shared backoff helper (``common/rpc.call_with_backoff`` — r18's one retry
code path, never a hand-rolled loop), because a reload deferred a whole
poll interval is a whole poll interval of stale weights; anything else
(a genuinely corrupt checkpoint) is logged and waits for the next poll
or the next publish — hammering it would fail identically.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from elasticdl_tpu.common import racesan, trace
from elasticdl_tpu.common.checkpoint import read_manifest
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.rpc import BackoffPolicy, call_with_backoff

logger = get_logger("serving.ckpt_watcher")

#: Retry shape for a transiently failing reload: a few fast attempts, then
#: give up until the poll cadence — the volume either heals in milliseconds
#: or the next poll (with fresh manifest state) is the right re-entry.
RELOAD_RETRY_POLICY = BackoffPolicy(
    base_s=0.05, multiplier=2.0, max_s=0.5, jitter=0.2, max_attempts=3
)


# racesan (r16): _applied is single-writer (the watcher thread); the
# main/server-side applied_step() read rides a GIL-atomic int load, so
# the attribute is declared atomic rather than locked.
@racesan.instrument(atomic=("_applied",))
class CheckpointWatcher:
    """Manifest poller: ``on_new_step(step, manifest)`` per published change.

    Any CHANGE of the published step triggers — including a step going
    backwards (a training job restarted from an older checkpoint publishes
    an older step; the serving tier must follow its source of truth, not
    ratchet forward onto weights the trainer abandoned)."""

    def __init__(
        self,
        directory: str,
        on_new_step: Callable[[int, Dict[str, Any]], None],
        poll_interval_s: float = 0.5,
        name: str = "serving",
        initial_step: Optional[int] = None,
    ):
        self.directory = directory
        self.poll_interval_s = poll_interval_s
        self._on_new_step = on_new_step
        # initial_step: the step the server already loaded at startup, so
        # the first poll does not redundantly re-apply it.
        # poke() is "also the deterministic test/bench hook" — callable
        # from any thread — so the consistency story is single-op
        # atomicity (matching the runtime opt-in's atomic=("_applied",)),
        # not a single writer role.
        self._applied: Optional[int] = initial_step  # gil-atomic
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=f"edl-ckpt-watch:{name}", daemon=True
        )

    def start(self) -> "CheckpointWatcher":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            self.poke()

    def poke(self) -> bool:
        """One synchronous poll (the loop body; also the deterministic
        test/bench hook).  True when a new step was applied."""
        m = read_manifest(self.directory)
        if m is None or m["step"] == self._applied:
            return False
        step = int(m["step"])
        try:
            call_with_backoff(
                lambda: self._on_new_step(step, m),
                service="serving.ckpt_watcher",
                is_transient=lambda e: isinstance(e, OSError),
                policy=RELOAD_RETRY_POLICY,
            )
        except Exception:
            logger.exception(
                "hot reload to step %d failed (transient attempts "
                "exhausted, or a non-transient error); retrying at the "
                "poll cadence", step,
            )
            return False
        self._applied = step
        trace.instant("serving:hot_reload", cat="serving", step=step)
        logger.info("hot reload applied: serving checkpoint step %d", step)
        return True

    def applied_step(self) -> Optional[int]:
        """The step the last successful reload applied (None = none yet)."""
        return self._applied

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout_s)
