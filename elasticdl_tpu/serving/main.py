"""Serving replica entrypoint: ``python -m elasticdl_tpu.serving.main``.

The process the fleet controller (serving/fleet.py) spawns per slot via
ProcessPodBackend.  Configuration arrives ENTIRELY by environment — the
pod-manager contract — and is deliberately identity-free except for the
slot:

- ``ELASTICDL_SERVING_CONFIG``: one JSON blob (model zoo/def/params,
  checkpoint dir, PS addresses, batcher + bucket knobs, base ports).  The
  SAME string for every slot, so the spawn env signature is uniform and
  one warm standby spare can serve any slot.
- ``ELASTICDL_WORKER_SLOT``: this replica's slot N.  Ports derive from it
  (gRPC on ``base_port + N``, /metrics on ``metrics_base_port + N``) —
  the address contract the controller and the p2c client resolve by.
- ``ELASTICDL_STANDBY_GO_FILE``: warm-standby mode (worker.main's r13
  protocol, mirrored): pre-pay python + jax + framework imports, publish
  the ``.ready`` marker, park until the pod manager's go-file names the
  replica this process becomes.

Boot order is bind -> load checkpoint -> WARMUP ALL BUCKETS -> serve:
the gRPC port accepts only after every batch bucket is compiled, so a
replica that answers its readiness probe serves its first request at
forward speed, never at XLA-compile speed — the difference between a
scale-up that relieves a p99 blowout and one that deepens it.

Exit contract: SIGTERM (PodManager delete_pod) drains within the grace
window and exits 0.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving.main")


def _park_as_standby(go_file: str) -> str:
    """Warm-standby parking, serving flavor (worker/main.py's protocol):
    pre-pay the boot tail — python + jax + framework + serving imports —
    then park until the pod manager writes the go file naming the replica
    id this process should become.  Nothing here may touch a jax backend:
    the spare must stay adoptable into any slot, and single-device
    backend init belongs after adoption with the slot known.  Returns the
    assigned replica id."""
    import importlib

    for mod in (
        "jax", "jax.numpy", "flax", "optax", "orbax.checkpoint",
        "elasticdl_tpu.parallel.trainer", "elasticdl_tpu.parallel.mesh",
        "elasticdl_tpu.models.spec", "elasticdl_tpu.serving.server",
        "elasticdl_tpu.serving.micro_batcher",
    ):
        importlib.import_module(mod)
    logger.info(
        "serving standby warmed (pid %d); parking on %s", os.getpid(), go_file
    )
    from elasticdl_tpu.common import durable

    ready = go_file + ".ready"
    durable.atomic_publish(ready, str(os.getpid()))
    parent0 = os.getppid()
    while not os.path.exists(go_file):
        if os.getppid() != parent0:
            # Controller died without close(): nothing will ever write the
            # go file — exit instead of parking a jax-loaded interpreter
            # forever (the worker standby's orphan rule).
            logger.info("serving standby orphaned (parent gone); exiting")
            raise SystemExit(0)
        time.sleep(0.05)
    payload = json.loads(open(go_file).read())
    for k, v in payload.get("env", {}).items():
        os.environ[k] = v
    replica_id = payload["worker_id"]
    logger.info("serving standby adopted as %s", replica_id)
    return replica_id


def main() -> int:
    go_file = os.environ.get("ELASTICDL_STANDBY_GO_FILE", "")
    if go_file:
        _park_as_standby(go_file)

    cfg = json.loads(os.environ["ELASTICDL_SERVING_CONFIG"])
    slot = int(os.environ.get("ELASTICDL_WORKER_SLOT", "0"))
    replica_id = os.environ.get("ELASTICDL_WORKER_ID", f"serve-{slot}")
    port = int(cfg.get("base_port", 8700)) + slot
    gauge_port = int(cfg.get("metrics_base_port", 8800)) + slot

    # Trainer before the model zoo: zoo modules import ops.embedding,
    # which mid-module imports parallel (-> trainer -> ops.embedding) —
    # resolvable only when trainer loads first.  Standby parking already
    # orders it this way; the cold-start path must too.
    import elasticdl_tpu.parallel.trainer  # noqa: F401
    from elasticdl_tpu.models.spec import load_model_spec
    from elasticdl_tpu.serving.server import ServingServer

    spec = load_model_spec(
        cfg.get("model_zoo", "elasticdl_tpu.models"),
        cfg["model_def"],
        **(cfg.get("model_params") or {}),
    )
    server = ServingServer(
        spec,
        checkpoint_dir=cfg.get("checkpoint_dir", ""),
        ps_addresses=cfg.get("ps_addresses", ""),
        max_batch=int(cfg.get("max_batch", 64)),
        max_delay_ms=float(cfg.get("max_delay_ms", 5.0)),
        cache_rows=int(cfg.get("cache_rows", 1 << 20)),
        poll_interval_s=float(cfg.get("poll_interval_s", 0.5)),
        port=port,
        gauge_port=gauge_port,
        seed=int(cfg.get("seed", 0)),
        target_p99_ms=float(cfg.get("target_p99_ms", 100.0)),
        batch_buckets=cfg.get("batch_buckets"),
        bulk_weight=float(cfg.get("bulk_weight", 0.25)),
        # Fleet sizing contract: the handler pool rides ABOVE the queue
        # bound so overload lands in the micro-batcher's measured, shedding
        # queue — never invisibly in the gRPC executor (the autoscaler
        # scrapes the batcher's signals, not grpc's).
        max_workers=int(cfg.get("max_workers", 16)),
        max_queue_rows=(
            int(cfg["max_queue_rows"])
            if cfg.get("max_queue_rows") is not None else None
        ),
    )
    warm_s = server.warmup()
    logger.info(
        "replica %s (slot %d): warmed %d bucket(s) in %.2fs; serving on "
        "port %d, /metrics on %d",
        replica_id, slot, len(server._shape_buckets), warm_s, port, gauge_port,
    )
    server.start()

    done = threading.Event()

    def _terminate(signum, frame) -> None:
        logger.info("replica %s: signal %d, draining", replica_id, signum)
        done.set()

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    done.wait()
    server.stop(grace=1.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
