"""Replica-fleet serving front: N serving replicas + a closed autoscaling
loop (ROADMAP "millions of users" — the scale tier).

One :class:`ServingFleetController` owns a fleet of ``serving/server.py``
replicas the way the master owns training workers — literally with the
same machinery, because r13–r18 already built it:

- **Spawn/retire**: ``master/pod_manager.PodManager`` over a pluggable
  backend.  Subprocess replicas run ``python -m elasticdl_tpu.serving.main``
  (ProcessPodBackend; warm-standby spares pre-pay the ~13 s jax import and
  park on a go-file exactly like worker standbys), in-process replicas
  (:class:`InProcessServingBackend`) serve the tier-1 fleet smoke without
  subprocess boot costs.  A replica that crashes relaunches on the
  manager's existing budgeted path — serving inherits training's
  self-healing for free.
- **Controller restart**: the r18 pod-reattach registry (``state_path``).
  A restarted controller ADOPTS the still-serving orphan fleet instead of
  spawning duplicates beside it; replicas ride the restart out, never
  dropping a request.
- **Autoscaling signal**: each replica's live /metrics endpoint (the r14
  plane).  The controller scrapes per-replica online-lane latency
  histograms and per-lane shed counters, forms WINDOWED signals by
  differencing consecutive scrapes (cumulative counters make every poll a
  rate), and compares the worst replica's windowed online p99 against the
  SLO target.

Control law (docs/serving.md has the full table)::

    slo = max over replicas( windowed online p99 / target_p99_ms )
    UP   pressure: slo >= up_slo  OR  online sheds in the window
    DOWN pressure: slo <= down_slo AND zero sheds (any lane) in the window

  Hysteresis, three layers — this is what makes the loop CONVERGE under an
  open-loop QPS ramp instead of flapping:

    1. a deadband between ``up_slo`` and ``down_slo`` where nothing moves;
    2. consecutive-poll streaks (``up_consecutive``/``down_consecutive``,
       down much slower than up — adding capacity late blows the SLO,
       removing it late costs only idle replicas);
    3. a post-action cooldown (``cooldown_polls``) so the fleet's response
       to the LAST action is measured before the next one.

The controller is deliberately jax-free: it is control plane, exactly like
the master, and must stay cheap to run beside anything.  Model/forward
concerns live entirely inside the replicas it manages.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticdl_tpu.common import gauge as gaugelib
from elasticdl_tpu.common import locksan, trace
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.metrics_http import fetch, fetch_text
from elasticdl_tpu.master.pod_manager import PodBackend, PodManager, PodPhase

logger = get_logger("serving.fleet")

#: Default first ports; replica at slot N serves gRPC on base+N and
#: /metrics on metrics_base+N.  Deriving ports from the slot keeps the
#: spawn env IDENTICAL across slots, which is what lets one warm standby
#: spare serve any slot (ProcessPodBackend env-signature matching).
DEFAULT_BASE_PORT = 8700
DEFAULT_METRICS_BASE_PORT = 8800


@dataclass(frozen=True)
class AutoscaleConfig:
    """The closed loop's knobs.  Defaults are tuned for the serving bench's
    second-scale ramps; production cadences would stretch ``poll_s`` and
    the streaks, not change the law."""

    min_replicas: int = 1
    max_replicas: int = 4
    poll_s: float = 1.0
    target_p99_ms: float = 100.0
    #: windowed online p99 / target at or above this = scale-up pressure.
    up_slo: float = 1.0
    #: ... at or below this (with zero sheds) = scale-down pressure.  The
    #: gap between the two thresholds is the hysteresis deadband.
    down_slo: float = 0.6
    #: consecutive pressured polls before acting (up fast, down slow).
    up_consecutive: int = 2
    down_consecutive: int = 6
    #: polls to sit out after ANY scale action before the next decision.
    cooldown_polls: int = 3
    #: graceful-retirement window: a scale-down victim leaves the
    #: readiness set IMMEDIATELY (the p2c client stops picking it at its
    #: next membership refresh) but keeps serving until the window
    #: elapses, and only then is its pod deleted.  Set it >= the client's
    #: refresh cadence or retirement races in-flight picks — clients keep
    #: choosing a replica that just vanished and burn their transient
    #: retries on a corpse (the fleet bench measured exactly that as
    #: client-visible errors).  0 = delete immediately (unit-test mode).
    drain_s: float = 0.0


def _lane_hist_buckets(
    families: Dict[str, dict], lane: str
) -> Dict[float, float]:
    """Cumulative {bucket edge: count} of one lane's request-latency
    histogram from a parsed /metrics scrape."""
    fam = families.get("edl_serving_request_ms")
    out: Dict[float, float] = {}
    if not fam:
        return out
    for s in fam["samples"]:
        if not s["name"].endswith("_bucket"):
            continue
        if s["labels"].get("lane") != lane:
            continue
        le = s["labels"].get("le")
        if le is None:
            continue
        edge = float("inf") if le == "+Inf" else float(le)
        out[edge] = s["value"]
    return out


def _delta_quantile(
    cur: Dict[float, float], prev: Optional[Dict[float, float]], q: float
) -> Optional[float]:
    """Quantile of the observations that landed BETWEEN two scrapes of a
    cumulative-bucket histogram (the registry's own interpolating
    estimator, applied to the bucket-wise difference).  None when the
    window holds no observations — a silent replica must read as "no
    signal", never as "p99 = 0"."""
    edges = sorted(cur)
    if not edges:
        return None
    deltas = [
        (e, max(cur[e] - (prev.get(e, 0.0) if prev else 0.0), 0.0))
        for e in edges
    ]
    total = deltas[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_edge, prev_cum = 0.0, 0.0
    for edge, cum in deltas:
        if cum >= target:
            if edge == float("inf"):
                return prev_edge
            frac = (target - prev_cum) / max(cum - prev_cum, 1e-12)
            return prev_edge + (edge - prev_edge) * frac
        prev_edge, prev_cum = (
            0.0 if edge == float("inf") else edge
        ), cum
    return prev_edge


def _lane_counter(
    families: Dict[str, dict], family: str, lane: str
) -> float:
    fam = families.get(family)
    if not fam:
        return 0.0
    return sum(
        s["value"] for s in fam["samples"] if s["labels"].get("lane") == lane
    )


class InProcessServingBackend(PodBackend):
    """Serving replicas as ServingServer instances IN THIS PROCESS.

    The tier-1 fleet smoke's backend: subprocess replicas each pay the
    full python + jax boot (~13 s on this box) before their first answer,
    which is bench territory, not CI.  ``server_factory(slot)`` builds and
    RETURNS A STARTED, WARMED server (jax stays an implementation detail
    of the factory — this module is import-time jax-free); the backend
    maps pod lifecycle onto it and reports real bound addresses, so the
    controller, the p2c client, and the autoscaler run exactly the code
    they run over subprocess fleets.

    ``adopt_pod`` revives a still-running server by name, which makes the
    r18 controller-restart adoption path testable in-process: hand the
    SAME backend to a second PodManager with the first one's registry and
    the fleet is re-owned without a single server restart."""

    def __init__(self, server_factory: Callable[[int], Any]):
        self._factory = server_factory
        self._lock = locksan.lock("InProcessServingBackend._lock", leaf=True)  # lock-order: leaf
        self._servers: Dict[str, Any] = {}  # guarded-by: _lock

    def start_pod(self, name: str, env: Dict[str, str]) -> None:
        slot = int(env.get("ELASTICDL_WORKER_SLOT", "0"))
        server = self._factory(slot)
        with self._lock:
            self._servers[name] = server
        self._emit(name, PodPhase.RUNNING)

    def adopt_pod(self, name: str, pid: int) -> None:
        with self._lock:
            if name not in self._servers:
                raise RuntimeError(f"no live in-process replica {name!r} to adopt")
        self._emit(name, PodPhase.RUNNING)

    def pid(self, name: str) -> Optional[int]:
        import os

        with self._lock:
            return os.getpid() if name in self._servers else None

    def delete_pod(self, name: str) -> None:
        with self._lock:
            server = self._servers.pop(name, None)
        if server is not None:
            server.stop(grace=0.2)
        self._emit(name, PodPhase.DELETED)

    def serving_address(self, name: str) -> Optional[str]:
        with self._lock:
            server = self._servers.get(name)
        return server.address if server is not None else None

    def metrics_address(self, name: str) -> Optional[str]:
        with self._lock:
            server = self._servers.get(name)
        return server.metrics_address if server is not None else None

    def close(self) -> None:
        with self._lock:
            servers = list(self._servers.values())
            self._servers.clear()
        for server in servers:
            server.stop(grace=0.2)


class ServingFleetController:
    """N serving replicas + the closed autoscaling loop over their gauges.

    ``backend``: any PodBackend.  Backends that expose
    ``serving_address(name)`` / ``metrics_address(name)`` (the in-process
    one) are asked; otherwise addresses derive as
    ``localhost:{base_port + slot}`` / ``localhost:{metrics_base_port +
    slot}`` — the contract ``serving/main.py`` replicas bind by.

    ``state_path`` enables the r18 reattach registry: a controller
    restarted over the same path adopts its live fleet on ``start()``.

    ``scrape_fn(metrics_address) -> parsed families`` is injectable so the
    control law is testable against synthetic signals without HTTP."""

    def __init__(
        self,
        backend: PodBackend,
        config: JobConfig,
        *,
        base_port: int = DEFAULT_BASE_PORT,
        metrics_base_port: int = DEFAULT_METRICS_BASE_PORT,
        worker_env: Optional[Dict[str, str]] = None,
        name_prefix: Optional[str] = None,
        state_path: Optional[str] = None,
        autoscale: Optional[AutoscaleConfig] = None,
        autoscale_enabled: bool = True,
        gauges: Optional[gaugelib.Registry] = None,
        scrape_fn: Optional[Callable[[str], Dict[str, dict]]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._backend = backend
        self.auto = autoscale or AutoscaleConfig()
        self._autoscale_enabled = autoscale_enabled
        self._base_port = base_port
        self._metrics_base_port = metrics_base_port
        self._scrape = scrape_fn or (lambda addr: fetch(addr, timeout_s=2.0))
        self._clock = clock
        self.pods = PodManager(
            backend,
            config,
            worker_env=worker_env,
            name_prefix=name_prefix or f"{config.job_name}-serve",
            state_path=state_path,
        )
        self.gauges = gauges if gauges is not None else gaugelib.default()
        self._lock = locksan.lock("ServingFleetController._lock", leaf=True)  # lock-order: leaf
        #: Scale-action audit trail [(t, from, to, reason)], the bench's
        #: convergence evidence.  guarded-by: _lock
        self.scale_events: List[dict] = []
        # Control-loop state below is single-writer: the autoscale thread,
        # or the caller driving poll_once() when the thread is off (the
        # bench/test hook) — never both, poll_once is not reentrant.
        self._prev_scrapes: Dict[str, Dict[str, dict]] = {}  # single-writer: thread:edl-serve-autoscale
        self._up_streak = 0  # single-writer: thread:edl-serve-autoscale
        self._down_streak = 0  # single-writer: thread:edl-serve-autoscale
        self._cooldown = 0  # single-writer: thread:edl-serve-autoscale
        #: Scale-down victims mid-retirement: name -> clock deadline at
        #: which the pod actually gets deleted.  Written only by the
        #: autoscale writer; read concurrently by replicas() (membership
        #: refreshers) — per-key reads, no iteration over a mutating dict.
        self._draining: Dict[str, float] = {}
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- fleet membership --

    def replicas(self) -> List[Tuple[str, str, str]]:
        """Live replicas as (pod name, serving address, metrics address).
        Backend-reported addresses win (in-process ephemeral ports);
        slot-derived ports otherwise."""
        out = []
        for name in self.pods.live_pods():
            if name in self._draining:
                # Retiring: still serving its in-flight work, but no new
                # picks — clients must stop routing here BEFORE the pod
                # dies, or retirement races their next send.
                continue
            saddr = maddr = None
            if hasattr(self._backend, "serving_address"):
                saddr = self._backend.serving_address(name)
                maddr = self._backend.metrics_address(name)
            if saddr is None or maddr is None:
                info = self.pods.pod_info(name)
                if info is None:
                    continue
                saddr = saddr or f"localhost:{self._base_port + info.slot}"
                maddr = (
                    maddr
                    or f"localhost:{self._metrics_base_port + info.slot}"
                )
            out.append((name, saddr, maddr))
        return out

    def ready_addresses(self, timeout_s: float = 1.0) -> List[str]:
        """Serving addresses of replicas whose /healthz answers right now —
        the readiness view the p2c client load-balances over."""
        ready = []
        for _name, saddr, maddr in self.replicas():
            try:
                if '"status"' in fetch_text(maddr, "/healthz", timeout_s):
                    ready.append(saddr)
            except OSError:
                continue
        return ready

    def wait_ready(self, n: int, timeout_s: float = 120.0) -> List[str]:
        """Block until ``n`` replicas probe ready (or raise)."""
        deadline = time.monotonic() + timeout_s
        while True:
            ready = self.ready_addresses()
            if len(ready) >= n:
                return ready
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"only {len(ready)}/{n} serving replicas ready within "
                    f"{timeout_s}s"
                )
            time.sleep(0.2)

    # -- lifecycle --

    def start(self, n: Optional[int] = None) -> "ServingFleetController":
        """Scale to ``n`` (default min_replicas) — adopting any live
        registry orphans first — and start the autoscale loop."""
        target = max(
            self.auto.min_replicas,
            min(n if n is not None else self.auto.min_replicas,
                self.auto.max_replicas),
        )
        self.pods.scale(target)
        if self._autoscale_enabled:
            self._thread = threading.Thread(
                target=self._autoscale_loop,
                name="edl-serve-autoscale",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Retire the fleet (registry removed — a clean stop owns its
        teardown; crash-stop WITHOUT calling this to exercise adoption)."""
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.pods.stop()

    # -- the closed loop --

    def _autoscale_loop(self) -> None:
        while not self._stop_event.wait(self.auto.poll_s):
            try:
                self.poll_once()
            except Exception:
                # The loop must survive any one poll: a scrape racing a
                # replica retirement is routine, not fatal.
                logger.exception("autoscale poll failed; continuing")

    def poll_once(self) -> Dict[str, Any]:
        """One control-loop iteration: scrape every replica, form the
        windowed knee signal, apply the hysteresis law, maybe scale.
        Returns the decision record (the bench logs these)."""
        a = self.auto
        self._finish_drains()
        scrapes: Dict[str, Dict[str, dict]] = {}
        unreachable = 0
        for name, _saddr, maddr in self.replicas():
            try:
                scrapes[name] = self._scrape(maddr)
            except OSError:
                unreachable += 1
        worst_p99: Optional[float] = None
        shed_online = shed_total = 0.0
        for name, fams in scrapes.items():
            prev = self._prev_scrapes.get(name)
            p99 = _delta_quantile(
                _lane_hist_buckets(fams, "online"),
                _lane_hist_buckets(prev, "online") if prev else None,
                0.99,
            )
            if p99 is not None and (worst_p99 is None or p99 > worst_p99):
                worst_p99 = p99
            if prev is not None:
                # First scrape of a replica is its baseline (an adopted
                # replica arrives with history; counting it as a window
                # delta would read old sheds as fresh pressure).
                for lane in ("online", "bulk"):
                    d = max(
                        _lane_counter(fams, "edl_serving_shed_total", lane)
                        - _lane_counter(prev, "edl_serving_shed_total", lane),
                        0.0,
                    )
                    shed_total += d
                    if lane == "online":
                        shed_online += d
        self._prev_scrapes = scrapes
        slo = (
            worst_p99 / a.target_p99_ms
            if worst_p99 is not None and a.target_p99_ms
            else None
        )

        pressure_up = (slo is not None and slo >= a.up_slo) or shed_online > 0
        pressure_down = (slo is None or slo <= a.down_slo) and shed_total == 0
        if pressure_up:
            self._up_streak += 1
            self._down_streak = 0
        elif pressure_down:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # Deadband: inside the hysteresis gap both streaks reset — a
            # borderline signal must re-earn consecutive evidence.
            self._up_streak = self._down_streak = 0

        # Serving count, not pod count: a draining victim still has a
        # live pod but left the membership — decisions must see the
        # capacity clients can actually reach.
        n = self.pods.desired() - len(self._draining)
        action = ""
        if self._cooldown > 0:
            self._cooldown -= 1
        elif self._up_streak >= a.up_consecutive and n < a.max_replicas:
            action = "up"
            self._record_scale(n, n + 1, slo, shed_online)
            if self._draining:
                # A still-warm draining victim beats a fresh spawn: cancel
                # the newest retirement and fold it back into membership.
                undrain = max(self._draining, key=self._draining.get)
                self._draining.pop(undrain, None)
            else:
                self.pods.scale(self.pods.desired() + 1)
            self._cooldown = a.cooldown_polls
            self._up_streak = self._down_streak = 0
        elif self._down_streak >= a.down_consecutive and n > a.min_replicas:
            action = "down"
            self._record_scale(n, n - 1, slo, shed_online)
            self._retire_one()
            self._cooldown = a.cooldown_polls
            self._up_streak = self._down_streak = 0

        counts = self.pods.counts()
        g = self.gauges
        g.gauge("edl_serving_fleet_replicas", "live serving replicas").set(
            float(counts["live"])
        )
        g.gauge("edl_serving_fleet_desired", "desired serving replicas").set(
            float(counts["desired"])
        )
        if slo is not None:
            g.gauge(
                "edl_serving_fleet_slo_ratio",
                "worst replica's windowed online p99 / target",
            ).set(slo)
        g.counter(
            "edl_serving_fleet_scale_events_total",
            "autoscaler actions taken",
        ).set_total(float(len(self.scale_events)))
        decision = {
            "slo": slo,
            "worst_p99_ms": worst_p99,
            "shed_online": shed_online,
            "shed_total": shed_total,
            "unreachable": unreachable,
            "replicas": counts["live"],
            "desired": counts["desired"],
            "action": action,
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "cooldown": self._cooldown,
        }
        return decision

    def _retire_one(self) -> None:
        """Scale down by one — gracefully when ``drain_s > 0``: the victim
        (the highest live slot, matching PodManager.scale's removal order)
        leaves the membership NOW, keeps draining its in-flight work, and
        its pod is deleted only once the drain window elapses."""
        a = self.auto
        victim = None
        victim_slot = -1
        if a.drain_s > 0:
            for name in self.pods.live_pods():
                if name in self._draining:
                    continue
                info = self.pods.pod_info(name)
                if info is not None and info.slot > victim_slot:
                    victim, victim_slot = name, info.slot
        if victim is None:
            self.pods.scale(self.pods.desired() - 1)
            return
        self._draining[victim] = self._clock() + a.drain_s
        logger.info(
            "retiring %s (slot %d): out of membership now, pod deleted in "
            "%.1fs", victim, victim_slot, a.drain_s,
        )

    def _finish_drains(self) -> None:
        """Delete pods whose drain window has elapsed.  Safe against the
        cooldown-covered window only: PodManager removes the HIGHEST slot
        on scale-down, which is the victim precisely because no scale-up
        spawned above it mid-drain (cooldown_polls x poll_s must cover
        drain_s; the up branch un-drains rather than spawns regardless)."""
        now = self._clock()
        done = [nm for nm, dl in list(self._draining.items()) if dl <= now]
        if not done:
            return
        for nm in done:
            self._draining.pop(nm, None)
            self._prev_scrapes.pop(nm, None)
        self.pods.scale(self.pods.desired() - len(done))

    def _record_scale(
        self, old: int, new: int, slo: Optional[float], shed_online: float
    ) -> None:
        event = {
            "t": self._clock(),
            "from": old,
            "to": new,
            "slo": slo,
            "shed_online": shed_online,
        }
        with self._lock:
            self.scale_events.append(event)
        trace.instant(
            "serving:scale", cat="serving", frm=old, to=new, slo=slo,
        )
        logger.info(
            "autoscale %d -> %d (slo=%s, online sheds in window=%.0f)",
            old, new, "n/a" if slo is None else f"{slo:.2f}", shed_online,
        )

    def events(self) -> List[dict]:
        with self._lock:
            return list(self.scale_events)
