"""Typed client for the serving tier (serving/server.ServingServer).

Deliberately jax-free and numpy-light: an online caller (a web frontend, a
bench driver) dials the prediction service with plain feature lists; the
client validates against SERVING_SCHEMAS before the wire, mirroring
JsonRpcClient's boundary contract for the master service.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from elasticdl_tpu.common.rpc import (
    SERVING_SCHEMAS,
    SERVING_SERVICE_NAME,
    JsonRpcClient,
)


def _jsonable(value: Any) -> Any:
    """Feature value -> JSON-serializable nested lists (numpy arrays and
    scalars included; python lists pass through)."""
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, (np.generic,)):
        return value.item()
    return value


class ServingClient:
    """Blocking Predict/ModelInfo calls to one serving replica."""

    def __init__(self, address: str):
        self.address = address
        self._rpc = JsonRpcClient(
            address, SERVING_SERVICE_NAME, schemas=SERVING_SCHEMAS
        )

    def wait_ready(self, timeout_s: float = 10.0) -> None:
        self._rpc.wait_ready(timeout_s)

    # hot-path: the caller-side request — serialize, one RPC, done
    def predict(
        self, features: Dict[str, Any], timeout_s: float = 30.0
    ) -> Dict[str, Any]:
        """``features``: {name: array-like} per the model's feature template
        (ModelInfo reports dtypes/shapes; a single example may omit the
        batch dim).  Returns {"outputs": nested lists, "model": name,
        "step": serving checkpoint step}."""
        # graftlint: allow[blocking-propagation] _jsonable's .item() is numpy-scalar unboxing, not a device read — this client is jax-free by design
        payload = {k: _jsonable(v) for k, v in features.items()}
        return self._rpc.call(
            "Predict", {"features": payload}, timeout_s=timeout_s
        )

    def predict_outputs(
        self, features: Dict[str, Any], timeout_s: float = 30.0
    ) -> np.ndarray:
        """predict() with the outputs as a numpy array (the common case)."""
        return np.asarray(self.predict(features, timeout_s)["outputs"])

    def model_info(self, timeout_s: float = 10.0) -> Dict[str, Any]:
        return self._rpc.call("ModelInfo", {}, timeout_s=timeout_s)

    def close(self) -> None:
        self._rpc.close()
