"""Typed clients for the serving tier (serving/server.ServingServer).

Deliberately jax-free and numpy-light: an online caller (a web frontend, a
bench driver) dials the prediction service with plain feature lists; the
client validates against SERVING_SCHEMAS before the wire, mirroring
JsonRpcClient's boundary contract for the master service.

Two clients:

- :class:`ServingClient` — one replica, the r10 surface.
- :class:`FleetServingClient` — a replica FLEET (serving/fleet.py):
  client-side load balancing by power-of-two-choices over shared
  per-replica inflight counts (two random replicas, route to the less
  loaded — the classic result: exponential improvement over random with
  O(1) state and no coordination), replica health from failure marking +
  the controller's readiness view via ``set_replicas``, and transient
  faults (a replica mid-retirement answering UNAVAILABLE) retried onto
  ANOTHER replica through the shared r18 backoff helper
  (``common/rpc.call_with_backoff`` — never a hand-rolled retry loop).
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

import grpc
import numpy as np

from elasticdl_tpu.common import locksan
from elasticdl_tpu.common.rpc import (
    SERVING_SCHEMAS,
    SERVING_SERVICE_NAME,
    BackoffPolicy,
    JsonRpcClient,
    call_with_backoff,
)


def _jsonable(value: Any) -> Any:
    """Feature value -> JSON-serializable nested lists (numpy arrays and
    scalars included; python lists pass through)."""
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, (np.generic,)):
        return value.item()
    return value


class ServingClient:
    """Blocking Predict/ModelInfo calls to one serving replica."""

    def __init__(self, address: str):
        self.address = address
        self._rpc = JsonRpcClient(
            address, SERVING_SERVICE_NAME, schemas=SERVING_SCHEMAS
        )

    def wait_ready(self, timeout_s: float = 10.0) -> None:
        self._rpc.wait_ready(timeout_s)

    # hot-path: the caller-side request — serialize, one RPC, done
    def predict(
        self, features: Dict[str, Any], timeout_s: float = 30.0,
        lane: str = "online",
    ) -> Dict[str, Any]:
        """``features``: {name: array-like} per the model's feature template
        (ModelInfo reports dtypes/shapes; a single example may omit the
        batch dim).  ``lane``: priority lane ("online" default, "bulk" for
        eval/backfill scoring — weighted admission, shed first).  Returns
        {"outputs": nested lists, "model": name, "step": serving
        checkpoint step}."""
        # graftlint: allow[blocking-propagation] _jsonable's .item() is numpy-scalar unboxing, not a device read — this client is jax-free by design
        payload = {k: _jsonable(v) for k, v in features.items()}
        request: Dict[str, Any] = {"features": payload}
        if lane != "online":
            # Omitted = online: pre-lane servers never see the field.
            request["lane"] = lane
        return self._rpc.call("Predict", request, timeout_s=timeout_s)

    def predict_outputs(
        self, features: Dict[str, Any], timeout_s: float = 30.0
    ) -> np.ndarray:
        """predict() with the outputs as a numpy array (the common case)."""
        return np.asarray(self.predict(features, timeout_s)["outputs"])

    def model_info(self, timeout_s: float = 10.0) -> Dict[str, Any]:
        return self._rpc.call("ModelInfo", {}, timeout_s=timeout_s)

    def close(self) -> None:
        self._rpc.close()


#: Retry shape for fleet predicts: three attempts, fast — each retry
#: RE-PICKS a replica, so the point is routing around one dead/retiring
#: replica, not waiting one out.
FLEET_RETRY_POLICY = BackoffPolicy(
    base_s=0.05, multiplier=2.0, max_s=0.5, jitter=0.2, max_attempts=3
)

#: How long a replica that just failed transiently sits out of p2c picks.
#: Short on purpose: a retiring replica disappears from ``set_replicas``
#: anyway; this only bridges the gap until the membership refresh.
SUSPECT_S = 1.0


def _is_transient_fleet_error(e: BaseException) -> bool:
    """Worth retrying ON ANOTHER REPLICA: UNAVAILABLE is a replica down or
    mid-retirement.  DEADLINE_EXCEEDED is deliberately NOT transient — the
    request may still be queued server-side, and re-sending it doubles the
    load on a fleet exactly when it is slowest.  Schema errors and sheds
    (RESOURCE_EXHAUSTED from a BatcherOverloaded) are the caller's signal,
    never retried here."""
    return (
        isinstance(e, grpc.RpcError)
        and e.code() == grpc.StatusCode.UNAVAILABLE
    )


class FleetServingClient:
    """Predict across a serving fleet: p2c load balancing + health-aware
    retries.  Thread-safe and meant to be SHARED by every caller thread —
    the inflight counts p2c compares are only meaningful when one instance
    sees the whole process's traffic."""

    def __init__(
        self,
        addresses: List[str],
        policy: BackoffPolicy = FLEET_RETRY_POLICY,
        suspect_s: float = SUSPECT_S,
        rng: Optional[random.Random] = None,
    ):
        if not addresses:
            raise ValueError("FleetServingClient needs at least one address")
        self._policy = policy
        self._suspect_s = suspect_s
        self._rng = rng or random.Random()
        self._lock = locksan.lock("FleetServingClient._lock", leaf=True)  # lock-order: leaf
        self._clients: Dict[str, ServingClient] = {}  # guarded-by: _lock
        self._inflight: Dict[str, int] = {}  # guarded-by: _lock
        self._suspect_until: Dict[str, float] = {}  # guarded-by: _lock
        #: Removed from membership but lingering until in-flight work on
        #: their channel drains — closing a grpc channel CANCELS whatever
        #: is riding it, and CANCELLED is not transient.  guarded-by: _lock
        self._retired: Dict[str, ServingClient] = {}
        self.set_replicas(addresses)

    def set_replicas(self, addresses: List[str]) -> None:
        """Refresh fleet membership (the controller's readiness view —
        ``ServingFleetController.ready_addresses``).  New replicas join the
        pick set immediately; removed ones leave it immediately but their
        channels LINGER until in-flight requests drain — an eager
        channel.close() cancels the requests still riding it (CANCELLED,
        deliberately not a transient error) and turns the controller's
        graceful drain into client-visible failures.  A lingering replica
        that rejoins (the controller un-drained a scale-down victim) is
        resurrected, warm channel and all."""
        to_close: List[ServingClient] = []
        with self._lock:
            for addr in addresses:
                if addr in self._clients:
                    continue
                revived = self._retired.pop(addr, None)
                self._clients[addr] = revived or ServingClient(addr)
                self._inflight.setdefault(addr, 0)
            for addr in list(self._clients):
                if addr not in addresses:
                    self._retired[addr] = self._clients.pop(addr)
                    self._suspect_until.pop(addr, None)
            for addr in list(self._retired):
                if self._inflight.get(addr, 0) <= 0:
                    to_close.append(self._retired.pop(addr))
                    self._inflight.pop(addr, None)
        for client in to_close:
            client.close()

    def addresses(self) -> List[str]:
        with self._lock:
            return sorted(self._clients)

    # hot-path: replica choice — two dict reads and a comparison, no RPC
    def _pick_locked(self, now: float) -> str:  # guarded-by: _lock
        candidates = [
            a for a in self._clients
            if self._suspect_until.get(a, 0.0) <= now
        ]
        if not candidates:
            # Everyone suspect (whole fleet blinked): fall back to all —
            # shedding at the client with zero attempts would turn a
            # 1-second blip into hard errors.
            candidates = list(self._clients)
        if len(candidates) == 1:
            return candidates[0]
        a, b = self._rng.sample(candidates, 2)
        return a if self._inflight.get(a, 0) <= self._inflight.get(b, 0) else b

    def predict(
        self, features: Dict[str, Any], timeout_s: float = 30.0,
        lane: str = "online",
    ) -> Dict[str, Any]:
        """p2c-routed Predict.  Transient replica failures mark the replica
        suspect and retry on a fresh pick via the shared backoff helper."""

        def attempt() -> Dict[str, Any]:
            now = time.monotonic()
            with self._lock:
                addr = self._pick_locked(now)
                client = self._clients[addr]
                self._inflight[addr] = self._inflight.get(addr, 0) + 1
            try:
                return client.predict(features, timeout_s=timeout_s, lane=lane)
            except grpc.RpcError as e:
                if _is_transient_fleet_error(e):
                    with self._lock:
                        self._suspect_until[addr] = (
                            time.monotonic() + self._suspect_s
                        )
                raise
            finally:
                retired = None
                with self._lock:
                    if addr in self._inflight:
                        self._inflight[addr] -= 1
                        if (addr in self._retired
                                and self._inflight[addr] <= 0):
                            # Last rider off a lingering channel closes it.
                            retired = self._retired.pop(addr)
                            self._inflight.pop(addr, None)
                if retired is not None:
                    retired.close()

        return call_with_backoff(
            attempt,
            service="serving.fleet",
            is_transient=_is_transient_fleet_error,
            policy=self._policy,
        )

    def predict_outputs(
        self, features: Dict[str, Any], timeout_s: float = 30.0,
        lane: str = "online",
    ) -> np.ndarray:
        return np.asarray(
            self.predict(features, timeout_s, lane=lane)["outputs"]
        )

    def inflight(self) -> Dict[str, int]:
        """Live per-replica inflight counts (tests assert p2c spreads)."""
        with self._lock:
            return dict(self._inflight)

    def close(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            clients.extend(self._retired.values())
            self._clients.clear()
            self._retired.clear()
            self._inflight.clear()
            self._suspect_until.clear()
        for client in clients:
            client.close()
