"""The online serving tier: micro-batched inference gRPC over a model-zoo
model, with hot-id embedding caching and zero-drop checkpoint hot reload.

ROADMAP item 4: everything before r10 was training-side; this server is the
"serve heavy traffic" half.  The pieces compose rather than duplicate:

- **Forward**: the trainer's own jitted predict step
  (``parallel/trainer.build_predict_step``) over a serving mesh — one
  compiled program per declared batch BUCKET (the micro-batcher pads every
  flush to a bucket shape, and jitsan budgets exactly that many variants),
  using the model's ``predict`` inference entry
  (models/spec.ModelSpec.predict) so clients get probabilities, not
  training logits.
- **Micro-batching**: serving/micro_batcher.MicroBatcher —
  deadline-or-full flush, zero-padded to the smallest ``batch_buckets``
  size that fits, priority lanes (online vs bulk, weighted admission,
  shed-bulk-first), per-request fan-back.  The r9 amortization trick
  (many small requests, one hot-path crossing) applied to inference.
- **Sparse features**: host-tier tables pull through
  serving/embedding_cache.HotIdEmbeddingCache layered in front of the PS
  host store (``ps/host_store.py`` locally, ``ps/service.py`` for a PS
  fleet) via ``Trainer.wrap_host_stores`` — hits are a dict walk, only the
  cold tail pays the RPC.
- **Hot reload**: serving/checkpoint_watcher.CheckpointWatcher polls the
  published manifest (``common/checkpoint.publish_manifest`` — atomic, so
  a half-written checkpoint is unobservable).  The restore runs on the
  watcher thread CONCURRENT with serving; the cutover is one reference
  swap under a leaf lock plus a cache invalidation.  In-flight flushes
  hold the snapshot they started with — no request is ever dropped or
  drained for a reload (tools/serving_bench.py measures the swap at
  microseconds and stamps it).

Wire contract: JSON-over-gRPC like the master (``common/rpc.py``
SERVING_SCHEMAS — Predict / ModelInfo).  Online requests are a handful of
examples, so JSON beats dragging the PS binary-frame codec in; bulk
offline scoring belongs to predict-mode training jobs, not this tier.
"""

from __future__ import annotations

import time
from concurrent import futures
from typing import Any, Dict, Optional, Sequence, Tuple

import grpc
import numpy as np

from elasticdl_tpu.common import gauge as gaugelib
from elasticdl_tpu.common import locksan
from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.rpc import (
    SERVING_SCHEMAS,
    SERVING_SERVICE_NAME,
    SchemaError,
    make_generic_handler,
)
from elasticdl_tpu.serving.checkpoint_watcher import CheckpointWatcher
from elasticdl_tpu.serving.embedding_cache import HotIdEmbeddingCache
from elasticdl_tpu.serving.micro_batcher import (
    DEFAULT_LANE,
    LANES,
    MASK_KEY,
    MicroBatcher,
)

logger = get_logger("serving.server")

#: Feature keys of the model's example batch that are NOT client features.
_NON_FEATURE_KEYS = ("labels", MASK_KEY)


def _listify(outputs: Any) -> Any:
    """Flush outputs -> JSON-ready nested lists, leaf-wise for dict-shaped
    model outputs (the shapes micro_batcher._slice_outputs fans back)."""
    if isinstance(outputs, dict):
        return {k: _listify(v) for k, v in outputs.items()}
    return np.asarray(outputs).tolist()


class _LiveModel:
    """One immutable serving snapshot: the unit the hot reload swaps.
    Requests in flight keep the instance they were handed — the swap can
    never tear a half-old/half-new forward."""

    __slots__ = ("step", "state")

    def __init__(self, step: int, state: Any):
        self.step = step
        self.state = state


class ServingServer:
    """Micro-batched prediction service over one model-zoo model.

    ``checkpoint_dir``: a training job's checkpoint directory.  The newest
    PUBLISHED step loads at startup (fresh-initialized weights otherwise —
    logged loudly, legitimate for smoke tests) and the watcher hot-reloads
    every subsequent publish.  ``ps_addresses``: host-tier tables pull from
    that PS fleet (the live online store); empty = in-process host store.
    """

    def __init__(
        self,
        spec: Any,
        checkpoint_dir: str = "",
        ps_addresses: str = "",
        max_batch: int = 64,
        max_delay_ms: float = 5.0,
        cache_rows: int = 1 << 20,
        poll_interval_s: float = 0.5,
        port: int = 0,
        max_workers: int = 16,
        seed: int = 0,
        gauges: Optional[gaugelib.Registry] = None,
        gauge_port: int = -1,
        target_p99_ms: float = 100.0,
        batch_buckets: Optional[Sequence[int]] = None,
        bulk_weight: float = 0.25,
        max_queue_rows: Optional[int] = None,
    ):
        import jax

        from elasticdl_tpu.parallel.mesh import create_mesh
        from elasticdl_tpu.parallel.trainer import Trainer

        self.spec = spec
        self.max_batch = max_batch
        self.max_delay_ms = max_delay_ms
        config = JobConfig(
            job_type="prediction",
            ps_addresses=ps_addresses,
            checkpoint_dir=checkpoint_dir,
            distribution_strategy=(
                DistributionStrategy.PARAMETER_SERVER
                if spec.embedding_tables
                else DistributionStrategy.ALLREDUCE
            ),
        )
        # One-device serving replica: an online replica scales by running
        # MORE replicas behind a load balancer, not by sharding one
        # request's forward over a mesh (batch 64 cannot feed 8 chips).
        # Mesh-sharded-table models still restore fine: the padded table
        # shapes are mesh-size-invariant (trainer.pad_embedding_tables).
        self.trainer = Trainer(spec, config, create_mesh([jax.devices()[0]]))
        # jitsan (v6, bucketed r19): the padded-shape buckets this replica
        # serves.  Each flush zero-pads to the smallest bucket that holds
        # its real rows (micro_batcher), the jitted predict step retraces
        # once per bucket, and the declared budget IS the bucket count — so
        # an accidental extra compile (a shape leaking past the batcher's
        # padding) still fails loud, while intended buckets never trip the
        # retrace sanitizer.
        self._shape_buckets = tuple(
            sorted(set(int(b) for b in (batch_buckets or ())) | {max_batch})
        )
        self.trainer.jit_budgets["predict_step"] = len(self._shape_buckets)
        # Hot-id cache in front of every host-tier store (no-op for models
        # without host tables).
        self._caches: Dict[str, HotIdEmbeddingCache] = {}

        def _wrap(key, store):
            cache = HotIdEmbeddingCache(store, capacity=cache_rows, name=key)
            self._caches[key] = cache
            return cache

        self.trainer.wrap_host_stores(_wrap)

        # The restore template: a freshly initialized state carries the
        # exact tree structure/shapes/shardings every checkpoint of this
        # model has — and doubles as the fresh-serve state when no
        # checkpoint exists yet.
        self._template = self.trainer.init_state(jax.random.key(seed))
        self._ckpt = None
        self._state_lock = locksan.lock("ServingServer._state_lock", leaf=True)  # lock-order: leaf
        self._live = _LiveModel(-1, self._template)  # guarded-by: _state_lock
        self._reloads = 0  # guarded-by: _state_lock
        self._last_swap_ms = 0.0  # guarded-by: _state_lock
        self._last_load_s = 0.0  # guarded-by: _state_lock
        self._requests = 0  # guarded-by: _state_lock
        self._watcher: Optional[CheckpointWatcher] = None
        if checkpoint_dir:
            from elasticdl_tpu.common.checkpoint import (
                CheckpointManager,
                read_manifest,
            )

            self._ckpt = CheckpointManager(checkpoint_dir)
            manifest = read_manifest(checkpoint_dir)
            if manifest is not None:
                self._reload(int(manifest["step"]), manifest)
            else:
                # Pre-manifest checkpoints (or none at all): fall back to
                # Orbax's newest step once, loudly.  The watcher still keys
                # strictly off the manifest from here on.
                step = self._ckpt.latest_step()
                if step is not None:
                    logger.warning(
                        "no published manifest under %s; serving Orbax "
                        "latest step %d (publish manifests for atomic "
                        "reload)", checkpoint_dir, step,
                    )
                    self._reload(int(step), {})
                else:
                    logger.warning(
                        "no checkpoint under %s: serving FRESHLY "
                        "INITIALIZED weights", checkpoint_dir,
                    )
            with self._state_lock:
                loaded = self._live.step
            self._watcher = CheckpointWatcher(
                checkpoint_dir, self._reload, poll_interval_s, name=spec.name,
                initial_step=None if loaded < 0 else loaded,
            )
        else:
            logger.warning(
                "serving without --checkpoint_dir: fresh weights, no hot "
                "reload (smoke/bench mode)"
            )

        # Client-facing feature template (dtype/shape contract, ModelInfo).
        example = spec.example_batch(max_batch) if spec.example_batch else None
        if example is None:
            raise ValueError(
                f"model {spec.name!r} declares no example_batch; the serving "
                "tier needs it for the feature template"
            )
        self._features = {
            k: np.asarray(v)
            for k, v in example.items()
            if k not in _NON_FEATURE_KEYS
        }
        self._batcher = MicroBatcher(
            self._run_batch,
            self._features,
            max_batch=max_batch,
            max_delay_ms=max_delay_ms,
            name=spec.name,
            batch_buckets=self._shape_buckets,
            bulk_weight=bulk_weight,
            # The batcher's bounded queue must be THE queue: size the gRPC
            # handler pool (max_workers) at or above the expected in-flight
            # request count, or excess load parks invisibly in the
            # executor — unmeasured by the latency histogram and unshed by
            # the admission bounds, which blinds the fleet autoscaler's
            # two pressure signals.
            max_queue_rows=max_queue_rows,
        )

        # graftgauge (r14): the replica's live metrics — request counter +
        # per-request latency histogram updated on the # hot-path handler
        # (O(1): gauge-discipline), everything else (batcher fill/shed,
        # cache hit rate, reload counter, the p99-vs-target SLO ratio)
        # collected from the existing stats() surfaces at scrape time.
        # ``target_p99_ms`` is the operator's SLO line: the endpoint serves
        # the live p99/target ratio so a blowout reads as a number > 1.0.
        self.target_p99_ms = float(target_p99_ms)
        self.gauges = gauges if gauges is not None else gaugelib.default()
        self._g_requests = self.gauges.counter(
            "edl_serving_requests_total", "Predict requests answered"
        )
        # Per-lane latency histograms: the SLO (p99 / slo_ratio gauges, and
        # the fleet autoscaler's windowed-p99 signal) is defined over the
        # ONLINE lane only — bulk latency is throughput traffic and must
        # not pollute the knee signal that adds replicas.
        self._g_request_ms = {
            lane: self.gauges.histogram(
                "edl_serving_request_ms",
                "per-request wall inside the Predict handler (parse + "
                "queue + flush + fan-back), by priority lane",
                labels={"lane": lane},
            )
            for lane in LANES
        }
        self._g_lane_requests = {
            lane: self.gauges.counter(
                "edl_serving_lane_requests_total",
                "Predict requests answered, by priority lane",
                labels={"lane": lane},
            )
            for lane in LANES
        }
        self.gauges.add_collector(self._collect_gauges)
        self._gauge_port = gauge_port
        self._metrics_server = None

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers))
        self._server.add_generic_rpc_handlers(
            (
                make_generic_handler(
                    SERVING_SERVICE_NAME,
                    {"Predict": self._predict, "ModelInfo": self._model_info},
                    SERVING_SCHEMAS,
                ),
            )
        )
        self.port = self._server.add_insecure_port(f"[::]:{port}")
        # Same loud-bind contract as PSServer: an advertised port that
        # silently rebinds serves nothing while looking healthy.
        if self.port == 0 or (port and self.port != port):
            raise RuntimeError(
                f"serving server failed to bind port {port} (got {self.port})"
            )

    # ---- model lifecycle ----

    def warmup(self) -> float:
        """Compile the forward at EVERY serving batch bucket (one padded
        zero batch per bucket through the real path) so the first request
        of any bucket pays RPC + forward, not RPC + XLA compile — and so
        the full jitsan variant budget is spent here, loudly, rather than
        one retrace at a time under live traffic.  Returns the total
        warmup wall seconds."""
        t0 = time.perf_counter()
        for bucket in self._shape_buckets:
            batch = {
                k: np.zeros((bucket,) + t.shape[1:], t.dtype)
                for k, t in self._batcher._template.items()
            }
            batch[MASK_KEY] = np.zeros((bucket,), np.float32)
            self._run_batch(batch, 0)
        return time.perf_counter() - t0

    def _reload(self, step: int, manifest: Dict[str, Any]) -> None:
        """Load checkpoint ``step`` and swap it live (the watcher callback).

        The expensive half — Orbax read + device placement — happens on the
        CALLING thread against a private state object while serving
        continues on the old snapshot.  The live path is touched only by
        the reference swap + cache invalidation at the end (microseconds,
        stamped in ModelInfo as ``last_swap_ms``)."""
        t0 = time.perf_counter()
        state = self._ckpt.restore(self._template, step=step)
        load_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        with self._state_lock:
            self._live = _LiveModel(step, state)
        # AFTER the swap: a pull that lands between swap and invalidate
        # caches NEW-era rows, which are valid; rows cached before the
        # swap are dropped here, and in-flight fetches from the old
        # generation are insert-blocked by the generation guard.
        for cache in self._caches.values():
            cache.invalidate()
        swap_ms = (time.perf_counter() - t1) * 1e3
        with self._state_lock:
            self._reloads += 1
            self._last_swap_ms = swap_ms
            self._last_load_s = load_s
        logger.info(
            "serving step %d live (load %.2fs off-path, swap %.3fms)",
            step, load_s, swap_ms,
        )

    # ---- request path ----

    def _parse_features(self, features: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Client JSON -> typed numpy per the model template.  Violations
        raise SchemaError: the handler surfaces them as structured
        FAILED_PRECONDITION at the boundary, never a KeyError mid-flush."""
        out: Dict[str, np.ndarray] = {}
        n = None
        for key, tmpl in self._features.items():
            if key not in features:
                raise SchemaError(
                    f"Predict: missing feature {key!r} "
                    f"(model {self.spec.name} expects {sorted(self._features)})"
                )
            try:
                arr = np.asarray(features[key], dtype=tmpl.dtype)
            except (TypeError, ValueError) as e:
                raise SchemaError(
                    f"Predict: feature {key!r} not convertible to "
                    f"{tmpl.dtype}: {e}"
                ) from e
            if arr.ndim == tmpl.ndim - 1:
                arr = arr[None]  # single example without the batch dim
            if arr.ndim != tmpl.ndim or arr.shape[1:] != tmpl.shape[1:]:
                raise SchemaError(
                    f"Predict: feature {key!r} has shape {arr.shape}, "
                    f"expected [n{''.join(f', {d}' for d in tmpl.shape[1:])}]"
                )
            if n is None:
                n = arr.shape[0]
            elif arr.shape[0] != n:
                raise SchemaError(
                    f"Predict: feature {key!r} carries {arr.shape[0]} "
                    f"examples but earlier features carry {n}"
                )
            out[key] = arr
        if not 1 <= (n or 0) <= self.max_batch:
            raise SchemaError(
                f"Predict: {n} examples; must be 1..{self.max_batch}"
            )
        return out

    # hot-path: the per-request gRPC handler — parse, enqueue, park on the
    # flush fan-back; never a device touch (the flusher owns the forward)
    def _predict(self, req: Dict[str, Any]) -> Dict[str, Any]:
        t0 = time.perf_counter()
        lane = req.get("lane", DEFAULT_LANE)
        if lane not in LANES:
            raise SchemaError(
                f"Predict: unknown priority lane {lane!r}; expected one "
                f"of {list(LANES)}"
            )
        features = self._parse_features(req["features"])
        handle = self._batcher.submit(features, lane=lane)
        outputs, meta = handle.result(timeout_s=30.0)
        with self._state_lock:
            self._requests += 1
        self._g_requests.inc()
        self._g_lane_requests[lane].inc()
        self._g_request_ms[lane].observe((time.perf_counter() - t0) * 1e3)
        return {
            "outputs": _listify(outputs),
            "model": self.spec.name,
            "step": meta.get("step", -1),
        }

    def _run_batch(self, batch: Dict[str, np.ndarray], n_real: int) -> Tuple[Any, Dict]:
        """The flusher's runner: ONE jitted forward of the padded batch on
        the serving snapshot current at flush time.  Holding the snapshot
        as a local is the zero-drop reload mechanism: a concurrent swap
        retargets the NEXT flush, never this one."""
        with self._state_lock:
            live = self._live
        import jax

        out = self.trainer.run_predict_step(live.state, batch)
        return jax.device_get(out), {"step": live.step}

    def _collect_gauges(self) -> None:
        """Scrape-time collector (gauge-discipline: never the request
        path): batcher/cache/reload state re-published from the stats()
        surfaces, plus the goodput/SLO gauges — live p99 estimated from
        the request histogram on the shared bucket grid, served beside the
        operator's target as a ratio (> 1.0 = the SLO is blown NOW)."""
        g = self.gauges
        stats = self._batcher.stats()
        g.gauge("edl_serving_queue_depth", "requests parked in the "
                "micro-batcher").set(float(stats["queued"]))
        g.gauge("edl_serving_shed_overload", "requests shed at the "
                "queue-row bound").set(float(stats["shed_overload"]))
        g.gauge("edl_serving_expired", "requests expired at flush time"
                ).set(float(stats["expired"]))
        # Per-lane shed/expiry attribution (r19 satellite): the autoscaler
        # and the SLO dashboard must tell bulk shed (by design under the
        # shed-bulk-first policy) from online shed (a capacity red alert).
        for lane, ls in stats["lanes"].items():
            g.counter(
                "edl_serving_shed_total",
                "requests shed at admission or evicted, by priority lane",
                labels={"lane": lane},
            ).set_total(float(ls["shed"]))
            g.counter(
                "edl_serving_expired_total",
                "requests expired at flush time, by priority lane",
                labels={"lane": lane},
            ).set_total(float(ls["expired"]))
            g.gauge(
                "edl_serving_lane_queued_rows",
                "rows parked in the micro-batcher, by priority lane",
                labels={"lane": lane},
            ).set(float(ls["queued_rows"]))
        for bucket, n in stats["flushes_by_bucket"].items():
            g.counter(
                "edl_serving_bucket_flushes_total",
                "flushes per padded batch bucket (bucketed compiles)",
                labels={"bucket": bucket},
            ).set_total(float(n))
        served = stats["rows_served"]
        g.gauge(
            "edl_serving_batch_fill_ratio",
            "real rows / flushed rows (padding waste is 1 - this)",
        ).set(served / (served + stats["rows_padded"])
              if served + stats["rows_padded"] else 0.0)
        for key, cache in self._caches.items():
            cs = cache.stats()
            hits, misses = cs["hits"], cs["misses"]
            g.gauge(
                "edl_serving_cache_hit_ratio",
                "hot-id embedding cache hit rate",
                labels={"table": key},
            ).set(hits / (hits + misses) if hits + misses else 0.0)
            g.gauge(
                "edl_serving_cache_rows", "cached rows",
                labels={"table": key},
            ).set(float(cs["size"]))
        with self._state_lock:
            step, reloads = self._live.step, self._reloads
        g.gauge("edl_serving_step", "live model step").set(float(step))
        g.gauge("edl_serving_reloads", "hot reloads performed").set(
            float(reloads)
        )
        # The SLO gauges track the ONLINE lane: bulk is throughput traffic
        # whose latency is not what the autoscaler protects.
        p99 = self._g_request_ms["online"].quantile(0.99)
        if p99 is not None:
            g.gauge(
                "edl_serving_p99_ms",
                "live online-lane request p99 (bucket-grid estimate)",
            ).set(p99)
            g.gauge(
                "edl_serving_p99_target_ms", "operator SLO target"
            ).set(self.target_p99_ms)
            g.gauge(
                "edl_serving_slo_ratio",
                "live p99 over the target — > 1.0 means the SLO is "
                "blown right now",
            ).set(p99 / self.target_p99_ms if self.target_p99_ms else 0.0)

    def _model_info(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._state_lock:
            step = self._live.step
            reloads = self._reloads
            last_swap_ms = self._last_swap_ms
            last_load_s = self._last_load_s
            requests = self._requests
        return {
            "model": self.spec.name,
            "step": step,
            "max_batch": self.max_batch,
            "max_delay_ms": self.max_delay_ms,
            "batch_buckets": list(self._shape_buckets),
            "features": {
                k: {"dtype": str(v.dtype), "example_shape": list(v.shape[1:])}
                for k, v in self._features.items()
            },
            "requests": requests,
            "reloads": reloads,
            "last_swap_ms": round(last_swap_ms, 3),
            "last_load_s": round(last_load_s, 3),
            "batcher": self._batcher.stats(),
            "cache": {k: c.stats() for k, c in self._caches.items()},
        }

    # ---- lifecycle ----

    @property
    def address(self) -> str:
        return f"localhost:{self.port}"

    @property
    def metrics_address(self) -> Optional[str]:
        """host:port of the live /metrics endpoint (after start(); None
        when gauge_port < 0 or the bind failed)."""
        return (
            self._metrics_server.address
            if self._metrics_server is not None else None
        )

    def start(self) -> "ServingServer":
        self._server.start()
        if self._watcher is not None:
            self._watcher.start()
        # The scrape endpoint runs its own daemon threads — a replica
        # wedged past its knee must still answer /metrics (the whole
        # point of serving the SLO ratio live).
        from elasticdl_tpu.common.metrics_http import maybe_start

        self._metrics_server = maybe_start(
            self._gauge_port,
            self.gauges.render_prometheus,
            health_fn=lambda: {"role": "serving", "model": self.spec.name},
            registry=self.gauges,
        )
        logger.info(
            "serving %s on port %d (max_batch %d, deadline %.1fms)",
            self.spec.name, self.port, self.max_batch, self.max_delay_ms,
        )
        return self

    def wait(self) -> None:
        self._server.wait_for_termination()

    def stop(self, grace: float = 1.0) -> None:
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        # Unhook from the (possibly process-shared) registry: a stopped
        # replica must neither keep publishing its frozen stats nor be
        # pinned in memory by the registry's collector reference.
        self.gauges.remove_collector(self._collect_gauges)
        if self._watcher is not None:
            self._watcher.stop()
        # grpc's stop() is non-blocking (it returns an Event); WAIT the
        # grace window out before closing the batcher, or a handler that
        # was admitted pre-stop would hit BatcherClosed at submit() and
        # fail a request the grace period promised to finish.
        self._server.stop(grace).wait(grace + 5.0)
        self._batcher.close()
