"""Micro-batching for the online serving tier.

Concurrent single-example ``Predict`` RPCs are individually far too small to
feed a jitted forward efficiently — but the r9 lease work proved the repo's
amortization move: batch many small requests into ONE hot-path crossing.
This module is that move for inference.  gRPC handler threads ``submit()``
their examples; a flusher thread coalesces them into ONE fixed-shape padded
batch and runs the jitted forward once, then fans each request's slice of
the outputs back to its waiting handler.

Flush policy — deadline-or-full:

- **full**: queued examples fill ``max_batch`` (or the next request would
  overflow it) -> flush immediately; under load the batcher converges to
  back-to-back full batches and per-request latency ~= one forward.
- **deadline**: the OLDEST queued request has waited ``max_delay_ms`` ->
  flush whatever is queued; under light load a lone request pays at most
  the deadline plus one forward, never an unbounded wait for company.

Every flush pads to exactly ``max_batch`` rows (zero rows, ``__mask__``
marking the real ones) so the jitted forward compiles ONCE — a varying
batch dimension would recompile per distinct size, and XLA compiles are
milliseconds-to-seconds, i.e. death on a latency SLO.

The runner executes in the flusher thread and is HANDED the current model
snapshot by the server (serving/server.py) — requests in flight during a
hot reload keep the weights they started with; the swap is a reference
assignment, never a drain.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from elasticdl_tpu.common import locksan, trace
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving.micro_batcher")

MASK_KEY = "__mask__"


class BatcherClosed(RuntimeError):
    """submit() after close(): the server is shutting down."""


class BatcherOverloaded(RuntimeError):
    """submit() with the queue at its row bound: the replica is past its
    knee — shed THIS request now (the caller sees a fast structured error)
    instead of queueing it into a wait it cannot survive."""


class PredictionHandle:
    """One request's slot in a future flush: the handler thread parks on
    ``result()`` until the flusher fans the outputs back."""

    __slots__ = ("count", "features", "arrival", "_event", "_outputs",
                 "_meta", "_error")

    def __init__(self, count: int, features: Dict[str, np.ndarray],
                 arrival: float):
        self.count = count
        self.features = features
        self.arrival = arrival
        self._event = threading.Event()
        self._outputs: Any = None
        self._meta: Dict[str, Any] = {}
        self._error: Optional[BaseException] = None

    def _resolve(self, outputs: Any, meta: Dict[str, Any]) -> None:
        self._outputs = outputs
        self._meta = meta
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def result(self, timeout_s: float = 30.0) -> Tuple[Any, Dict[str, Any]]:
        """(outputs sliced to this request's rows, flush metadata).  Raises
        the runner's error, or TimeoutError when no flush resolved us."""
        if not self._event.wait(timeout_s):
            raise TimeoutError(
                f"prediction not served within {timeout_s}s "
                "(flusher wedged or overloaded)"
            )
        if self._error is not None:
            raise self._error
        return self._outputs, self._meta


def _slice_outputs(outputs: Any, lo: int, hi: int) -> Any:
    """Per-request view of the flush outputs: arrays slice on the leading
    (example) dim; dicts slice leaf-wise — covers every model-zoo output
    shape without a jax dependency."""
    if isinstance(outputs, dict):
        return {k: _slice_outputs(v, lo, hi) for k, v in outputs.items()}
    return np.asarray(outputs)[lo:hi]


class MicroBatcher:
    """Deadline-or-full request coalescing in front of a batch runner.

    ``runner(batch, n_real) -> (outputs, meta)``: ``batch`` is a dict of
    numpy arrays padded to ``max_batch`` rows (plus ``__mask__`` f32
    [max_batch], 1.0 on real rows); outputs must keep the leading example
    dim; ``meta`` is attached to every request of the flush (the server
    stamps the serving model step).  Runs on the flusher thread — blocking
    there is the design (it IS the accounted inference), which is why the
    runner is not a ``# hot-path`` function but ``submit`` is.
    """

    def __init__(
        self,
        runner: Callable[[Dict[str, np.ndarray], int], Tuple[Any, Dict]],
        template: Dict[str, np.ndarray],
        max_batch: int = 64,
        max_delay_ms: float = 5.0,
        name: str = "serving",
        max_queue_rows: Optional[int] = None,
        drop_after_s: float = 30.0,
    ):
        """Overload policy (sustained load past the replica's knee):

        - ``max_queue_rows`` (default 32 * max_batch): submit() sheds with
          :class:`BatcherOverloaded` once the queue holds this many rows —
          a fast structured error beats queueing into a wait the request
          cannot survive, and it bounds queue memory.
        - ``drop_after_s`` (default 30.0, matching ``PredictionHandle.
          result``'s timeout): a queued request older than this at flush
          time fails with TimeoutError instead of occupying flush slots —
          its handler already gave up, and running a padded forward for
          nobody would deepen the very backlog that expired it.
        """
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._runner = runner
        # Per-feature zero rows at the padded batch shape: built once, so a
        # flush only copies request rows in (no per-flush allocation of the
        # template itself — padded buffers are fresh per flush, the model
        # may donate them).
        self._template = {
            k: np.zeros((max_batch,) + tuple(np.asarray(v).shape[1:]),
                        np.asarray(v).dtype)
            for k, v in template.items()
        }
        self.max_batch = max_batch
        self.max_delay_s = max_delay_ms / 1e3
        self.max_queue_rows = (
            max_queue_rows if max_queue_rows is not None else 32 * max_batch
        )
        self.drop_after_s = drop_after_s
        self._lock = locksan.lock("MicroBatcher._lock", leaf=True)  # lock-order: leaf
        self._cond = threading.Condition(self._lock)
        self._queue: List[PredictionHandle] = []  # guarded-by: _cond
        self._queued_rows = 0  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        # Counters (stats()): mutated only under the condition lock.
        self._submitted = 0  # guarded-by: _cond
        self._flushes_full = 0  # guarded-by: _cond
        self._flushes_deadline = 0  # guarded-by: _cond
        self._flushes_close = 0  # guarded-by: _cond
        self._rows_served = 0  # guarded-by: _cond
        self._rows_padded = 0  # guarded-by: _cond
        self._shed = 0  # guarded-by: _cond
        self._expired = 0  # guarded-by: _cond
        self._thread = threading.Thread(
            target=self._flush_loop, name=f"edl-serve-flush:{name}", daemon=True
        )
        self._thread.start()

    # -- request side --

    # hot-path: the per-request enqueue on the serving critical path — one
    # lock hand-off and a notify, never a device touch or an RPC
    def submit(self, features: Dict[str, np.ndarray]) -> PredictionHandle:
        """Queue ``features`` (dict of [n, ...] arrays covering the template
        keys, consistent leading dim 1 <= n <= max_batch) for the next
        flush.  Validation is exhaustive HERE, in the offender's own stack
        frame: a malformed request that only failed during batch assembly
        would fan its error to every innocent request co-batched with it."""
        missing = [k for k in self._template if k not in features]
        if missing:
            raise ValueError(f"request missing feature(s) {missing}")
        arrays: Dict[str, np.ndarray] = {}
        n = None
        for k, tmpl in self._template.items():
            arr = np.asarray(features[k], tmpl.dtype)
            if arr.shape[1:] != tmpl.shape[1:]:
                raise ValueError(
                    f"feature {k!r} has shape {arr.shape}, expected "
                    f"[n, ...] with trailing dims {tmpl.shape[1:]}"
                )
            if n is None:
                n = arr.shape[0] if arr.ndim else 0
            elif arr.shape[0] != n:
                raise ValueError(
                    f"feature {k!r} carries {arr.shape[0]} examples, "
                    f"earlier features carry {n}"
                )
            arrays[k] = arr
        if not 1 <= (n or 0) <= self.max_batch:
            raise ValueError(
                f"request carries {n} examples; must be 1..{self.max_batch} "
                "(split larger requests client-side)"
            )
        handle = PredictionHandle(n, arrays, time.monotonic())
        with self._cond:
            if self._closed:
                raise BatcherClosed("micro-batcher is closed")
            if self._queued_rows + n > self.max_queue_rows:
                self._shed += 1
                raise BatcherOverloaded(
                    f"queue holds {self._queued_rows} rows (bound "
                    f"{self.max_queue_rows}); shedding — the replica is "
                    "past its knee, add replicas or lower the offered load"
                )
            self._queue.append(handle)
            self._queued_rows += n
            self._submitted += 1
            self._cond.notify()
        return handle

    # -- flusher side --

    def _take_locked(self) -> Tuple[List[PredictionHandle], str]:  # guarded-by: _cond
        """(requests to flush now, reason) or ([], "") to keep waiting.
        Whole requests only — a request never splits across flushes, so its
        outputs fan back from exactly one runner call."""
        # Shed expired requests (queued longer than drop_after_s — their
        # handlers have already timed out): running a forward for nobody
        # would deepen the backlog that expired them.  Arrival-ordered, so
        # the expired set is a prefix.
        now = time.monotonic()
        while self._queue and now - self._queue[0].arrival > self.drop_after_s:
            h = self._queue.pop(0)
            self._queued_rows -= h.count
            self._expired += 1
            h._fail(TimeoutError(
                f"request expired after {self.drop_after_s}s in the serving "
                "queue (replica overloaded)"
            ))
        if not self._queue:
            return [], ""
        take: List[PredictionHandle] = []
        rows = 0
        overflow = False
        for h in self._queue:
            if rows + h.count > self.max_batch:
                overflow = True
                break
            take.append(h)
            rows += h.count
        if rows == self.max_batch or overflow:
            return take, "full"
        if self._closed:
            return take, "close"
        oldest = self._queue[0].arrival
        if time.monotonic() - oldest >= self.max_delay_s:
            return take, "deadline"
        return [], ""

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                take, reason = self._take_locked()
                while not take:
                    if self._closed and not self._queue:
                        return
                    if self._queue:
                        # Sleep exactly to the oldest request's deadline.
                        remaining = (
                            self._queue[0].arrival + self.max_delay_s
                            - time.monotonic()
                        )
                        self._cond.wait(max(remaining, 0.0))
                    else:
                        self._cond.wait()
                    take, reason = self._take_locked()
                del self._queue[: len(take)]
                n_real = sum(h.count for h in take)
                self._queued_rows -= n_real
                if reason == "full":
                    self._flushes_full += 1
                elif reason == "deadline":
                    self._flushes_deadline += 1
                else:
                    self._flushes_close += 1
                self._rows_served += n_real
                self._rows_padded += self.max_batch - n_real
            self._run_flush(take, n_real)

    def _run_flush(self, take: List[PredictionHandle], n_real: int) -> None:
        """Assemble the padded batch, run it, fan outputs back.  Runner
        failures resolve every request of THIS flush with the error and the
        flusher survives — one poisoned batch must not wedge the server."""
        try:
            # The flush span IS the serving tier's unit of work: request
            # count + real/padded rows beside its wall make batching
            # efficiency (and the padding tax) visible in the merged trace.
            with trace.span(
                "serving:flush", cat="serving", n_requests=len(take),
                n_real=n_real, n_padded=self.max_batch - n_real,
            ):
                batch = {k: t.copy() for k, t in self._template.items()}
                mask = np.zeros((self.max_batch,), np.float32)
                mask[:n_real] = 1.0
                batch[MASK_KEY] = mask
                lo = 0
                for h in take:
                    for k in self._template:
                        arr = np.asarray(
                            h.features[k], self._template[k].dtype
                        )
                        batch[k][lo : lo + h.count] = arr
                    lo += h.count
                outputs, meta = self._runner(batch, n_real)
            lo = 0
            for h in take:
                h._resolve(_slice_outputs(outputs, lo, lo + h.count), meta)
                lo += h.count
        except BaseException as e:  # noqa: BLE001 — fan the failure back
            logger.exception("micro-batch flush of %d request(s) failed", len(take))
            for h in take:
                h._fail(e)

    # -- lifecycle / observability --

    def stats(self) -> Dict[str, int]:
        with self._cond:
            return {
                "submitted": self._submitted,
                "queued": len(self._queue),
                "flushes_full": self._flushes_full,
                "flushes_deadline": self._flushes_deadline,
                "flushes_close": self._flushes_close,
                "rows_served": self._rows_served,
                "rows_padded": self._rows_padded,
                "shed_overload": self._shed,
                "expired": self._expired,
            }

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting requests, flush what is queued, join the flusher."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout_s)
