"""Micro-batching for the online serving tier.

Concurrent single-example ``Predict`` RPCs are individually far too small to
feed a jitted forward efficiently — but the r9 lease work proved the repo's
amortization move: batch many small requests into ONE hot-path crossing.
This module is that move for inference.  gRPC handler threads ``submit()``
their examples; a flusher thread coalesces them into ONE fixed-shape padded
batch and runs the jitted forward once, then fans each request's slice of
the outputs back to its waiting handler.

Flush policy — deadline-or-full:

- **full**: queued examples fill ``max_batch`` (or the next request would
  overflow it) -> flush immediately; under load the batcher converges to
  back-to-back full batches and per-request latency ~= one forward.
- **deadline**: the OLDEST queued request has waited ``max_delay_ms`` ->
  flush whatever is queued; under light load a lone request pays at most
  the deadline plus one forward, never an unbounded wait for company.

Padding is BUCKETED (r19): each flush zero-pads to the smallest declared
``batch_buckets`` size that holds its real rows (``__mask__`` marking the
real ones), so the jitted forward compiles once PER BUCKET — a bounded,
budget-declared set of shapes (serving/server.py registers the bucket count
as the jitsan ``expected_variants`` budget) instead of either extreme:
padding every deadline flush to ``max_batch`` (SERVE_r10 measured 94% of
flushed rows as padding) or recompiling per arbitrary batch size (XLA
compiles are milliseconds-to-seconds, i.e. death on a latency SLO).

Requests ride in PRIORITY LANES (r19): ``online`` (the latency-SLO traffic)
and ``bulk`` (eval scoring, backfills).  Admission is weighted — a flush
takes online requests first and reserves at most a ``bulk_weight`` fraction
of the batch for bulk when both lanes are queued, so bulk saturation cannot
starve online p99s while bulk still drains at a guaranteed trickle.
Overload sheds bulk FIRST: the bulk lane's queue share is bounded at
``bulk_queue_frac`` of the row bound, and an online submit that finds the
queue full evicts the newest queued bulk requests before it would ever shed
itself.  Every shed/expiry is attributed to its lane in ``stats()``.

The runner executes in the flusher thread and is HANDED the current model
snapshot by the server (serving/server.py) — requests in flight during a
hot reload keep the weights they started with; the swap is a reference
assignment, never a drain.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticdl_tpu.common import locksan, trace
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.rpc import RpcOverloaded

logger = get_logger("serving.micro_batcher")

MASK_KEY = "__mask__"

#: Priority lanes, highest priority first.  ``online`` is the latency-SLO
#: lane; ``bulk`` is throughput traffic that is admitted at a bounded
#: weight and shed first under overload.
LANES = ("online", "bulk")
DEFAULT_LANE = "online"


class BatcherClosed(RuntimeError):
    """submit() after close(): the server is shutting down."""


class BatcherOverloaded(RpcOverloaded):
    """submit() with the queue at its row bound: the replica is past its
    knee — shed THIS request now (the caller sees a fast structured
    RESOURCE_EXHAUSTED, via the RpcOverloaded mapping at the generic
    handler) instead of queueing it into a wait it cannot survive."""


class PredictionHandle:
    """One request's slot in a future flush: the handler thread parks on
    ``result()`` until the flusher fans the outputs back."""

    __slots__ = ("count", "features", "arrival", "lane", "_event",
                 "_outputs", "_meta", "_error")

    def __init__(self, count: int, features: Dict[str, np.ndarray],
                 arrival: float, lane: str = DEFAULT_LANE):
        self.count = count
        self.features = features
        self.arrival = arrival
        self.lane = lane
        self._event = threading.Event()
        self._outputs: Any = None
        self._meta: Dict[str, Any] = {}
        self._error: Optional[BaseException] = None

    def _resolve(self, outputs: Any, meta: Dict[str, Any]) -> None:
        self._outputs = outputs
        self._meta = meta
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def result(self, timeout_s: float = 30.0) -> Tuple[Any, Dict[str, Any]]:
        """(outputs sliced to this request's rows, flush metadata).  Raises
        the runner's error, or TimeoutError when no flush resolved us."""
        if not self._event.wait(timeout_s):
            raise TimeoutError(
                f"prediction not served within {timeout_s}s "
                "(flusher wedged or overloaded)"
            )
        if self._error is not None:
            raise self._error
        return self._outputs, self._meta


def _slice_outputs(outputs: Any, lo: int, hi: int) -> Any:
    """Per-request view of the flush outputs: arrays slice on the leading
    (example) dim; dicts slice leaf-wise — covers every model-zoo output
    shape without a jax dependency."""
    if isinstance(outputs, dict):
        return {k: _slice_outputs(v, lo, hi) for k, v in outputs.items()}
    return np.asarray(outputs)[lo:hi]


class _LaneState:
    """One priority lane's queue + attribution counters (guarded-by the
    batcher's _cond, like every other piece of queue state)."""

    __slots__ = ("queue", "queued_rows", "submitted", "shed", "expired",
                 "rows_served")

    def __init__(self) -> None:
        self.queue: List[PredictionHandle] = []
        self.queued_rows = 0
        self.submitted = 0
        self.shed = 0
        self.expired = 0
        self.rows_served = 0


class MicroBatcher:
    """Deadline-or-full request coalescing in front of a batch runner.

    ``runner(batch, n_real) -> (outputs, meta)``: ``batch`` is a dict of
    numpy arrays padded to one of the ``batch_buckets`` row counts (plus
    ``__mask__`` f32 [bucket], 1.0 on real rows); outputs must keep the
    leading example dim; ``meta`` is attached to every request of the flush
    (the server stamps the serving model step).  Runs on the flusher
    thread — blocking there is the design (it IS the accounted inference),
    which is why the runner is not a ``# hot-path`` function but ``submit``
    is.
    """

    def __init__(
        self,
        runner: Callable[[Dict[str, np.ndarray], int], Tuple[Any, Dict]],
        template: Dict[str, np.ndarray],
        max_batch: int = 64,
        max_delay_ms: float = 5.0,
        name: str = "serving",
        max_queue_rows: Optional[int] = None,
        drop_after_s: float = 30.0,
        batch_buckets: Optional[Sequence[int]] = None,
        bulk_weight: float = 0.25,
        bulk_queue_frac: float = 0.5,
    ):
        """Overload policy (sustained load past the replica's knee):

        - ``max_queue_rows`` (default 32 * max_batch): submit() sheds with
          :class:`BatcherOverloaded` once the queue holds this many rows —
          a fast structured error beats queueing into a wait the request
          cannot survive, and it bounds queue memory.
        - ``drop_after_s`` (default 30.0, matching ``PredictionHandle.
          result``'s timeout): a queued request older than this at flush
          time fails with TimeoutError instead of occupying flush slots —
          its handler already gave up, and running a padded forward for
          nobody would deepen the very backlog that expired it.

        Shape policy:

        - ``batch_buckets`` (default ``(max_batch,)``): the padded batch
          sizes this batcher emits.  Each flush pads to the smallest bucket
          holding its real rows; ``max_batch`` is always a bucket so a full
          flush stays legal.  The server declares ``len(batch_buckets)`` as
          the predict step's jitsan variant budget.

        Lane policy:

        - ``bulk_weight``: fraction of a flush reserved for the bulk lane
          while BOTH lanes are queued (weighted admission — bulk cannot
          starve, online keeps the rest).  0.0 = strict priority.
        - ``bulk_queue_frac``: the bulk lane's share of ``max_queue_rows``;
          bulk sheds at this bound (and at the total bound) so a bulk flood
          can never consume the queue capacity online admission relies on.
        """
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if not 0.0 <= bulk_weight < 1.0:
            raise ValueError(f"bulk_weight must be in [0, 1), got {bulk_weight}")
        if not 0.0 < bulk_queue_frac <= 1.0:
            raise ValueError(
                f"bulk_queue_frac must be in (0, 1], got {bulk_queue_frac}"
            )
        buckets = sorted(set(int(b) for b in (batch_buckets or ())) | {max_batch})
        if buckets[0] < 1 or buckets[-1] > max_batch:
            raise ValueError(
                f"batch_buckets must lie in 1..max_batch={max_batch}, "
                f"got {buckets}"
            )
        self.batch_buckets: Tuple[int, ...] = tuple(buckets)
        self._runner = runner
        # Per-feature zero rows at the padded batch shape: built once at
        # max_batch (the largest bucket); a smaller-bucket flush slices the
        # leading rows off these, so a flush only copies request rows in
        # (padded buffers are fresh per flush, the model may donate them).
        self._template = {
            k: np.zeros((max_batch,) + tuple(np.asarray(v).shape[1:]),
                        np.asarray(v).dtype)
            for k, v in template.items()
        }
        self.max_batch = max_batch
        self.max_delay_s = max_delay_ms / 1e3
        self.max_queue_rows = (
            max_queue_rows if max_queue_rows is not None else 32 * max_batch
        )
        self.bulk_weight = bulk_weight
        self.bulk_max_rows = max(1, int(self.max_queue_rows * bulk_queue_frac))
        self.drop_after_s = drop_after_s
        self._lock = locksan.lock("MicroBatcher._lock", leaf=True)  # lock-order: leaf
        self._cond = threading.Condition(self._lock)
        self._lanes: Dict[str, _LaneState] = {ln: _LaneState() for ln in LANES}  # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        # Counters (stats()): mutated only under the condition lock.
        self._flushes_full = 0  # guarded-by: _cond
        self._flushes_deadline = 0  # guarded-by: _cond
        self._flushes_close = 0  # guarded-by: _cond
        self._rows_served = 0  # guarded-by: _cond
        self._rows_padded = 0  # guarded-by: _cond
        self._flushes_by_bucket: Dict[int, int] = {b: 0 for b in self.batch_buckets}  # guarded-by: _cond
        self._thread = threading.Thread(
            target=self._flush_loop, name=f"edl-serve-flush:{name}", daemon=True
        )
        self._thread.start()

    # -- request side --

    def _queued_rows_locked(self) -> int:  # guarded-by: _cond
        return sum(ln.queued_rows for ln in self._lanes.values())

    # hot-path: the per-request enqueue on the serving critical path — one
    # lock hand-off and a notify, never a device touch or an RPC
    def submit(
        self, features: Dict[str, np.ndarray], lane: str = DEFAULT_LANE
    ) -> PredictionHandle:
        """Queue ``features`` (dict of [n, ...] arrays covering the template
        keys, consistent leading dim 1 <= n <= max_batch) on priority
        ``lane`` for a future flush.  Validation is exhaustive HERE, in the
        offender's own stack frame: a malformed request that only failed
        during batch assembly would fan its error to every innocent request
        co-batched with it."""
        if lane not in LANES:  # the lane SET is a module constant; _lanes stays behind _cond
            raise ValueError(f"unknown priority lane {lane!r}; expected {LANES}")
        missing = [k for k in self._template if k not in features]
        if missing:
            raise ValueError(f"request missing feature(s) {missing}")
        arrays: Dict[str, np.ndarray] = {}
        n = None
        for k, tmpl in self._template.items():
            arr = np.asarray(features[k], tmpl.dtype)
            if arr.shape[1:] != tmpl.shape[1:]:
                raise ValueError(
                    f"feature {k!r} has shape {arr.shape}, expected "
                    f"[n, ...] with trailing dims {tmpl.shape[1:]}"
                )
            if n is None:
                n = arr.shape[0] if arr.ndim else 0
            elif arr.shape[0] != n:
                raise ValueError(
                    f"feature {k!r} carries {arr.shape[0]} examples, "
                    f"earlier features carry {n}"
                )
            arrays[k] = arr
        if not 1 <= (n or 0) <= self.max_batch:
            raise ValueError(
                f"request carries {n} examples; must be 1..{self.max_batch} "
                "(split larger requests client-side)"
            )
        handle = PredictionHandle(n, arrays, time.monotonic(), lane)
        with self._cond:
            if self._closed:
                raise BatcherClosed("micro-batcher is closed")
            st = self._lanes[lane]
            bulk = self._lanes["bulk"]
            if lane == "bulk" and bulk.queued_rows + n > self.bulk_max_rows:
                st.shed += 1
                raise BatcherOverloaded(
                    f"bulk lane holds {bulk.queued_rows} rows (lane bound "
                    f"{self.bulk_max_rows}); shedding bulk — the online lane "
                    "keeps the remaining queue capacity"
                )
            if self._queued_rows_locked() + n > self.max_queue_rows:
                if lane == "online":
                    # Shed bulk first: evict the NEWEST queued bulk requests
                    # (they have waited least) until this online request
                    # fits.  The evicted callers see the same structured
                    # BatcherOverloaded a front-door shed produces.
                    while (bulk.queue
                           and self._queued_rows_locked() + n > self.max_queue_rows):
                        evicted = bulk.queue.pop()
                        bulk.queued_rows -= evicted.count
                        bulk.shed += 1
                        evicted._fail(BatcherOverloaded(
                            "bulk request evicted from the serving queue to "
                            "admit online traffic (shed-bulk-first overload "
                            "policy)"
                        ))
                if self._queued_rows_locked() + n > self.max_queue_rows:
                    st.shed += 1
                    raise BatcherOverloaded(
                        f"queue holds {self._queued_rows_locked()} rows (bound "
                        f"{self.max_queue_rows}); shedding — the replica is "
                        "past its knee, add replicas or lower the offered load"
                    )
            st.queue.append(handle)
            st.queued_rows += n
            st.submitted += 1
            self._cond.notify()
        return handle

    # -- flusher side --

    def _expire_locked(self, now: float) -> None:  # guarded-by: _cond
        """Shed expired requests (queued longer than drop_after_s — their
        handlers have already timed out): running a forward for nobody
        would deepen the backlog that expired them.  Arrival-ordered per
        lane, so each lane's expired set is a prefix."""
        for st in self._lanes.values():
            while st.queue and now - st.queue[0].arrival > self.drop_after_s:
                h = st.queue.pop(0)
                st.queued_rows -= h.count
                st.expired += 1
                h._fail(TimeoutError(
                    f"request expired after {self.drop_after_s}s in the "
                    "serving queue (replica overloaded)"
                ))

    def _take_locked(self) -> Tuple[List[PredictionHandle], str]:  # guarded-by: _cond
        """(requests to flush now, reason) or ([], "") to keep waiting.
        Whole requests only — a request never splits across flushes, so its
        outputs fan back from exactly one runner call.

        Weighted admission: online packs first, but while BOTH lanes are
        queued at most ``1 - bulk_weight`` of the batch goes to online so
        bulk drains at a guaranteed trickle; bulk then fills whatever rows
        remain.  An overflow in either lane flushes immediately ("full") —
        the leftover requests lead the very next flush, so the online cap
        delays online rows by one flush at most, never stalls them."""
        self._expire_locked(time.monotonic())
        online, bulk = self._lanes["online"], self._lanes["bulk"]
        if not online.queue and not bulk.queue:
            return [], ""
        cap_online = self.max_batch
        if bulk.queue and online.queue:
            cap_online = max(1, self.max_batch - int(self.max_batch * self.bulk_weight))
        take: List[PredictionHandle] = []
        rows = 0
        overflow = False
        for i, h in enumerate(online.queue):
            # The weighted cap never blocks the HEAD online request: a
            # request wider than the cap would otherwise starve behind a
            # standing bulk queue (bulk just trickles less that flush).
            limit = self.max_batch if i == 0 else cap_online
            if rows + h.count > limit:
                overflow = True
                break
            take.append(h)
            rows += h.count
        for h in bulk.queue:
            if rows + h.count > self.max_batch:
                overflow = True
                break
            take.append(h)
            rows += h.count
        if rows == self.max_batch or overflow:
            return take, "full"
        if self._closed:
            return take, "close"
        oldest = min(
            q[0].arrival for q in (online.queue, bulk.queue) if q
        )
        if time.monotonic() - oldest >= self.max_delay_s:
            return take, "deadline"
        return [], ""

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                take, reason = self._take_locked()
                while not take:
                    queues = [st.queue for st in self._lanes.values() if st.queue]
                    if self._closed and not queues:
                        return
                    if queues:
                        # Sleep exactly to the oldest request's deadline.
                        remaining = (
                            min(q[0].arrival for q in queues)
                            + self.max_delay_s - time.monotonic()
                        )
                        self._cond.wait(max(remaining, 0.0))
                    else:
                        self._cond.wait()
                    take, reason = self._take_locked()
                n_real = 0
                for h in take:
                    st = self._lanes[h.lane]
                    st.queue.remove(h)
                    st.queued_rows -= h.count
                    st.rows_served += h.count
                    n_real += h.count
                bucket = next(b for b in self.batch_buckets if b >= n_real)
                if reason == "full":
                    self._flushes_full += 1
                elif reason == "deadline":
                    self._flushes_deadline += 1
                else:
                    self._flushes_close += 1
                self._rows_served += n_real
                self._rows_padded += bucket - n_real
                self._flushes_by_bucket[bucket] += 1
            self._run_flush(take, n_real, bucket)

    def _run_flush(
        self, take: List[PredictionHandle], n_real: int, bucket: int
    ) -> None:
        """Assemble the bucket-padded batch, run it, fan outputs back.
        Runner failures resolve every request of THIS flush with the error
        and the flusher survives — one poisoned batch must not wedge the
        server."""
        try:
            # The flush span IS the serving tier's unit of work: request
            # count + real/padded rows + the chosen bucket beside its wall
            # make batching efficiency (and the padding tax) visible in the
            # merged trace.
            with trace.span(
                "serving:flush", cat="serving", n_requests=len(take),
                n_real=n_real, n_padded=bucket - n_real, bucket=bucket,
            ):
                batch = {
                    k: t[:bucket].copy() for k, t in self._template.items()
                }
                mask = np.zeros((bucket,), np.float32)
                mask[:n_real] = 1.0
                batch[MASK_KEY] = mask
                lo = 0
                for h in take:
                    for k in self._template:
                        arr = np.asarray(
                            h.features[k], self._template[k].dtype
                        )
                        batch[k][lo : lo + h.count] = arr
                    lo += h.count
                outputs, meta = self._runner(batch, n_real)
            lo = 0
            for h in take:
                h._resolve(_slice_outputs(outputs, lo, lo + h.count), meta)
                lo += h.count
        except BaseException as e:  # noqa: BLE001 — fan the failure back
            logger.exception("micro-batch flush of %d request(s) failed", len(take))
            for h in take:
                h._fail(e)

    # -- lifecycle / observability --

    def stats(self) -> Dict[str, Any]:
        """Counters since construction.  Top-level keys are lane-summed
        totals (the pre-lane surface, kept stable for dashboards and the
        bench); ``lanes`` attributes submission/shed/expiry/service to each
        priority lane and ``flushes_by_bucket`` counts flushes per padded
        batch size (JSON-string keys — the stats dict travels in ModelInfo
        responses and stamped artifacts)."""
        with self._cond:
            lanes = {
                name: {
                    "submitted": st.submitted,
                    "queued": len(st.queue),
                    "queued_rows": st.queued_rows,
                    "shed": st.shed,
                    "expired": st.expired,
                    "rows_served": st.rows_served,
                }
                for name, st in self._lanes.items()
            }
            return {
                "submitted": sum(s["submitted"] for s in lanes.values()),
                "queued": sum(s["queued"] for s in lanes.values()),
                "flushes_full": self._flushes_full,
                "flushes_deadline": self._flushes_deadline,
                "flushes_close": self._flushes_close,
                "rows_served": self._rows_served,
                "rows_padded": self._rows_padded,
                "shed_overload": sum(s["shed"] for s in lanes.values()),
                "expired": sum(s["expired"] for s in lanes.values()),
                "lanes": lanes,
                "flushes_by_bucket": {
                    str(b): n for b, n in self._flushes_by_bucket.items()
                },
            }

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop accepting requests, flush what is queued, join the flusher."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout_s)
