"""Hot-id embedding LRU cache — the serving tier's layer over the PS store.

Online traffic is zipfian: a small set of hot ids dominates the pull volume
(the reference serves the same skew — "Elastic Model Aggregation with
Parameter Service", PAPERS.md).  The PS host store sustains millions of
rows/s but each pull pays an RPC round trip (tools/ps_bench.py quantifies
it); caching the hot rows worker-side turns the steady-state embedding read
into a dict hit and reserves the RPC for the cold tail.

Consistency contract:

- Rows are READ-ONLY between weight swaps: serving never pushes gradients,
  so a cached row is exact as-of the time it was pulled.  Training keeps
  pushing to the PS underneath — cached rows go stale the same bounded way
  an async-PS worker's pulled rows do (the repo's existing staleness
  model; docs/serving.md).
- A hot reload (checkpoint swap) calls ``invalidate()``: the cache empties
  and the GENERATION bumps, so a pull that was already in flight against
  the old weights may still RETURN its rows to its caller (that request
  started pre-swap — correct) but can no longer INSERT them: stale rows
  must not survive the swap (tests/test_serving.py pins this).

The miss fetch runs OUTSIDE the lock: an RPC to the PS must not block
concurrent cache hits — only the index walk and insert hold the (leaf,
locksan-wrapped) lock.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict

import numpy as np

from elasticdl_tpu.common import locksan


class HotIdEmbeddingCache:
    """LRU row cache in front of a pull-compatible embedding store
    (``ps/host_store.HostEmbeddingStore`` or ``ps/service.
    RemoteEmbeddingStore`` — anything with ``pull(ids) -> rows`` and
    ``dim``).  Same ``pull`` surface, so the trainer's host-tier injection
    path works through it unchanged (parallel/trainer.wrap_host_stores)."""

    def __init__(self, store: Any, capacity: int = 1 << 20, name: str = "table"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._store = store
        self.dim = store.dim
        self.name = name
        self.capacity = capacity
        self._lock = locksan.lock("HotIdEmbeddingCache._lock", leaf=True)  # lock-order: leaf
        self._rows: "OrderedDict[int, np.ndarray]" = OrderedDict()  # guarded-by: _lock
        self._gen = 0  # guarded-by: _lock
        self._hits = 0  # guarded-by: _lock
        self._misses = 0  # guarded-by: _lock
        self._evictions = 0  # guarded-by: _lock
        self._invalidations = 0  # guarded-by: _lock
        self._stale_drops = 0  # guarded-by: _lock

    # hot-path: the per-flush embedding read on the serving critical path —
    # hits are a dict walk under a leaf lock; only misses pay the store RPC
    def pull(self, ids: np.ndarray) -> np.ndarray:
        """Rows for ``ids`` (any shape), shaped ``ids.shape + (dim,)`` —
        the HostEmbeddingStore.pull contract."""
        ids = np.ascontiguousarray(ids, np.int64)
        flat = ids.ravel()
        out = np.empty((flat.size, self.dim), np.float32)
        miss_pos = []
        with self._lock:
            gen = self._gen
            rows = self._rows
            for i, id_ in enumerate(flat.tolist()):
                row = rows.get(id_)
                if row is None:
                    miss_pos.append(i)
                else:
                    rows.move_to_end(id_)
                    out[i] = row
            self._hits += flat.size - len(miss_pos)
            self._misses += len(miss_pos)
        if miss_pos:
            pos = np.asarray(miss_pos, np.int64)
            # One store pull for the UNIQUE missing ids (duplicates within a
            # batch fan out from the same fetched row).
            uniq, inverse = np.unique(flat[pos], return_inverse=True)
            fetched = self._store.pull(uniq)
            out[pos] = fetched[inverse]
            with self._lock:
                if self._gen == gen:
                    for id_, row in zip(uniq.tolist(), fetched):
                        # copy(): a row view would pin the whole fetched
                        # buffer per id; the copy bounds memory at dim f32s.
                        rows[id_] = np.array(row, np.float32)
                    while len(rows) > self.capacity:
                        rows.popitem(last=False)
                        self._evictions += 1
                else:
                    # Generation moved (hot reload landed mid-fetch): the
                    # caller still gets its rows — its request started
                    # against the old weights — but the cache must not keep
                    # them past the swap.
                    self._stale_drops += len(uniq)
        return out.reshape(ids.shape + (self.dim,))

    def invalidate(self) -> None:
        """Drop every cached row and bump the generation (hot-reload hook:
        in-flight fetches from the old generation cannot re-insert)."""
        with self._lock:
            self._rows.clear()
            self._gen += 1
            self._invalidations += 1

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "size": len(self._rows),
                "capacity": self.capacity,
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "invalidations": self._invalidations,
                "stale_drops": self._stale_drops,
                "generation": self._gen,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)
