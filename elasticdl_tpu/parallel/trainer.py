"""Trainers: the reference's AllReduceTrainer / PS worker path as ONE jitted
step over a mesh.

Reference parity ([D: BASELINE.json north_star]; sources unverifiable — mount
empty at survey time):

- ``AllReduceTrainer.train_minibatch`` (tf.GradientTape fwd/bwd +
  ``hvd.allreduce(grads)`` + local apply) becomes a shard_map'd function:
  local fwd/bwd on each device's batch shard, ``lax.psum`` of gradients over
  the ``dp`` mesh axis, optax update — all inside one XLA program, so the
  allreduce overlaps/fuses with the backward pass instead of being a separate
  NCCL launch.
- The PS worker path (pull dense params / pull_embedding_vectors, local step,
  push_gradients) becomes the *same* step with embedding tables row-sharded
  over the mesh (see ``elasticdl_tpu.ops.embedding``); "pull" is the
  collective lookup's all_gather/psum_scatter, "push" is its AD transpose.
  The hybrid DeepFM mode (PS embeddings + allreduce dense) is therefore just
  two partition specs inside one step.

Gradient math: each device computes ``loss_local_mean / n_devices``; dense
grads are ``psum``'d (=> grad of the global batch mean), while sharded-table
grads come out of the collective transpose already globally summed, so they
are left alone.  The two paths are consistent without rescaling.
"""

from __future__ import annotations

import inspect
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.metrics import HIST_PREFIX
from elasticdl_tpu.models.spec import EmbeddingTableSpec, ModelSpec
from elasticdl_tpu.parallel import collectives as coll
logger = get_logger("trainer")
from elasticdl_tpu.ops.embedding import (
    ParallelContext,
    pack_table,
    resolve_impl,
    table_shape,
)

from elasticdl_tpu.common.jax_compat import jit_compiled, jit_donating, shard_map


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any


class TrainLoopError(RuntimeError):
    """A step failed mid-run of ``run_train_steps``.

    The jitted step DONATES its input state, so after a failure neither the
    caller's original state nor (possibly) the failing call's input still
    backs real buffers.  ``state`` carries the newest state whose buffers
    are verifiably alive (the last successful step's output), or None when
    nothing usable survives — the worker then rebuilds from the checkpoint
    instead of retrying tasks against deleted buffers forever (the pre-r4
    failure mode: one failed step wedged every subsequent task)."""

    def __init__(self, state: Optional["TrainState"], cause: BaseException):
        super().__init__(str(cause))
        self.state = state


def _state_alive(state: Optional["TrainState"]) -> bool:
    if state is None:
        return False
    try:
        return not any(
            getattr(leaf, "is_deleted", lambda: False)()
            for leaf in jax.tree.leaves(state)
        )
    except Exception:  # pragma: no cover - defensive
        return False


def _process_count(mesh: Mesh) -> int:
    """Distinct host processes owning this mesh's devices (1 = single-host)."""
    return len({d.process_index for d in mesh.devices.flat})


def sp_batch_spec(axes: Tuple[str, ...], d: int) -> P:
    """PartitionSpec for a sequence-parallel leaf of ndim > d: the sequence
    dim ``d`` shards over the INNER axis; on hierarchical meshes the example
    dim additionally shards over the outer axes.  One definition shared by
    input batch sharding and predict output sharding so the two layouts
    cannot drift apart."""
    outer = axes[:-1]
    lead = ((outer,) + (None,) * (d - 1)) if outer else (None,) * d
    return P(*lead, axes[-1])


def batch_leaf_spec(axes: Tuple[str, ...], d: int) -> P:
    """The spec for a batch-shaped leaf of ndim > d under either layout —
    the single selector used by input sharding, predict outputs, and host
    cotangents, so the three cannot drift apart."""
    return P(axes) if d == 0 else sp_batch_spec(axes, d)


def _path_keys(path) -> Tuple[str, ...]:
    keys = []
    for entry in path:
        if hasattr(entry, "key"):
            keys.append(str(entry.key))
        elif hasattr(entry, "name"):
            keys.append(str(entry.name))
        elif hasattr(entry, "idx"):
            keys.append(str(entry.idx))
        else:  # pragma: no cover
            keys.append(str(entry))
    return tuple(keys)


def _tp_dim_leaves(params: Any, tp_dims: Any) -> List[Optional[int]]:
    """Flatten a ``ModelSpec.tensor_sharding`` plan against the params
    structure, keeping the plan's None leaves (``flatten_up_to`` stops at
    the params' leaf positions, where a plain ``tree_flatten`` would
    swallow None as an empty subtree)."""
    treedef = jax.tree_util.tree_structure(params)
    if tp_dims is None:
        return [None] * treedef.num_leaves
    return treedef.flatten_up_to(tp_dims)


def params_partition_specs(
    params: Any,
    tables: List[EmbeddingTableSpec],
    axis_name: str,
    sharded: bool,
    tp_dims: Any = None,
    tp_axis: Optional[str] = None,
):
    """Partition-spec tree for params: tables row-sharded, tensor-parallel
    leaves (``tp_dims`` — the model's tensor_sharding plan, used only on a
    2D mesh where ``tp_axis`` is set) sharded on their declared dim over
    the tp axis, the rest replicated."""
    table_paths = {t.path for t in tables} if sharded else set()
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    dims = (
        _tp_dim_leaves(params, tp_dims)
        if tp_axis is not None
        else [None] * len(paths_leaves)
    )
    specs = []
    for (path, leaf), d in zip(paths_leaves, dims):
        if _path_keys(path) in table_paths:
            specs.append(P(axis_name))
        elif d is not None:
            ndim = len(getattr(leaf, "shape", ()))
            if not 0 <= d < ndim:
                raise ValueError(
                    f"tensor_sharding dim {d} out of range for param "
                    f"{_path_keys(path)} with {ndim} dims"
                )
            entry: List[Any] = [None] * ndim
            entry[d] = tp_axis
            specs.append(P(*entry))
        else:
            specs.append(P())
    return jax.tree_util.tree_unflatten(treedef, specs)


class _OptShard:
    """Per-param shard-plan entry (a deliberately UNREGISTERED class, so a
    plan tree treats it as one pytree leaf): how this dense param's
    optimizer slots lay out over the data-parallel axis.  The canonical
    param-shaped leaf flattens to [size], zero-pads to [padded] (the
    smallest multiple of the shard count — the ``pad_embedding_tables``
    move applied to the flat vector), and shards over the dp axis so each
    replica holds [padded / dp]."""

    __slots__ = ("shape", "size", "padded")

    def __init__(self, shape: Tuple[int, ...], size: int, padded: int):
        self.shape = shape
        self.size = size
        self.padded = padded

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"_OptShard(shape={self.shape}, size={self.size}, padded={self.padded})"


#: Plan marker for leaves the dp-sharding leaves alone: mesh-sharded
#: embedding tables, and (r20) tensor-parallel weight shards — in both
#: cases the optimizer slots already co-shard with the param, so the
#: ZeRO flatten/scatter must not touch them.
_OPT_KEEP = "keep"


def opt_shard_plan(
    params: Any,
    tables: List[EmbeddingTableSpec],
    sharded_embeddings: bool,
    n_shards: int,
    tp_dims: Any = None,
) -> Any:
    """Params-structured tree of ``_OptShard`` entries (dense replicated
    leaves) and ``_OPT_KEEP`` markers (mesh-sharded table leaves, and
    tensor-parallel leaves when ``tp_dims`` carries the model's plan on a
    2D mesh — their moments co-shard over ``tp``, so ZeRO's dp scatter
    skips them and their grads take the plain dp psum)."""
    table_paths = {t.path for t in tables} if sharded_embeddings else set()
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(params)
    dims = _tp_dim_leaves(params, tp_dims)
    entries = []
    for (path, leaf), d in zip(paths_leaves, dims):
        if _path_keys(path) in table_paths or d is not None:
            entries.append(_OPT_KEEP)
            continue
        shape = tuple(leaf.shape)
        size = int(np.prod(shape)) if shape else 1
        padded = -(-size // n_shards) * n_shards
        entries.append(_OptShard(shape, size, padded))
    return jax.tree_util.tree_unflatten(treedef, entries)


def opt_state_partition_specs(
    optimizer: optax.GradientTransformation,
    params: Any,
    param_specs: Any,
    shard_plan: Any = None,
    shard_axis: Optional[str] = None,
):
    """Partition specs for optax state: param-shaped leaves (momenta etc.)
    inherit their param's spec — co-sharding table optimizer slots with the
    table rows, as the reference's per-PS-pod Go optimizer state does.

    With a ``shard_plan`` (ZeRO-style mode), dense param-shaped leaves are
    stored flat [padded] and partition over ``shard_axis`` instead; table
    leaves keep their co-sharded spec, non-param leaves stay replicated."""
    state_shapes = jax.eval_shape(optimizer.init, params)
    if shard_plan is None:
        return optax.tree_map_params(
            optimizer,
            lambda _, spec: spec,
            state_shapes,
            param_specs,
            transform_non_params=lambda _: P(),
        )
    return optax.tree_map_params(
        optimizer,
        lambda _, spec, entry: (
            P(shard_axis) if isinstance(entry, _OptShard) else spec
        ),
        state_shapes,
        param_specs,
        shard_plan,
        transform_non_params=lambda _: P(),
    )


def _tree_psum_except(tree: Any, skip_paths, axes, skip_axes, topo=None):
    """psum ``tree`` over ``axes``, except leaves at ``skip_paths`` which
    psum over ``skip_axes`` only (empty = left alone).

    Dense grads sum over every mesh axis; sharded-table grads come out of
    the collective lookup's transpose already summed WITHIN the embedding
    axis, so on a hierarchical mesh they still need the data-parallel axes'
    contribution (each dp replica saw different examples) — but psum'ing
    them over the embedding axis too would multiply the gradient by its
    size.  ``topo`` routes big dense leaves over the graftreduce
    hierarchical path (parallel/collectives.py)."""

    def maybe_psum(path, leaf):
        if _path_keys(path) in skip_paths:
            return coll.psum(leaf, skip_axes, topo) if skip_axes else leaf
        return coll.psum(leaf, axes, topo)

    return jax.tree_util.tree_map_with_path(maybe_psum, tree)


def pad_embedding_tables(params: Any, tables: List[EmbeddingTableSpec]) -> Any:
    """Bring each declared table into the padded lane-packed [P, pack*dim]
    layout (see ops.embedding docstring), so shapes are stable across every
    mesh size.  Tables already in that shape pass through; plain [V, dim] or
    flat [V*dim] user tables are packed and zero-padded."""
    if not tables:
        return params
    by_path = {t.path: t for t in tables}

    def pad(path, leaf):
        t = by_path.get(_path_keys(path))
        if t is None:
            return leaf
        target = table_shape(t.vocab_size, t.dim)
        if leaf.ndim == 2 and leaf.shape == target:
            return leaf
        packed = pack_table(leaf, t.dim)
        if packed.shape[1] != target[1] or packed.shape[0] > target[0]:
            raise ValueError(
                f"table {t.path}: shape {leaf.shape} packs to {packed.shape}, "
                f"incompatible with the declared vocab {t.vocab_size} x dim "
                f"{t.dim} (padded shape {target})"
            )
        if packed.shape[0] < target[0]:
            # Leaf holds fewer rows than the declared vocab (e.g. a user
            # table built for the raw vocab): zero-pad up to the target.
            packed = jnp.concatenate(
                [packed, jnp.zeros((target[0] - packed.shape[0], target[1]),
                                   packed.dtype)]
            )
        return packed

    return jax.tree_util.tree_map_with_path(pad, params)


class Trainer:
    """Builds and runs jitted train/eval steps for a ModelSpec over a mesh."""

    def __init__(self, spec: ModelSpec, config: JobConfig, mesh: Mesh):
        self.spec = spec
        self.config = config
        self.mesh = mesh
        self._adopt_mesh_axes(mesh)
        self.sharded_embeddings = (
            config.distribution_strategy == DistributionStrategy.PARAMETER_SERVER
            and bool(spec.embedding_tables)
        )
        self.ctx = self._make_ctx()
        self._state_specs = None
        # ZeRO-style optimizer-state shard plan (opt_shard_plan) — set by
        # shard_state once the mode resolves against this mesh; None =
        # replicated layout.
        # Rebuilt only on the task loop (set_mesh / shard_state); the
        # preemption thread's snapshot_state reads the current refs.
        self._opt_plan = None  # single-writer: main
        self._snapshot_fn = None  # single-writer: main
        # Per-batch-structure step caches (see _structured); _train_step
        # keeps pointing at the most recently used build (profiling tools).
        self._train_steps: Dict = {}
        self._eval_steps: Dict = {}
        self._predict_steps: Dict = {}
        self._train_step = None
        self._eval_step = None
        self._predict_step = None
        # jitsan (v6) compile budgets: how many times each built step may
        # LOWER per compiled callable.  Per-step shapes are fixed by the
        # wrap-padding contract, so the per-step budgets are 1 — the
        # fixed-shape promise of docs/perf.md, now enforced at runtime.
        # The scan variants lower once per distinct task length T (full
        # tasks share one T; the job's remainder task adds a second), so
        # they carry headroom instead of a false alarm.  The serving
        # tier overrides predict_step to its padded-shape bucket count.
        # Written only at construction/serving-setup time; the step
        # builders read it.
        self.jit_budgets: Dict[str, int] = {
            "train_step": 1,
            # The tp-sharded train step of the 2D (dp, tp) mesh (r20):
            # a 2D reform re-lowers exactly once like any other reform,
            # and shape-preserving reforms add zero recompiles — same
            # fixed-shape promise, separate declaration so the 2D path's
            # budget is pinned by name (tests/test_mesh2d.py).
            "train_step_2d": 1,
            "train_scan": 4,
            "eval_step": 1,
            "eval_scan": 4,
            "predict_step": 1,
            "snapshot_state": 1,
        }
        # Host-tier tables (spec.host_io): rows live in the native C++ store
        # — in-process on this host (single-process meshes), or behind the
        # gRPC PS service tier when the job runs PS pods (config.ps_addresses
        # — ps/service.py).  The trainer pulls/injects per step and pushes
        # the sparse cotangents back (models/spec.HostTableIO).
        # Replaced wholesale on restore (task loop only); background
        # checkpoint threads read the dict reference atomically.
        self._host_stores: Dict[str, Any] = {}  # single-writer: main
        self._remote_ps = False
        if spec.host_io:
            if spec.batch_shard_dim != 0:
                # Single-process SP works for PER-TOKEN tables (ids [B, S]:
                # the injected rows legally shard with the sequence — the
                # HostTableIO.per_token declaration is the contract; a
                # [B, F]-shaped table would silently feature-slice).
                # Multi-process SP would additionally need per-PROCESS
                # slicing of the sharded dim, which _local_example_range
                # only does for the example dim.
                not_per_token = [
                    k for k, io in spec.host_io.items()
                    if not getattr(io, "per_token", False)
                ]
                if not_per_token:
                    raise NotImplementedError(
                        "host-tier tables under sequence parallelism must "
                        "declare per_token=True (ids [B, S]); table(s) "
                        f"{not_per_token} do not"
                    )
                if _process_count(mesh) > 1:
                    raise NotImplementedError(
                        "host-tier tables with sequence parallelism are "
                        "single-process only; multi-process meshes need "
                        "per-token process slicing"
                    )
            addrs = [
                a.strip()
                for a in getattr(config, "ps_addresses", "").split(",")
                if a.strip()
            ]
            if addrs:
                # Shared PS service fleet: the only legal host-tier layout
                # for multi-process meshes (a per-process store would train
                # divergent row copies), and async-PS semantics throughout.
                from elasticdl_tpu.ps.service import RemoteEmbeddingStore

                self._remote_ps = True
                self._host_stores = {
                    key: RemoteEmbeddingStore(key, io.dim, addrs)
                    for key, io in spec.host_io.items()
                }
            elif _process_count(mesh) > 1:
                raise NotImplementedError(
                    "host-tier embedding tables on a multi-process mesh need "
                    "the PS service tier: run with --num_ps_pods > 0 (or set "
                    "--ps_addresses to an external PS fleet)"
                )
            else:
                from elasticdl_tpu.ps.host_store import HostEmbeddingStore

                self._host_stores = {
                    key: HostEmbeddingStore(
                        dim=io.dim,
                        optimizer=io.optimizer,
                        learning_rate=io.learning_rate,
                        init_scale=io.init_scale,
                    )
                    for key, io in spec.host_io.items()
                }

    def _adopt_mesh_axes(self, mesh: Mesh) -> None:
        """Axis roles for 1-D and hierarchical meshes.

        Embedding tables (and the collective lookup / ring attention) always
        use the LAST axis.  Batch layout by model:

        - data-parallel models (batch_shard_dim=0): the example dim shards
          over EVERY axis jointly.
        - sequence-parallel models (batch_shard_dim=1): on a 1-D mesh the
          sequence dim shards over the single axis (examples replicated); on
          a hierarchical ``("dp", "ep")`` mesh examples shard over the outer
          dp axis and the sequence over the inner ICI axis — data
          parallelism across hosts (DCN sees only the grad psum) with the
          ring attention's ppermutes confined to ICI within a slice.

        The graftreduce topology (r15) re-resolves here too: the outer
        axis's (host, local) factorization is a property of THIS mesh, so
        every elastic reform re-derives it, and the subgroup mask resets
        to all-active (contributor count is mesh-shaped).

        Tensor-parallel models (spec.tensor_sharding, r20) on a 2D
        ``(dp, tp)`` mesh: ``tp_axis`` names the inner model axis and
        ``reduce_axes`` drops it — the tp axis carries ONLY the model's
        in-block activation all-reduces; loss/metric/gradient reductions
        run over ``dp`` alone (tp ranks hold the same examples, and the
        custom-VJP pair in collectives.py leaves replicated-param grads
        already complete per rank).  On a 1-D mesh the same model runs
        dense and ``reduce_axes == batch_axes`` as always — that IS the
        2D->1D re-partition target.
        """
        self.batch_axes = tuple(mesh.axis_names)
        self.axis_name = mesh.axis_names[-1]  # embedding/sequence axis
        from elasticdl_tpu.parallel.mesh import MODEL_AXIS

        self.tp_axis = (
            MODEL_AXIS
            if self.spec.tensor_sharding is not None
            and self.batch_axes[-1] == MODEL_AXIS
            else None
        )
        self.reduce_axes = tuple(
            a for a in self.batch_axes if a != self.tp_axis
        )
        self.collective = coll.resolve_topology(
            mesh,
            self.reduce_axes,
            mode=getattr(self.config, "collective", coll.AUTO),
            local_size=int(getattr(self.config, "collective_local_size", 0)),
            min_elems=int(
                getattr(self.config, "collective_min_elems", coll.DEFAULT_MIN_ELEMS)
            ),
        )
        # Subgroup-mask contributors are EXAMPLE shards, never sequence
        # slices or tensor-parallel ranks: a data-parallel model
        # (batch_shard_dim=0) shards examples over every REDUCE axis, so
        # each dp position is a contributor (a 2D mesh's tp ranks hold
        # pieces of the same weights and must never be excluded alone); a
        # sequence-parallel model shards examples over the OUTER axes
        # only — its inner-axis slices hold pieces of the SAME examples,
        # and excluding one slice of an example would train on a tensor
        # no dataset produced.  On a 1-D sequence-parallel mesh there is
        # no example sharding at all: one contributor, exclusion
        # unsupported (the worker's gate self-disables at n <= 1).
        self.contributor_axes = (
            self.reduce_axes
            if self.spec.batch_shard_dim == 0
            else self.batch_axes[:-1]
        )
        self._active_np = np.ones(
            coll.contributor_count(mesh, self.contributor_axes), np.float32
        )
        self._active_dev = None

    # ---- graftreduce subgroup participation (r15) ----

    def num_contributors(self) -> int:
        """Subgroup-mask slots: one per EXAMPLE shard of this mesh
        (row-major over ``contributor_axes``) — the worker's collective
        gate addresses exclusions by this index."""
        return int(self._active_np.size)

    def active_contributors(self) -> np.ndarray:
        """The current 0/1 participation mask (host copy)."""
        return np.array(self._active_np)

    def set_active_contributors(self, active=None) -> None:
        """Set the subgroup mask for subsequent train steps.  ``None``
        restores all-active.  The mask is a traced INPUT to the jitted
        step, so this never recompiles — the whole point of in-collective
        exclusion is that it costs data movement, not a recompile (pinned
        by test).  All-zero masks are rejected: a collective over an
        empty subgroup has no mean to renormalize."""
        n = self.num_contributors()
        if active is None:
            mask = np.ones(n, np.float32)
        else:
            mask = np.asarray(active, np.float32).reshape(-1)
            if mask.size != n:
                raise ValueError(
                    f"active mask has {mask.size} slots, mesh has {n} "
                    "contributors"
                )
            if not mask.any():
                raise ValueError("cannot exclude every contributor")
        if np.array_equal(mask, self._active_np):
            return
        self._active_np = mask
        self._active_dev = None

    def _active_device(self):
        """The mask as a replicated device array (built lazily, cached
        until the mask or mesh changes — the steady state costs one
        reference read per step)."""
        if self._active_dev is None:
            sh = NamedSharding(self.mesh, P())
            self._active_dev = jax.tree.leaves(
                self._place_global(self._active_np, sh)
            )[0]
        return self._active_dev

    def collective_bytes_per_step(self, state: TrainState) -> Dict[str, int]:
        """Analytic per-replica inter-host bytes of one step's dense-grad
        all-reduce under this mesh's resolved topology vs the flat route
        (collectives.interhost_bytes_per_step's model; the live
        ``edl_collective_interhost_bytes_total`` counter advances by
        ``resolved`` per step)."""
        table_paths = (
            {t.path for t in self.spec.embedding_tables}
            if self.sharded_embeddings
            else set()
        )
        tp = (
            int(self.mesh.shape[self.tp_axis])
            if self.tp_axis is not None
            else 1
        )
        dims = _tp_dim_leaves(
            state.params,
            self.spec.tensor_sharding(state.params)
            if self.tp_axis is not None and self.spec.tensor_sharding
            else None,
        )
        sizes = []
        for (path, leaf), d in zip(
            jax.tree_util.tree_flatten_with_path(state.params)[0], dims
        ):
            if _path_keys(path) in table_paths:
                continue
            elems = coll.leaf_elems(leaf)
            if d is not None:
                # Tensor-parallel leaf: each rank reduces only its LOCAL
                # shard's grad over dp — 1/tp of the leaf rides the wire.
                elems = -(-elems // tp)
            sizes.append(elems)
        # The grad reduce runs over the dp axes only (reduce_axes): on the
        # 2D mesh the (dp x tp) product never all-reduces as one axis —
        # that is the bytes the 2D layout exists to not move.
        n = coll.contributor_count(self.mesh, self.reduce_axes)
        return {
            "flat": coll.interhost_bytes_per_step(sizes, n, None),
            "resolved": coll.interhost_bytes_per_step(sizes, n, self.collective),
        }

    def _make_ctx(self) -> ParallelContext:
        # Resolve "auto" against the MESH's platform (not the default
        # backend): tests build CPU meshes in a process whose default backend
        # may be TPU, and the ragged-all-to-all HLO only exists on TPU.  The
        # mesh size matters too: a 1-device axis resolves to dense, whose n=1
        # path is a plain local gather (VERDICT r2 Weak #1 — ragged at n=1
        # paid the full routing machinery with zero peers).
        platform = self.mesh.devices.flat[0].platform
        return ParallelContext(
            axis_name=self.axis_name,
            sharded_embeddings=self.sharded_embeddings,
            embedding_impl=resolve_impl(
                self.config.embedding_lookup_impl,
                platform,
                # Tables shard over the LAST axis only; that is the size the
                # collective lookup sees (a hierarchical mesh's dp axis never
                # carries embedding traffic).
                axis_size=self.mesh.shape[self.axis_name],
            ),
            tp_axis=self.tp_axis,
        )

    # ---- elastic re-formation ----

    def set_mesh(self, mesh: Mesh) -> None:
        """Adopt a re-formed mesh (elastic join/leave) and drop compiled
        steps/specs so the next call re-lowers for the new topology.  The
        caller must then re-place state with ``shard_state`` — typically
        after an Orbax restore on the new membership (see master.rendezvous).
        """
        self.mesh = mesh
        self._adopt_mesh_axes(mesh)
        self.ctx = self._make_ctx()
        self._state_specs = None
        self._opt_plan = None
        self._snapshot_fn = None
        self._train_steps = {}
        self._eval_steps = {}
        self._predict_steps = {}
        self._train_step = None
        self._eval_step = None
        self._predict_step = None

    # ---- state management ----

    def init_state(self, rng: jax.Array) -> TrainState:
        params = self.spec.init(rng)
        params = pad_embedding_tables(params, self.spec.embedding_tables)
        opt_state = self.spec.optimizer.init(params)
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)
        return self.shard_state(state)

    def state_specs(self) -> TrainState:
        if self._state_specs is None:
            raise RuntimeError("call init_state/shard_state first")
        return self._state_specs

    # ---- optimizer-state sharding (ZeRO over the data-parallel axis) ----

    def _opt_shard_axis(self) -> str:
        """The axis optimizer state shards over: the OUTER (data-parallel)
        mesh axis — ``dp`` on both the flat 1-D mesh and the hierarchical
        ``(dp, ep)`` mesh."""
        return self.batch_axes[0]

    def _opt_shard_count(self) -> int:
        return int(self.mesh.shape[self._opt_shard_axis()])

    def _opt_map(self, fn, opt_state: Any, *rest: Any) -> Any:
        """Map ``fn(opt_leaf, *rest_leaves)`` over the PARAM-SHAPED leaves
        of an optax state (momenta etc.), passing non-param leaves (step
        counts) through untouched.  ``rest`` trees are params-structured."""
        return optax.tree_map_params(
            self.spec.optimizer,
            fn,
            opt_state,
            *rest,
            transform_non_params=lambda x: x,
        )

    def _resolve_opt_sharding(self, params: Any, plan: Any) -> bool:
        """Whether THIS mesh runs the sharded optimizer: the config knob,
        re-resolved per mesh adoption (an elastic resize can change the
        answer in ``auto`` mode — the canonical host layout bridges)."""
        mode = getattr(self.config, "optimizer_sharding", "replicated")
        if mode not in ("sharded", "auto") or self._opt_shard_count() <= 1:
            return False
        if mode == "sharded":
            return True
        shapes = jax.eval_shape(self.spec.optimizer.init, params)
        sizes = self._opt_map(
            lambda leaf, entry: (
                int(leaf.size) * leaf.dtype.itemsize
                if isinstance(entry, _OptShard)
                else 0
            ),
            shapes,
            plan,
        )
        per_replica = sum(
            s for s in jax.tree.leaves(sizes) if isinstance(s, int)
        )
        threshold = float(
            getattr(self.config, "optimizer_sharding_auto_mb", 64.0)
        ) * (1 << 20)
        return per_replica >= threshold

    def _opt_canonical(self, opt_state: Any, params: Any) -> Any:
        """Bring every param-shaped optimizer leaf to the CANONICAL
        (param-shaped) layout, from EITHER layout.  A flat leaf is always
        ``[data, zero-pad]`` regardless of which shard count padded it, so
        ``reshape(-1)[:size]`` recovers the data bit-for-bit — this is
        what lets a 4->8->4 resize redistribute existing moments instead
        of re-initializing them, and what makes checkpoints topology- and
        mode-agnostic."""

        def canon(leaf, p):
            shape = tuple(np.shape(p))
            if tuple(np.shape(leaf)) == shape:
                return leaf
            size = int(np.prod(shape)) if shape else 1
            return np.reshape(np.reshape(np.asarray(leaf), -1)[:size], shape)

        return self._opt_map(canon, opt_state, params)

    def _opt_flat_host(self, opt_state: Any, plan: Any) -> Any:
        """Canonical -> flat-padded host layout per the plan (pure numpy
        data movement; zero-pad mirrors ``pad_embedding_tables``)."""

        def flat(leaf, entry):
            if not isinstance(entry, _OptShard):
                return leaf
            v = np.reshape(np.asarray(leaf), -1)
            if entry.padded != entry.size:
                v = np.concatenate(
                    [v, np.zeros((entry.padded - entry.size,), v.dtype)]
                )
            return v

        return self._opt_map(flat, opt_state, plan)

    def host_state(self, state: TrainState) -> TrainState:
        """Device -> host state in the CANONICAL layout (param-shaped
        optimizer leaves) regardless of the live device layout.  This is
        the ONE representation checkpoints store and elastic reforms
        bridge through: save it anywhere, restore it into any world size,
        either optimizer_sharding mode."""
        state = jax.device_get(state)
        return state.replace(
            opt_state=self._opt_canonical(state.opt_state, state.params)
        )

    def opt_state_bytes_per_device(self, state: TrainState) -> Dict[str, int]:
        """Per-device resident optimizer-state bytes of a PLACED state —
        the number the sharded mode exists to cut (replicated leaves count
        their full copy on every device).  Keys are device ids as strings;
        bench/tests assert on ``max``."""
        per: Dict[str, int] = {}
        for leaf in jax.tree.leaves(state.opt_state):
            if not hasattr(leaf, "addressable_shards"):
                continue
            for shard in leaf.addressable_shards:
                key = str(shard.device.id)
                per[key] = per.get(key, 0) + int(shard.data.nbytes)
        return per

    def shard_state(self, state: TrainState) -> TrainState:
        """Place (or re-place, after a mesh re-formation) state on the mesh.

        Accepts optimizer state in EITHER layout (canonical param-shaped,
        or the flat dp-sharded layout of any PREVIOUS mesh): leaves are
        first canonicalized, then laid out for THIS mesh per the resolved
        optimizer_sharding mode — so an elastic 4->8->4 resize
        REDISTRIBUTES existing Adam/Adagrad moments instead of rebuilding
        them."""
        tp_dims = (
            self.spec.tensor_sharding(state.params)
            if self.tp_axis is not None and self.spec.tensor_sharding
            else None
        )
        p_specs = params_partition_specs(
            state.params,
            self.spec.embedding_tables,
            self.axis_name,
            self.sharded_embeddings,
            tp_dims=tp_dims,
            tp_axis=self.tp_axis,
        )
        params = jax.tree.map(jnp.asarray, state.params)
        plan = opt_shard_plan(
            params,
            self.spec.embedding_tables,
            self.sharded_embeddings,
            self._opt_shard_count(),
            tp_dims=tp_dims,
        )
        self._opt_plan = (
            plan if self._resolve_opt_sharding(params, plan) else None
        )
        o_specs = opt_state_partition_specs(
            self.spec.optimizer,
            params,
            p_specs,
            shard_plan=self._opt_plan,
            shard_axis=self._opt_shard_axis(),
        )
        opt_state = self._opt_canonical(state.opt_state, state.params)
        if self._opt_plan is not None:
            opt_state = self._opt_flat_host(opt_state, self._opt_plan)
        state = state.replace(opt_state=opt_state)
        self._state_specs = TrainState(step=P(), params=p_specs, opt_state=o_specs)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._state_specs
        )
        procs = {d.process_index for d in self.mesh.devices.flat}
        if len(procs) <= 1:
            return jax.device_put(state, shardings)
        # Multi-process mesh: device_put cannot target non-addressable
        # devices.  Every process holds the same host-side state (init is
        # deterministic from the shared seed; restores read the same
        # checkpoint), so each fills in its own addressable shards.

        def place(x, sh):
            arr = np.asarray(jax.device_get(x))
            return jax.make_array_from_callback(arr.shape, sh, lambda i: arr[i])

        return jax.tree.map(place, state, shardings)

    def restore_template(self, state: TrainState) -> TrainState:
        """``state_like`` for CheckpointManager.restore.  Checkpoints store
        the CANONICAL optimizer layout (host_state), so in sharded mode
        the live flat leaves are swapped for param-shaped REPLICATED
        targets; replicated mode passes the live state straight through
        (restore lands directly in the mesh shardings, as before)."""
        if self._opt_plan is None:
            return state

        def target(leaf, entry):
            if not isinstance(entry, _OptShard):
                return leaf
            return jax.ShapeDtypeStruct(
                entry.shape,
                leaf.dtype,
                sharding=NamedSharding(self.mesh, P()),
            )

        return state.replace(
            opt_state=self._opt_map(target, state.opt_state, self._opt_plan)
        )

    def adopt_restored(self, state: TrainState) -> TrainState:
        """Lay a just-restored checkpoint back into the live layout: a
        no-op in replicated mode; in sharded mode each canonical
        (replicated) optimizer leaf is flattened, padded and placed over
        the shard axis — every process placing only its own addressable
        shards, so this works in multi-process worlds too."""
        if self._opt_plan is None:
            return state
        sh = NamedSharding(self.mesh, P(self._opt_shard_axis()))
        single = _process_count(self.mesh) <= 1
        # ONE definition of the canonical->flat padding rule (_opt_flat_host;
        # np.asarray only touches addressable replicas — the restore target
        # is replicated); this method adds just the placement.
        flat = self._opt_flat_host(state.opt_state, self._opt_plan)

        def place(leaf, entry):
            if not isinstance(entry, _OptShard):
                return leaf
            v = np.asarray(leaf)
            if single:
                return jax.device_put(v, sh)
            return jax.make_array_from_callback(v.shape, sh, lambda i, v=v: v[i])

        return state.replace(
            opt_state=self._opt_map(place, flat, self._opt_plan)
        )

    # hot-path: dispatch-only by design — ONE jitted device-side copy per
    # checkpoint boundary, no transfers or collectives on the caller
    # jit-boundary: returns device buffers fresh off the compiled copy
    def snapshot_state(self, state: TrainState) -> TrainState:
        """ONE jitted device-side copy of the live state in the CANONICAL
        layout: fresh buffers no later step can donate (copying the live
        state on the host would race donation), optimizer leaves
        param-shaped so even group-mode collective Orbax saves — which
        stream device arrays straight to disk — write the topology-
        agnostic checkpoint format.  Dispatch-only: the caller pays a
        dispatch RTT, never a drain."""
        if self._snapshot_fn is None:
            plan = self._opt_plan

            def snap(s):
                s = jax.tree.map(jnp.copy, s)
                if plan is None:
                    return s

                def canon(leaf, entry):
                    if not isinstance(entry, _OptShard):
                        return leaf
                    return jnp.reshape(
                        jnp.reshape(leaf, (-1,))[: entry.size], entry.shape
                    )

                return s.replace(
                    opt_state=self._opt_map(canon, s.opt_state, plan)
                )

            # graftlint: allow[shared-state] idempotent jit memo: a racing rebuild costs one duplicate compile of the same function, and either reference is valid
            self._snapshot_fn = jit_compiled(
                snap, name="trainer.snapshot_state",
                expected_variants=self.jit_budgets["snapshot_state"],
            )
        return self._snapshot_fn(state)

    def _batch_spec_for(self, leaf) -> P:
        """PartitionSpec for one batch leaf.

        Data-parallel models (batch_shard_dim=0): the example dim shards
        over every REDUCE axis jointly — each device holds B/total
        examples; on a tensor-parallel 2D mesh that means over ``dp``
        only, REPLICATED along ``tp`` (every tp rank of a dp row works
        the same examples through its weight shard).

        Sequence-parallel models (batch_shard_dim=1): the sequence dim
        shards over the inner axis; on hierarchical meshes the example dim
        additionally shards over the outer (dp) axes (sp_batch_spec).
        Leaves WITHOUT a sequence dim (per-example masks) replicate on a
        1-D mesh but must follow the example-dim sharding on hierarchical
        meshes — a replicated [B] mask against dp-sharded [B/dp, S/ep]
        tokens would weight the wrong examples."""
        d = self.spec.batch_shard_dim
        if d == 0:
            return P(self.reduce_axes)
        if getattr(leaf, "ndim", 0) > d:
            return batch_leaf_spec(self.batch_axes, d)
        outer = self.batch_axes[:-1]
        if outer and getattr(leaf, "ndim", 0) >= 1:
            return P(outer)
        return P()

    def batch_specs(self, batch: Any):
        return jax.tree.map(self._batch_spec_for, batch)

    def shard_batch(self, batch: Any) -> Any:
        """Place a GLOBAL batch on the mesh, sharded on the model's
        ``batch_shard_dim`` (examples for DP, sequence for SP).

        Single-process meshes device_put directly.  Multi-process meshes
        (jax.distributed worlds) cannot device_put onto non-addressable
        devices; every process feeds the same deterministic global batch and
        contributes its own slice via
        ``jax.make_array_from_process_local_data`` (SURVEY.md §3.5).
        """
        for leaf in jax.tree.leaves(batch):
            spec = self._batch_spec_for(leaf)
            for dim, part in enumerate(spec):
                if part is None:
                    continue
                names = part if isinstance(part, tuple) else (part,)
                k = 1
                for nm in names:
                    k *= self.mesh.shape[nm]
                if leaf.shape[dim] % k != 0:
                    raise ValueError(
                        f"batch dimension {dim} of size {leaf.shape[dim]} "
                        f"not divisible by its mesh axes {names} (size {k})"
                    )
        shardings = jax.tree.map(
            lambda x: NamedSharding(self.mesh, self._batch_spec_for(x)), batch
        )
        return self._place_global(batch, shardings)

    def _place_global(self, batch: Any, shardings: Any) -> Any:
        """Place GLOBAL host data under per-leaf shardings: device_put on
        single-process meshes; on multi-process meshes every process holds
        the same global data and contributes its own slice via
        ``jax.make_array_from_process_local_data``."""
        procs = {d_.process_index for d_ in self.mesh.devices.flat}
        if len(procs) <= 1:
            return jax.device_put(batch, shardings)

        def to_global(x, sh):
            x = np.asarray(x)
            spec_dims = [i for i, s in enumerate(sh.spec) if s is not None]
            if not spec_dims:  # replicated leaf: full copy from each process
                return jax.make_array_from_process_local_data(sh, x, x.shape)
            dd = spec_dims[0]
            # This process's contiguous slice range along the sharded dim:
            # the union of its addressable devices' index slices.
            idx_map = sh.addressable_devices_indices_map(x.shape)
            starts = [s[dd].start or 0 for s in idx_map.values()]
            stops = [
                x.shape[dd] if s[dd].stop is None else s[dd].stop
                for s in idx_map.values()
            ]
            take = [slice(None)] * x.ndim
            take[dd] = slice(min(starts), max(stops))
            return jax.make_array_from_process_local_data(
                sh, x[tuple(take)], x.shape
            )

        return jax.tree.map(to_global, batch, shardings)

    # ---- host-tier pull/push (spec.host_io) ----

    def _is_multiprocess(self) -> bool:
        return _process_count(self.mesh) > 1

    def _local_example_range(self, n_examples: int) -> Tuple[int, int]:
        """This process's contiguous [lo, hi) slice of the batch dimension
        under the data-parallel sharding (union of its addressable devices'
        index slices)."""
        sh = NamedSharding(self.mesh, P(self.reduce_axes))
        idx_map = sh.addressable_devices_indices_map((n_examples,))
        starts = [s[0].start or 0 for s in idx_map.values()]
        stops = [
            n_examples if s[0].stop is None else s[0].stop
            for s in idx_map.values()
        ]
        return min(starts), max(stops)

    def _inject_host_rows(self, batch: Any) -> Tuple[Any, Dict[str, Any]]:
        ids = {k: io.ids_fn(batch) for k, io in self.spec.host_io.items()}
        injected = dict(batch)
        multi = self._is_multiprocess()
        for key, table_ids in ids.items():
            if multi:
                # Pull only this process's example slice from the PS fleet;
                # shard_batch's make_array_from_process_local_data reads
                # exactly that slice of the global-shaped buffer, so the
                # zero rows elsewhere are never consumed.
                table_ids = np.asarray(table_ids)
                lo, hi = self._local_example_range(table_ids.shape[0])
                local = self._host_stores[key].pull(table_ids[lo:hi])
                buf = np.zeros(
                    (table_ids.shape[0],) + local.shape[1:], np.float32
                )
                buf[lo:hi] = local
                injected[key] = buf
            else:
                injected[key] = self._host_stores[key].pull(table_ids)
        return injected, ids

    def _push_host_grads(self, ids: Dict[str, Any], host_grads: Dict[str, Any]):
        """Push the step's sparse cotangents into the host-tier stores.

        Materializing ``host_grads`` (np.asarray) BLOCKS on the step that
        produced them — this is the synchronization point the async driver
        (run_train_steps) moves past the next batch's pull.
        """
        multi = self._is_multiprocess()
        for key, grads in host_grads.items():
            # The store applies its server-side optimizer per distinct id,
            # duplicates pre-accumulated (the reference PS's IndexedSlices
            # apply, in C++ — ps/native/edl_native.cc).  Multi-process
            # worlds: each process pushes its OWN example slice (the only
            # shards it can address); duplicates are pre-accumulated within
            # a process's push but land as separate optimizer applies when
            # the same id appears on two processes — the reference's
            # per-worker async push has exactly these semantics.
            if multi:
                id_arr = np.asarray(ids[key])
                part_ids = []
                part_grads = []
                for shard in grads.addressable_shards:
                    part_ids.append(id_arr[shard.index[0]])
                    part_grads.append(np.asarray(shard.data))
                self._host_stores[key].push_grad(
                    np.concatenate(part_ids), np.concatenate(part_grads)
                )
            else:
                self._host_stores[key].push_grad(ids[key], np.asarray(grads))

    # jit-boundary: state/metrics come back undisposed off the jitted step
    def run_train_step(self, state: TrainState, batch: Any):
        """Full training step from a HOST batch: host-tier pull -> shard ->
        jitted step -> sparse cotangent push.  Without host tables this is
        just shard+step."""
        if not self.spec.host_io:
            return self.train_step(state, self.shard_batch(batch))
        injected, ids = self._inject_host_rows(batch)
        state, metrics, host_grads = self.train_step(
            state, self.shard_batch(injected)
        )
        self._push_host_grads(ids, host_grads)
        return state, metrics

    # jit-boundary: state/metrics come back undisposed off the jitted step
    def run_train_steps(
        self,
        state: TrainState,
        batches,
        use_async: bool = False,
        pre_sharded: bool = False,
    ):
        """Train over an iterable of HOST batches.

        ``pre_sharded=True``: the batches are ALREADY device-placed (the
        worker's prefetch thread ran ``shard_batch``, overlapping the H2D
        transfer with the in-flight device step — on a remote/tunneled chip
        a synchronous device_put costs a full RTT per batch).  Only legal
        without host-tier tables: host injection needs the host batch.

        ``use_async=False``: the synchronous loop — each batch's pull sees
        every prior push (sync-by-version PS semantics).

        ``use_async=True`` (host-tier tables only): the reference's async-PS
        mode (SURVEY §2 #9 "async or sync-by-version") as a software
        pipeline — batch ``n+1``'s row pull (host RPC) is issued BEFORE
        blocking on batch ``n``'s cotangents, overlapping the pull with the
        device step still in flight.  The pull therefore reads rows that are
        one un-applied push stale, exactly the bounded-staleness contract of
        an async parameter server; dense params stay exact (they live in the
        jitted step).  With a single batch the pipeline degenerates to the
        synchronous order, so short tasks are bit-identical to sync.

        Returns (state, [metrics per batch]).
        """
        if pre_sharded and self.spec.host_io:
            raise ValueError(
                "pre_sharded batches are incompatible with host-tier "
                "tables (the host pull needs the host batch)"
            )
        metrics_out = []
        last_good: Optional[TrainState] = None  # newest verified-alive state
        try:
            if pre_sharded or not self.spec.host_io or not use_async:
                step = self.train_step if pre_sharded else self.run_train_step
                for batch in batches:
                    state, metrics = step(state, batch)
                    metrics_out.append(metrics)
                    last_good = state
                return state, metrics_out
            # Staleness bound D = config.async_staleness: up to D steps'
            # pushes may be outstanding when a pull happens, letting D
            # host-tier RPC round-trips hide behind device steps (depth 1 =
            # the reference's classic async-PS window; deeper bounds
            # measured by tools/async_depth_bench.py — the default is
            # chosen by that data).
            from collections import deque

            depth = self.config.async_staleness
            pending: deque = deque()  # (ids, host_grads) of in-flight steps
            for batch in batches:
                injected, ids = self._inject_host_rows(batch)
                while len(pending) >= depth:
                    self._push_host_grads(*pending.popleft())
                state, metrics, host_grads = self.train_step(
                    state, self.shard_batch(injected)
                )
                pending.append((ids, host_grads))
                metrics_out.append(metrics)
                last_good = state
            while pending:
                self._push_host_grads(*pending.popleft())
            return state, metrics_out
        except Exception as e:
            # The failed call may have consumed (donated) its input state;
            # surface the newest state that still backs live buffers so the
            # caller can continue instead of wedging on deleted arrays.
            raise TrainLoopError(
                last_good if _state_alive(last_good) else None, e
            ) from e

    # jit-boundary: metrics come back undisposed off the jitted step
    def run_eval_step(self, state: TrainState, batch: Any):
        if self.spec.host_io:
            batch, _ = self._inject_host_rows(batch)
        return self.eval_step(state, self.shard_batch(batch))

    # jit-boundary: outputs come back undisposed off the jitted step
    def run_predict_step(self, state: TrainState, batch: Any):
        if self.spec.host_io:
            batch, _ = self._inject_host_rows(batch)
        return self.predict_step(state, self.shard_batch(batch))

    def save_host_stores(self, directory: str, step: int, keep_max: int = 3) -> None:
        """Snapshot host-tier stores alongside the Orbax checkpoint, pruning
        old step snapshots like Orbax's own retention does (host tables are
        the multi-GB case — unbounded snapshots would exhaust the volume)."""
        if not self._host_stores:
            return
        if self._remote_ps:
            # PS fleet: each shard dumps its own slice atomically and prunes
            # its own old files (ps/service.PSServer._save) — the worker only
            # fans the request out.  ONE fan-out total: a Save request makes
            # a shard snapshot EVERY table it serves, so looping over stores
            # (all views of the same fleet) would rewrite identical files
            # len(host_io) times per checkpoint.  Callers rank-gate this in
            # multi-process worlds (worker._maybe_checkpoint) so shards save
            # once per step.
            next(iter(self._host_stores.values())).save_snapshot(
                directory, step, keep_max=keep_max
            )
            return
        root = os.path.join(directory, "host_stores")
        d = os.path.join(root, str(step))
        os.makedirs(d, exist_ok=True)
        from elasticdl_tpu.common import durable

        for key, store in self._host_stores.items():
            # Atomic per-file commit: a crash mid-write must leave either no
            # snapshot (restore falls back to an older step) or a complete
            # one — never a truncated file that poisons every relaunch.
            final = os.path.join(d, f"{key}.bin")
            tmp = durable.tmp_path(final)
            store.save(tmp)
            durable.atomic_replace(tmp, final)
        steps = sorted(
            (int(s) for s in os.listdir(root) if s.isdigit()), reverse=True
        )
        for old in steps[max(keep_max, 1):]:
            import shutil

            shutil.rmtree(os.path.join(root, str(old)), ignore_errors=True)

    def restore_host_stores(
        self, directory: str, step: int, strict: bool = True
    ) -> bool:
        """Load host-tier snapshots for ``step``.  ``strict`` (default)
        raises FileNotFoundError when the spec has host tables but the
        snapshot is missing — silently continuing would pair restored dense
        params with freshly re-initialized embeddings (a torn checkpoint)."""
        if not self._host_stores:
            return False
        if self._remote_ps:
            # Async-PS semantics: the PS fleet outlives worker restarts, so
            # an elastic re-join does NOT roll the host tier back to the
            # checkpoint step (pushed gradients are never un-applied — the
            # reference PS behaves identically).  PS pods restore their own
            # slices from the newest complete snapshot when THEY (re)start
            # (ps/main.py).  The worker still VERIFIES fleet consistency
            # here: shards restore independently, so a crash can leave them
            # on different steps — and an evaluation/prediction job whose
            # fleet restored nothing would silently score freshly
            # initialized rows.  Fail those loud; training re-joins
            # error-log and continue (bounded-staleness tolerance).
            steps = next(iter(self._host_stores.values())).restored_steps()
            distinct = set(steps)
            scoring = self.config.job_type in ("evaluation", "prediction")
            if distinct == {None}:
                # Whole fleet fresh: fine mid-training (rows accumulated
                # since job start live only in memory until the first
                # snapshot), fatal when scoring a trained model.
                if scoring:
                    raise RuntimeError(
                        f"{self.config.job_type} job: no PS shard restored "
                        "any snapshot — refusing to score freshly "
                        "initialized embedding rows"
                    )
                return True
            if len(distinct) > 1:
                msg = (
                    f"PS shards restored divergent steps {steps} — the "
                    "fleet mixes model versions"
                )
                if scoring:
                    raise RuntimeError(msg)
                logger.error("%s; continuing (async-PS training tolerance)", msg)
            return True
        paths = {
            key: os.path.join(directory, "host_stores", str(step), f"{key}.bin")
            for key in self._host_stores
        }
        missing = [p for p in paths.values() if not os.path.exists(p)]
        if missing:
            # Validate BEFORE mutating any store: a partial load would pair
            # some tables' checkpoint rows with others' live/fresh rows.
            if strict:
                raise FileNotFoundError(
                    f"host store snapshot missing for step {step}: "
                    f"{missing[0]} (torn checkpoint — dense state and host "
                    "rows must restore together)"
                )
            # non-strict: load whatever exists (in-process resize keeps live
            # rows for the rest)
            loaded = False
            for key, path in paths.items():
                if os.path.exists(path):
                    self._host_stores[key].load(path)
                    loaded = True
            return loaded
        try:
            for key, path in paths.items():
                self._host_stores[key].load(path)
        except (IOError, ValueError) as e:
            # A corrupt file detected mid-load leaves earlier stores mutated;
            # re-initialize them all so a fallback to an older step (or a
            # fresh start) never mixes rows from a torn step.
            from elasticdl_tpu.ps.host_store import HostEmbeddingStore

            self._host_stores = {
                key: HostEmbeddingStore(
                    dim=io.dim,
                    optimizer=io.optimizer,
                    learning_rate=io.learning_rate,
                    init_scale=io.init_scale,
                )
                for key, io in self.spec.host_io.items()
            }
            raise FileNotFoundError(
                f"host store snapshot for step {step} is unreadable ({e}); "
                "stores re-initialized"
            ) from e
        return True

    def wrap_host_stores(self, wrap) -> None:
        """Layer a decorator over every host-tier store — the serving tier
        interposes its hot-id LRU cache this way (serving/embedding_cache).
        ``wrap(key, store)`` must return a pull-compatible object (same
        ``pull``/``dim`` surface); training paths additionally need
        ``push_grad``/``save``/``load`` if they run through the wrapper."""
        self._host_stores = {
            key: wrap(key, store) for key, store in self._host_stores.items()
        }

    # ---- step builders ----

    # Built steps cache by the BATCH TREE STRUCTURE, not just lazily once:
    # shard_map in_specs are a structural prefix of the batch, and batches
    # of one job legitimately differ in structure (a wrap-padded tail adds
    # ``__mask__``).  A single cached step built from the first batch then
    # blows up on the tail's pytree (found by test_partial_tail_batch).
    # jit still handles shape/dtype retraces within a structure.

    def _structured(self, cache: Dict, build, batch: Any, **kwargs):
        key = jax.tree.structure(batch)
        fn = cache.get(key)
        if fn is None:
            fn = build(
                self.spec,
                self.mesh,
                self.ctx,
                self.state_specs(),
                batch_specs=self.batch_specs(batch),
                batch_axes=self.batch_axes,
                **kwargs,
            )
            cache[key] = fn
        return fn

    def _train_build_kwargs(self) -> Dict[str, Any]:
        """The build_train_step kwargs shared by the per-step and scan
        variants: the optimizer shard plan for this mesh and the donation
        knob — one definition so the two step shapes cannot drift."""
        return dict(
            opt_shard=self._opt_plan,
            opt_shard_axis=self._opt_shard_axis(),
            donate=bool(getattr(self.config, "donate_train_state", True)),
            collective=self.collective,
        )

    # jit-boundary: returns device buffers fresh off the compiled step
    def train_step(self, state: TrainState, batch: Any):
        self._train_step = self._structured(
            self._train_steps, build_train_step, batch,
            host_keys=tuple(sorted(self.spec.host_io)),
            variant_budget=self.jit_budgets[
                "train_step_2d" if self.tp_axis is not None else "train_step"
            ],
            **self._train_build_kwargs(),
        )
        return self._train_step(state, batch, self._active_device())

    def shard_stacked_batch(self, stacked: Any) -> Any:
        """Place a HOST batch of stacked minibatches ([T, mb, ...] per leaf)
        on the mesh in ONE transfer, sharded per STEP (leading scan dim
        replicated, batch dims sharded as usual)."""
        shardings = jax.tree.map(
            lambda x, o: NamedSharding(
                self.mesh, P(None, *self._batch_spec_for(o))
            ),
            stacked,
            self._one_step_shapes(stacked),
        )
        return self._place_global(stacked, shardings)

    @staticmethod
    def _one_step_shapes(stacked: Any):
        """ShapeDtypeStructs of a single step of a stacked [T, ...] batch —
        the shape basis for scan-variant specs (shared by
        shard_stacked_batch / train_scan / eval_scan so the three cannot
        drift)."""
        return jax.eval_shape(
            lambda t: jax.tree.map(lambda v: v[0], t), stacked
        )

    def _scanned(self, cache: Dict, build, stacked: Any, **kwargs):
        """Scan-variant twin of _structured: build (or fetch) the fused
        lax.scan step for this stacked batch's tree structure."""
        key = ("scan", jax.tree.structure(stacked))
        fn = cache.get(key)
        if fn is None:
            fn = build(
                self.spec,
                self.mesh,
                self.ctx,
                self.state_specs(),
                batch_specs=self.batch_specs(self._one_step_shapes(stacked)),
                batch_axes=self.batch_axes,
                scan_steps=True,
                **kwargs,
            )
            cache[key] = fn
        return fn

    # jit-boundary: returns device buffers fresh off the compiled scan
    def train_scan(self, state: TrainState, stacked: Any):
        """All T steps of a task in one jitted lax.scan (one dispatch, one
        compiled program — see build_train_step(scan_steps=True)).
        ``stacked``: device batch from shard_stacked_batch.  Returns
        (state, metrics dict of [T]-stacked scalars)."""
        self._train_step = self._scanned(
            self._train_steps, build_train_step, stacked, host_keys=(),
            variant_budget=self.jit_budgets["train_scan"],
            **self._train_build_kwargs(),
        )
        return self._train_step(state, stacked, self._active_device())

    # jit-boundary: returns device metrics fresh off the compiled step
    def eval_step(self, state: TrainState, batch: Any) -> Dict[str, jax.Array]:
        self._eval_step = self._structured(
            self._eval_steps, build_eval_step, batch,
            variant_budget=self.jit_budgets["eval_step"],
        )
        return self._eval_step(state, batch)

    # jit-boundary: returns device metrics fresh off the compiled scan
    def eval_scan(self, state: TrainState, stacked: Any):
        """All T eval steps of a task in one jitted lax.scan (see
        build_eval_step(scan_steps=True)).  Returns a metrics dict of
        [T]-stacked leaves; the caller weights per-chunk as usual."""
        self._eval_step = self._scanned(
            self._eval_steps, build_eval_step, stacked,
            variant_budget=self.jit_budgets["eval_scan"],
        )
        return self._eval_step(state, stacked)

    # jit-boundary: returns device outputs fresh off the compiled step
    def predict_step(self, state: TrainState, batch: Any):
        self._predict_step = self._structured(
            self._predict_steps, build_predict_step, batch,
            variant_budget=self.jit_budgets["predict_step"],
        )
        return self._predict_step(state, batch)


def build_train_step(
    spec: ModelSpec,
    mesh: Mesh,
    ctx: ParallelContext,
    state_specs: TrainState,
    host_keys: Sequence[str] = (),
    batch_specs: Any = None,
    batch_axes: Optional[Tuple[str, ...]] = None,
    scan_steps: bool = False,
    opt_shard: Any = None,
    opt_shard_axis: Optional[str] = None,
    donate: bool = True,
    collective: Any = None,
    variant_budget: int = 1,
) -> Callable:
    """The jitted train step ``(state, batch, active) -> ...``.  With
    ``host_keys`` (host-tier tables), the step ALSO differentiates with
    respect to those injected batch arrays and returns their cotangents as
    a third output, batch-sharded — the device-side half of the
    pull/step/push cycle (Trainer.run_train_step).

    ``active`` is the graftreduce subgroup mask (r15): a replicated
    ``[n_contributors]`` float32 vector of 0/1 participation weights, one
    per data-parallel shard.  Every contribution — the loss term, and via
    the chain rule every dense AND sparse gradient — scales by this
    shard's weight before any reduction, and every mean divides by the
    ACTIVE count (``sum/|G'|``), so an excluded straggler's shard drops
    out exactly and the survivors' math renormalizes.  With the all-ones
    default the spelling is bit-identical to the pre-r15 step (×1.0 is
    exact; ``psum`` of ones is exactly ``n``).  The mask is a traced
    input: changing the excluded set never recompiles.

    ``collective`` is the resolved graftreduce topology
    (collectives.CollectiveTopology or None): big dense-grad reductions
    route hierarchically (intra-host reduce-scatter, inter-host residue
    psum, local gather), scalars stay flat.

    ``batch_axes`` lists every mesh axis the batch shards over (defaults to
    just the embedding axis — the 1-D mesh).  Reductions of loss/metrics/
    dense grads run over all of them; sharded-table grads get only the
    NON-embedding axes' psum (their transpose already summed within the
    embedding axis).  On a tensor-parallel 2D mesh (``ctx.tp_axis``, r20)
    the tp axis is dropped from every reduction here: tp ranks see the
    same examples, the model's own f/g collectives already complete
    replicated-leaf grads per rank, and tp-sharded leaves' grads ARE the
    local shard's — summing any of it over tp would double-count.

    ``scan_steps=True``: the function takes STACKED batches ([T, ...] per
    leaf, T = steps) and runs all T steps inside one ``lax.scan`` — ONE
    dispatch and one host round-trip per task instead of per minibatch.
    Per-step dispatch costs ~half the step wall-clock on a remote-attached
    chip (docs/perf.md); fusing the task's steps into a single XLA program
    removes it, and is the idiomatic XLA training-loop shape besides
    (static trip count, donated carry).  Caller passes ``batch_specs`` of
    ONE step; specs gain a leading None (scan) dim here.  Incompatible
    with host-tier tables (their pull/push is host work between steps).
    """
    axis = ctx.axis_name
    assert axis is not None
    axes = tuple(batch_axes) if batch_axes else (axis,)
    if ctx.tp_axis is not None:
        axes = tuple(a for a in axes if a != ctx.tp_axis)
    dcn_axes = tuple(a for a in axes if a != axis)
    # Paths of sharded-table grads (params-relative): the collective
    # lookup's transpose sums them within the embedding axis already.
    grad_skip = {t.path for t in spec.embedding_tables} if ctx.sharded_embeddings else set()

    # ZeRO-style sharded weight update (``opt_shard`` is the trainer's
    # opt_shard_plan tree).  Instead of every replica psum'ing full dense
    # grads and redundantly computing the full optax update, dense grads
    # are REDUCE-SCATTERED over the shard axis, the update runs on each
    # replica's 1/dp flat shard (against its matching param slice and its
    # resident 1/dp optimizer-state shard), and the fresh updates are
    # all-gathered back — same math, 1/dp of the optimizer memory and
    # update FLOPs per replica.  Table leaves (_OPT_KEEP) keep the
    # existing co-sharded path untouched.
    if opt_shard is not None:
        shard_axis = opt_shard_axis or axes[0]
        n_shards = int(mesh.shape[shard_axis])
        other_axes = tuple(a for a in axes if a != shard_axis)

        def _pad_flat(x, entry):
            v = jnp.reshape(x, (-1,))
            if entry.padded != entry.size:
                v = jnp.concatenate(
                    [v, jnp.zeros((entry.padded - entry.size,), v.dtype)]
                )
            return v

        def sharded_update(state: TrainState, grads):
            idx = lax.axis_index(shard_axis)

            def combine_grad(entry, g):
                if not isinstance(entry, _OptShard):
                    # Sharded-table grad: already summed within the
                    # embedding axis by the collective transpose.
                    return coll.psum(g, dcn_axes, collective) if dcn_axes else g
                if other_axes:
                    g = coll.psum(g, other_axes, collective)
                return coll.psum_scatter(
                    _pad_flat(g, entry), shard_axis,
                    scatter_dimension=0, tiled=True,
                )

            def shard_param(entry, p):
                if not isinstance(entry, _OptShard):
                    return p  # table leaf: already the local row shard
                k = entry.padded // n_shards
                return lax.dynamic_slice_in_dim(
                    _pad_flat(p, entry), idx * k, k
                )

            def expand_update(entry, u):
                if not isinstance(entry, _OptShard):
                    return u
                full = lax.all_gather(u, shard_axis, axis=0, tiled=True)
                return jnp.reshape(full[: entry.size], entry.shape)

            g_dom = jax.tree.map(combine_grad, opt_shard, grads)
            p_dom = jax.tree.map(shard_param, opt_shard, state.params)
            updates, opt_state = spec.optimizer.update(
                g_dom, state.opt_state, p_dom
            )
            updates = jax.tree.map(expand_update, opt_shard, updates)
            params = optax.apply_updates(state.params, updates)
            return params, opt_state

    # Wrap-padded training tails: the worker marks real rows in
    # ``__mask__`` (exactly as eval does); padded duplicates then carry
    # ZERO loss — hence zero gradient, dense and sparse alike — and the
    # cross-device combine weights each shard by its REAL count:
    # psum(local_masked_mean * count) / psum(count).  Without a mask the
    # math reduces to the old equal-shards /n + psum form bit-for-bit.
    # Loss fns without a mask parameter (user models) train on the padded
    # batch as before.
    wants_mask = "mask" in inspect.signature(spec.loss).parameters
    wants_metric_mask = "mask" in inspect.signature(spec.metrics).parameters

    # Exclusion slots are EXAMPLE shards (Trainer.contributor_axes): all
    # axes for data-parallel models, the outer axes for sequence-parallel
    # ones — an inner-axis sequence slice shares its examples with its
    # row and must never be excluded alone.
    contrib_axes = tuple(axes) if spec.batch_shard_dim == 0 else tuple(axes[:-1])

    def local_step(state: TrainState, batch, active):
        # This shard's 0/1 subgroup weight (graftreduce r15): scales the
        # loss BEFORE autodiff, so every gradient — dense psum'd, table
        # transpose-summed, host cotangent — carries the exclusion via
        # the chain rule; no per-leaf masking can drift from the loss.
        # Constant per contributor, so sequence-parallel slices of one
        # example row scale uniformly; psum over ALL axes then counts
        # each contributor once per inner slice in both numerator and
        # denominator — the renormalization cancels exactly.
        w = (
            coll.contributor_weight(active, contrib_axes)
            if contrib_axes
            else active[0]  # SP 1-D mesh: one contributor, always active
        )
        n_active = jnp.maximum(coll.psum(w, axes), 1.0)
        batch = dict(batch)
        mask = batch.pop("__mask__", None) if wants_mask else None
        host_in = {k: batch.pop(k) for k in host_keys}
        if mask is not None:
            # Real-example count of THIS shard, zeroed when excluded: the
            # renormalized total is the active shards' real examples.
            count = jnp.sum(mask.astype(jnp.float32)) * w
            total = jnp.maximum(coll.psum(count, axes), 1e-12)

        def loss_fn(params, host_embs):
            merged = dict(batch)
            merged.update(host_embs)
            out = spec.apply(params, merged, train=True, ctx=ctx)
            if mask is not None:
                # count/total are constants w.r.t. params; the psum above
                # traces fine under grad.
                return spec.loss(out, merged, mask=mask) * count / total, out
            return spec.loss(out, merged) * w / n_active, out

        (loss, out), (grads, host_grads) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True
        )(state.params, host_in)
        loss = coll.psum(loss, axes)
        if opt_shard is not None:
            params, opt_state = sharded_update(state, grads)
        else:
            grads = _tree_psum_except(
                grads, grad_skip, axes, dcn_axes, collective
            )
            updates, opt_state = spec.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
        # Histogram metrics (streaming AUC, common/metrics.HIST_PREFIX) are
        # EVAL machinery — per-minibatch training AUC is noise, and the
        # reference computes AUC only in evaluation — so the train step
        # drops them before the collective mean.
        if mask is not None and wants_metric_mask:
            raw = spec.metrics(out, batch, mask=mask)
            metrics = {
                k: coll.psum(v * count, axes) / total
                for k, v in raw.items()
                if not k.startswith(HIST_PREFIX)
            }
        else:
            metrics = {
                k: coll.psum(v * w, axes) / n_active
                for k, v in spec.metrics(out, batch).items()
                if not k.startswith(HIST_PREFIX)
            }
        metrics["loss"] = loss
        new_state = TrainState(step=state.step + 1, params=params, opt_state=opt_state)
        if host_keys:
            # Per-example cotangents of the global-mean loss, batch-sharded;
            # NOT psum'd (each example's grad lives on its own shard).
            return new_state, metrics, host_grads
        return new_state, metrics

    if scan_steps:
        if host_keys:
            raise ValueError("scan_steps is incompatible with host-tier tables")

        def local_scan(state: TrainState, batches, active):
            # The mask is scan-invariant: one exclusion set per task
            # dispatch (the worker's gate runs at the task boundary).
            def body(carry, one):
                return local_step(carry, one, active)

            return lax.scan(body, state, batches)

        one_step_specs = batch_specs if batch_specs is not None else P(axis)
        stacked_specs = jax.tree.map(
            lambda s: P(None, *s),
            one_step_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        mapped = shard_map(
            local_scan,
            mesh=mesh,
            in_specs=(state_specs, stacked_specs, P()),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
        if donate:
            return jit_donating(
                mapped, name="trainer.train_scan",
                expected_variants=variant_budget,
            )
        return jit_compiled(
            mapped, name="trainer.train_scan",
            expected_variants=variant_budget,
        )

    out_specs: Tuple = (state_specs, P())
    if host_keys:
        # Host cotangents mirror the injected leaf's batch layout
        # (batch_leaf_spec — the same selector as input sharding).
        host_spec = batch_leaf_spec(axes, spec.batch_shard_dim)
        out_specs = (state_specs, P(), {k: host_spec for k in host_keys})
    mapped = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(
            state_specs,
            batch_specs if batch_specs is not None else P(axis),
            P(),
        ),
        out_specs=out_specs,
        check_vma=False,
    )
    if donate:
        return jit_donating(
            mapped, name="trainer.train_step", expected_variants=variant_budget
        )
    return jit_compiled(
        mapped, name="trainer.train_step", expected_variants=variant_budget
    )


def build_predict_step(
    spec: ModelSpec,
    mesh: Mesh,
    ctx: ParallelContext,
    state_specs: TrainState,
    batch_specs: Any = None,
    batch_axes: Optional[Tuple[str, ...]] = None,
    variant_budget: int = 1,
) -> Callable:
    """Per-example model outputs, batch-sharded in and out (the reference's
    predict mode, SURVEY.md §2 #1 'predict').  Models with a ``predict``
    entry (models/spec.ModelSpec.predict) serve client-ready values (e.g.
    probabilities); the rest serve raw ``apply(train=False)`` outputs."""
    axis = ctx.axis_name
    assert axis is not None

    def local_predict(state: TrainState, batch):
        # Tensor-parallel meshes: outputs are replicated along tp (the
        # model's final tp_all_reduce completes them on every rank), so
        # the dp-only out_spec below reassembles the global batch.
        # Serving batches ride with a padding mask the model must not see
        # (``__mask__`` is the micro-batcher's fan-back bookkeeping) —
        # mirror local_eval's pop.
        batch = dict(batch)
        batch.pop("__mask__", None)
        if spec.predict is not None:
            return spec.predict(state.params, batch, ctx=ctx)
        return spec.apply(state.params, batch, train=False, ctx=ctx)

    d = spec.batch_shard_dim
    axes = tuple(batch_axes) if batch_axes else (axis,)
    if ctx.tp_axis is not None:
        axes = tuple(a for a in axes if a != ctx.tp_axis)
    # Per-example outputs mirror the input batch layout (batch_leaf_spec —
    # the same selector as input sharding and host cotangents).
    out_spec = batch_leaf_spec(axes, d)
    mapped = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(state_specs, batch_specs if batch_specs is not None else P(axis)),
        out_specs=out_spec,
        check_vma=False,
    )
    return jit_compiled(
        mapped, name="trainer.predict_step", expected_variants=variant_budget
    )


def build_eval_step(
    spec: ModelSpec,
    mesh: Mesh,
    ctx: ParallelContext,
    state_specs: TrainState,
    batch_specs: Any = None,
    batch_axes: Optional[Tuple[str, ...]] = None,
    scan_steps: bool = False,
    variant_budget: int = 1,
) -> Callable:
    axis = ctx.axis_name
    assert axis is not None
    axes = tuple(batch_axes) if batch_axes else (axis,)
    if ctx.tp_axis is not None:
        # Metrics reduce over dp only — each tp rank computes identical
        # metrics from its replicated logits and examples.
        axes = tuple(a for a in axes if a != ctx.tp_axis)
    # Tail-chunk correctness: the worker wrap-pads the last eval chunk to the
    # static minibatch size and marks real rows in ``__mask__``.  Metrics
    # functions that accept a mask compute means over real examples only;
    # the cross-device aggregate is psum(local_mean * local_count) /
    # psum(local_count), exact under uneven per-device real counts.  Metrics
    # without a mask parameter (user models) fall back to plain pmean over
    # the padded batch.
    wants_mask = "mask" in inspect.signature(spec.metrics).parameters

    def local_eval(state: TrainState, batch):
        batch = dict(batch)
        mask = batch.pop("__mask__", None)
        out = spec.apply(state.params, batch, train=False, ctx=ctx)
        if mask is not None and wants_mask:
            metrics = spec.metrics(out, batch, mask=mask)
            count = jnp.sum(mask.astype(jnp.float32))
            total = jnp.maximum(coll.psum(count, axes), 1e-12)
            return {
                k: coll.psum(v * count, axes) / total
                for k, v in metrics.items()
            }
        return {
            k: coll.pmean(v, axes)
            for k, v in spec.metrics(out, batch).items()
        }

    if scan_steps:
        # Stacked [T, ...] batches, all T eval steps in one lax.scan — the
        # eval-side twin of the fused training task (one dispatch per eval
        # task).  Masked tails stay outside the scan (the worker evals them
        # as one extra step), so the scanned chunks are all full-size and
        # the per-chunk metric weighting stays host-side as before.
        def local_eval_scan(state: TrainState, batches):
            def body(carry, batch):
                return carry, local_eval(state, batch)

            _, metrics = lax.scan(body, 0, batches)
            return metrics

        one_step_specs = batch_specs if batch_specs is not None else P(axis)
        stacked_specs = jax.tree.map(
            lambda s: P(None, *s),
            one_step_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        mapped = shard_map(
            local_eval_scan,
            mesh=mesh,
            in_specs=(state_specs, stacked_specs),
            out_specs=P(),
            check_vma=False,
        )
        return jit_compiled(
            mapped, name="trainer.eval_scan", expected_variants=variant_budget
        )

    mapped = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(state_specs, batch_specs if batch_specs is not None else P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    return jit_compiled(
        mapped, name="trainer.eval_step", expected_variants=variant_budget
    )
