from elasticdl_tpu.parallel.mesh import MeshManager, create_mesh  # noqa: F401
from elasticdl_tpu.parallel.trainer import (  # noqa: F401
    Trainer,
    TrainState,
    build_eval_step,
    build_train_step,
)
