"""graftreduce — the collective layer every gradient/metric reduction
routes through (r15).

Before this module, every reduction in the jitted train/eval steps was a
raw flat ``lax.psum`` over the whole replica set, which bakes two costs
into the step itself:

- **topology blindness**: on a multi-host mesh a flat all-reduce drags
  every gradient byte across the expensive inter-host hop, even though
  the replicas within one host could pre-reduce over their cheap local
  interconnect first ("Highly Available Data Parallel ML training on
  Mesh Networks", PAPERS.md);
- **tail captivity**: the slowest contributor sets the collective's wall
  time — OptiReduce's (PAPERS.md) observation is that a tail-optimal
  AllReduce must be able to stop waiting *inside* the collective, not
  only at the task boundary where r13's gang deadline lives.

This module owns both answers behind one shim surface (the
``jax_compat`` stance: call sites spell the API once, enforced by the
graftlint ``collective-shim`` rule — raw ``lax.psum`` / ``lax.pmean`` /
``lax.psum_scatter`` outside this module and ``common/jax_compat.py``
are findings):

**Hierarchical reduce** (``--collective hierarchical|flat|auto``): the
data-parallel axis of size ``n`` factors into ``(n_host, n_local)``
sub-groups (``parallel/mesh.dp_factorization``: real process grouping,
or ``--collective_local_size`` to pin/emulate it).  A big-leaf psum then
runs in three phases over ``axis_index_groups``:

    1. intra-host reduce-scatter — each local replica ends holding
       1/n_local of its host's partial sum (the cheap hop);
    2. inter-host psum of that residue — only ``size/n_local`` elements
       per replica cross the host boundary (the whole point: inter-host
       bytes cut by the local fan-in);
    3. intra-host all-gather to reassemble the full reduced tensor.

The result equals the flat psum up to float reduction order (the parity
probe in tools/collective_bench.py stamps the max divergence).  Leaves
below ``min_elems`` (loss scalars, metric means, masked counts) stay
single flat collectives — three launches for an 8-byte scalar would be
pure overhead.

**Timeout-bounded participation** (the subgroup weight): reductions can
exclude a straggling contributor and renormalize the mean over the
survivors (``sum / |G'|``).  The exclusion mask is a *traced input* to
the jitted step — ``contributor_weight`` reads this replica's 0/1 weight
out of a replicated ``[n_contributors]`` float vector — so changing the
excluded set never recompiles (pinned by test).  The worker's in-step
deadline gate (worker/worker.py ``_collective_gate``) and the trainer's
``set_active_contributors`` drive the mask; the math here only promises:
with an all-ones mask every formula reduces bit-for-bit to the pre-r15
spelling (multiplying by 1.0 is exact, and ``psum(1.0)`` over the axes
is exactly ``n``).

Composition: the r11 sharded-optimizer path keeps its ``psum_scatter``
(routed through this shim, flat on the wire — a grouped reduce-scatter
would permute the shard→replica mapping the optimizer's
``dynamic_slice`` depends on; see ``psum_scatter``'s docstring), while
its pre-scatter cross-axis psums and the replicated path's grad psums
pick up the hierarchical route.  The subgroup weight composes with both:
it scales contributions *before* any reduction, so exclusion and
hierarchy never see each other.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from elasticdl_tpu.common.jax_compat import axis_size

FLAT = "flat"
HIERARCHICAL = "hierarchical"
AUTO = "auto"
MODES = (FLAT, HIERARCHICAL, AUTO)

#: Leaves smaller than this reduce with ONE flat collective even under a
#: hierarchical topology: the 3-phase route saves inter-host bytes in
#: proportion to leaf size, and a scalar's 3 launches cost more than the
#: bytes they save.  Overridable per job (--collective_min_elems).
DEFAULT_MIN_ELEMS = 4096

Axes = Union[str, Sequence[str]]


def _as_axes(axes: Axes) -> Tuple[str, ...]:
    return (axes,) if isinstance(axes, str) else tuple(axes)


class CollectiveTopology:
    """The static factorization one mesh's reduce axis resolves to.

    ``axis`` is the (outer, data-parallel) mesh axis whose ``n =
    n_host * n_local`` positions group into ``n_host`` hosts of
    ``n_local`` local replicas, contiguously: position ``h * n_local +
    l`` is local replica ``l`` of host ``h`` (exactly how
    ``jax.devices()`` orders a multi-process world — mesh.py).  The two
    group tables are the ``axis_index_groups`` of the 3-phase reduce.
    """

    def __init__(
        self,
        axis: str,
        n_host: int,
        n_local: int,
        min_elems: int = DEFAULT_MIN_ELEMS,
    ):
        self.axis = axis
        self.n_host = int(n_host)
        self.n_local = int(n_local)
        self.min_elems = int(min_elems)
        self.local_groups = [
            [h * self.n_local + l for l in range(self.n_local)]
            for h in range(self.n_host)
        ]
        self.cross_groups = [
            [h * self.n_local + l for h in range(self.n_host)]
            for l in range(self.n_local)
        ]

    @property
    def hierarchical(self) -> bool:
        """Both factors non-trivial — otherwise the 3-phase route
        degenerates to a flat reduce with extra launches."""
        return self.n_host > 1 and self.n_local > 1

    def describe(self) -> dict:
        return {
            "axis": self.axis,
            "n_host": self.n_host,
            "n_local": self.n_local,
            "hierarchical": self.hierarchical,
            "min_elems": self.min_elems,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"CollectiveTopology({self.axis!r}, host={self.n_host}, "
            f"local={self.n_local})"
        )


def resolve_topology(
    mesh,
    axes: Sequence[str],
    mode: str = AUTO,
    local_size: int = 0,
    min_elems: int = DEFAULT_MIN_ELEMS,
) -> Optional[CollectiveTopology]:
    """Resolve the collective mode for one mesh: a CollectiveTopology
    (hierarchical route armed for the outer axis) or None (flat
    everything).

    ``mode``: ``flat`` never factors; ``hierarchical`` factors by
    ``local_size`` (or the mesh's real process grouping) and falls back
    to flat — loudly, via the returned None — when no valid
    factorization exists; ``auto`` goes hierarchical exactly when the
    mesh presents a real multi-host, multi-local-replica grouping (or an
    explicit ``local_size`` says to emulate one).
    """
    if mode not in MODES:
        raise ValueError(f"collective mode must be one of {MODES}, got {mode!r}")
    if mode == FLAT or not axes:
        return None
    from elasticdl_tpu.parallel.mesh import dp_factorization

    axis = axes[0]
    n = int(mesh.shape[axis])
    n_host, n_local = dp_factorization(mesh, axis, local_size=local_size)
    topo = CollectiveTopology(axis, n_host, n_local, min_elems=min_elems)
    if not topo.hierarchical:
        return None
    assert n_host * n_local == n
    return topo


def contributor_count(mesh, axes: Axes) -> int:
    """How many subgroup-mask slots this mesh's batch axes carry — the
    length of the ``active`` vector fed to the jitted step."""
    n = 1
    for a in _as_axes(axes):
        n *= int(mesh.shape[a])
    return n


def contributor_index(axes: Axes):
    """This replica's row-major linear index over ``axes`` — the slot it
    reads out of the replicated exclusion-mask vector.  Static axis
    sizes (jax_compat.axis_size), traced per-axis position."""
    idx = None
    for a in _as_axes(axes):
        pos = lax.axis_index(a)
        idx = pos if idx is None else idx * axis_size(a) + pos
    return idx


def contributor_weight(active, axes: Axes):
    """This replica's 0/1 participation weight: ``active`` is the
    replicated ``[n_contributors]`` float32 mask, indexed by
    ``contributor_index``.  Multiplying a contribution by this weight
    *is* the subgroup psum — excluded replicas still ride the wire (the
    device collective needs every participant to dispatch the same
    program) but contribute exactly zero, and every mean renormalizes
    by ``psum(weight)`` = |G'| instead of the static world size."""
    return active[contributor_index(axes)]


def _hier_reduce_leaf(x, topo: CollectiveTopology):
    """The 3-phase hierarchical all-reduce of ONE leaf over
    ``topo.axis`` (see module docstring).  Flattens, zero-pads to
    n_local divisibility, reduce-scatters within the host group, psums
    the residue across hosts, all-gathers locally, and restores the
    shape.  Padding with zeros is exact for a sum."""
    shape = x.shape
    flat = jnp.reshape(x, (-1,))
    pad = (-flat.size) % topo.n_local
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    part = lax.psum_scatter(
        flat,
        topo.axis,
        scatter_dimension=0,
        tiled=True,
        axis_index_groups=topo.local_groups,
    )
    part = lax.psum(part, topo.axis, axis_index_groups=topo.cross_groups)
    full = lax.all_gather(
        part,
        topo.axis,
        axis=0,
        tiled=True,
        axis_index_groups=topo.local_groups,
    )
    if pad:
        full = full[: int(np.prod(shape)) if shape else 1]
    return jnp.reshape(full, shape)


def leaf_elems(x) -> int:
    """Element count of one leaf (arrays, tracers, or ShapeDtypeStructs;
    shapeless scalars count 1) — the size the ``min_elems`` routing and
    the bytes model both judge, so they cannot drift."""
    shape = getattr(x, "shape", ())
    return int(np.prod(shape)) if shape else 1


def psum(x: Any, axes: Axes, topo: Optional[CollectiveTopology] = None):
    """Sum ``x`` over the named mesh axes.  With a hierarchical ``topo``
    covering one of the axes and a leaf big enough to pay for three
    launches, that axis reduces via the 3-phase grouped route; every
    other case is the flat ``lax.psum`` this shim replaces."""
    names = _as_axes(axes)
    if (
        topo is not None
        and topo.hierarchical
        and topo.axis in names
        and leaf_elems(x) >= topo.min_elems
    ):
        rest = tuple(a for a in names if a != topo.axis)
        if rest:
            x = lax.psum(x, rest)
        return _hier_reduce_leaf(x, topo)
    return lax.psum(x, names)


def pmean(x: Any, axes: Axes, topo: Optional[CollectiveTopology] = None):
    """Mean over the named axes — ``psum / n`` with the same routing as
    ``psum`` (the flat spelling's ``lax.pmean`` is just this with the
    division fused)."""
    names = _as_axes(axes)
    n = 1
    for a in names:
        n *= axis_size(a)
    return psum(x, names, topo) / n


def psum_scatter(
    x: Any,
    axis: str,
    *,
    scatter_dimension: int = 0,
    tiled: bool = True,
):
    """Reduce-scatter over ``axis`` — the r11 sharded-optimizer's grad
    combine, routed through the shim so the collective-shim rule can
    hold the line.  Deliberately flat on the wire: a grouped two-phase
    reduce-scatter lands shard ``l * n_host + h`` on replica
    ``h * n_local + l`` — a permutation of the ``shard == axis_index``
    contract the optimizer's ``dynamic_slice``/``all_gather`` pair
    depends on.  On a hierarchical mesh the scatter is already
    bandwidth-optimal per replica (each element crosses the wire once),
    so the hierarchy's win lives in the full-psum paths."""
    return lax.psum_scatter(
        x, axis, scatter_dimension=scatter_dimension, tiled=tiled
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_all_reduce(x: Any, axis: str):
    """Megatron's *g* operator — sum partial activations over the
    tensor-parallel axis (r20, the 2D ``(dp, tp)`` mesh).

    Forward: ``psum`` over ``axis`` (the one per-block activation
    all-reduce after each row-split matmul).  Backward: IDENTITY — the
    cotangent arriving at a psum output is already replicated across the
    tp ranks, and each rank's partial activation contributed linearly,
    so the true per-rank gradient is that replicated cotangent as-is.
    This must be a ``custom_vjp``: under the shim's
    ``check_vma=False`` shard_map JAX transposes psum to psum, which
    would multiply the replicated cotangent by ``tp``.

    Deliberately flat (no hierarchical route): the mesh places ``tp`` on
    the inner, cheap hop by construction (mesh.py), and the per-block
    activation is far below any inter-host residue worth scattering.
    """
    return lax.psum(x, axis)


def _tp_all_reduce_fwd(x, axis):
    return lax.psum(x, axis), None


def _tp_all_reduce_bwd(axis, _res, g):
    return (g,)


tp_all_reduce.defvjp(_tp_all_reduce_fwd, _tp_all_reduce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_grad_sync(x: Any, axis: str):
    """Megatron's *f* operator — identity forward, psum over the
    tensor-parallel axis backward.

    Placed on a REPLICATED activation right before a column-split
    matmul: forward is a no-op (every tp rank already holds the full
    activation), but each rank's branch consumed it independently, so
    the activation's true gradient is the SUM of the per-rank partials.
    Without this, parameters upstream of the split (norm gains, the
    residual stream, embeddings) would see only one rank's partial and
    the dp-only gradient reduce would never repair it.
    """
    return x


def _tp_grad_sync_fwd(x, axis):
    return x, None


def _tp_grad_sync_bwd(axis, _res, g):
    return (lax.psum(g, axis),)


tp_grad_sync.defvjp(_tp_grad_sync_fwd, _tp_grad_sync_bwd)


def interhost_bytes_per_step(
    leaf_sizes: Sequence[int],
    n_replicas: int,
    topo: Optional[CollectiveTopology] = None,
    itemsize: int = 4,
) -> int:
    """Analytic per-replica inter-host bytes of one step's grad
    all-reduce over ``leaf_sizes`` (element counts of the dense leaves).

    Model (ring/tree equivalences, documented in docs/perf.md): a flat
    all-reduce moves ``2 * size * (n-1)/n`` elements per replica, and on
    a mesh whose ring crosses hosts every hop is potentially inter-host;
    the hierarchical route's only inter-host phase is the residue psum —
    ``2 * (size/n_local) * (n_host-1)/n_host`` per replica.  Leaves
    below ``min_elems`` take the flat route either way.  This is the
    number the ``edl_collective_interhost_bytes_total`` gauge advances
    by (the CPU harness has no real DCN to meter, so the artifact stamps
    the model, labeled as such)."""
    if n_replicas <= 1:
        return 0
    total = 0.0
    for size in leaf_sizes:
        if topo is not None and topo.hierarchical and size >= topo.min_elems:
            residue = -(-size // topo.n_local)  # ceil: padded shard
            total += 2.0 * residue * (topo.n_host - 1) / topo.n_host
        else:
            total += 2.0 * size * (n_replicas - 1) / n_replicas
    return int(total * itemsize)
