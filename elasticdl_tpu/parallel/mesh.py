"""Device-mesh management — the TPU-native replacement for the reference's
Horovod communicator (RendezvousServer + NCCL/Gloo ring [D: BASELINE.json
north_star]; reference sources unverifiable, mount empty at survey time).

Where the reference re-forms an NCCL ring when workers join/leave, we re-form
a ``jax.sharding.Mesh`` over the currently-live devices.  Two shapes:

- **1-D** (default), axis ``"dp"``: data parallelism shards the batch over
  it, and (in ParameterServer strategy) embedding tables are row-sharded
  over the *same* axis — on TPU the "parameter server" is simply the HBM of
  the same chips that compute, and lookups ride ICI collectives instead of
  gRPC.
- **2-D hierarchical** (``dcn_parallelism > 1``), axes ``("dp", "ep")``:
  the outer ``dp`` axis strides across HOSTS (slices) — its only collective
  is the gradient psum, which tolerates DCN latency — while embedding
  tables shard over the inner ``ep`` axis, keeping the latency-sensitive
  ragged all-to-all entirely on ICI within a slice.  Device order from
  ``jax.devices()`` groups each process's devices contiguously, so
  ``reshape(dcn, -1)`` puts one process (or group of processes) per ``dp``
  row by construction.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "dp"
EMBED_AXIS = "ep"


def create_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    num_devices: Optional[int] = None,
    axis_name: str = DATA_AXIS,
    dcn_parallelism: int = 1,
) -> Mesh:
    """Build a mesh over ``devices`` (default: all local devices).

    ``num_devices`` takes a prefix of the available devices — used by the
    elastic path to form smaller meshes after a worker leaves, and by tests to
    emulate 4->8->4 scaling on a fixed pool of fake CPU devices.

    ``dcn_parallelism > 1`` builds the 2-D hierarchical ``(dp, ep)`` mesh
    (see module docstring); it must divide the device count.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} available"
            )
        devices = devices[:num_devices]
    if dcn_parallelism <= 1:
        return Mesh(np.asarray(devices), (axis_name,))
    if len(devices) % dcn_parallelism:
        raise ValueError(
            f"dcn_parallelism {dcn_parallelism} does not divide "
            f"{len(devices)} devices"
        )
    arr = np.asarray(devices).reshape(dcn_parallelism, -1)
    return Mesh(arr, (axis_name, EMBED_AXIS))


def dp_factorization(
    mesh: Mesh, axis_name: str = DATA_AXIS, local_size: int = 0
) -> tuple:
    """Factor ``axis_name``'s positions into ``(n_host, n_local)`` for
    the hierarchical collective route (parallel/collectives.py).

    ``local_size > 0`` pins the local fan-in explicitly — the CPU
    harness's way to emulate a multi-host grouping on fake devices, and
    an operator override for exotic device orders.  It must divide the
    axis size.

    ``local_size == 0`` derives the grouping from the mesh itself: the
    devices along the axis group by ``process_index``, and the
    factorization is real exactly when those groups are contiguous and
    equal-sized (how ``jax.devices()`` orders every multi-process world
    — each process's devices are contiguous).  Anything else — a single
    host, a 1-device-per-process world, ragged groups — returns the
    trivial ``(1, n)``: no hierarchy to exploit, callers fall back to
    flat collectives.
    """
    axis_dim = list(mesh.axis_names).index(axis_name)
    devs = np.moveaxis(mesh.devices, axis_dim, 0)
    n = devs.shape[0]
    if local_size:
        if n % local_size:
            raise ValueError(
                f"collective_local_size {local_size} does not divide the "
                f"{axis_name!r} axis size {n}"
            )
        return n // local_size, local_size
    # One process id per axis position (a position spanning processes —
    # possible only on multi-axis meshes — breaks the grouping).
    procs = []
    for i in range(n):
        owners = {d.process_index for d in np.atleast_1d(devs[i]).flat}
        if len(owners) != 1:
            return 1, n
        procs.append(owners.pop())
    runs = []  # contiguous (process, length) runs along the axis
    for p in procs:
        if runs and runs[-1][0] == p:
            runs[-1][1] += 1
        else:
            runs.append([p, 1])
    lengths = {length for _, length in runs}
    if len(runs) <= 1 or len(lengths) != 1:
        return 1, n
    if len({p for p, _ in runs}) != len(runs):
        return 1, n  # a process re-appears non-contiguously
    return len(runs), lengths.pop()


class MeshManager:
    """Owns the current mesh and re-forms it on membership changes.

    This is the worker-side half of elastic re-rendezvous: the master bumps a
    membership version (see ``elasticdl_tpu.master.rendezvous``); when a worker
    observes a new version it calls ``reform`` with the new world size, and the
    trainer recompiles its step for the new mesh (compile caches make repeat
    sizes cheap).
    """

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        dcn_parallelism: int = 1,
    ):
        self._pool = list(devices) if devices is not None else list(jax.devices())
        self._dcn = dcn_parallelism
        self._mesh: Optional[Mesh] = None
        self._version = -1

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self.reform(len(self._pool), version=0)
        assert self._mesh is not None
        return self._mesh

    @property
    def version(self) -> int:
        return self._version

    def reform(self, num_devices: int, version: int) -> Mesh:
        self._mesh = create_mesh(
            self._pool, num_devices=num_devices, dcn_parallelism=self._dcn
        )
        self._version = version
        return self._mesh

    def num_devices(self) -> int:
        return self.mesh.devices.size
