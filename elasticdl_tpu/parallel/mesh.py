"""Device-mesh management — the TPU-native replacement for the reference's
Horovod communicator (RendezvousServer + NCCL/Gloo ring [D: BASELINE.json
north_star]; reference sources unverifiable, mount empty at survey time).

Where the reference re-forms an NCCL ring when workers join/leave, we re-form
a ``jax.sharding.Mesh`` over the currently-live devices.  Two shapes:

- **1-D** (default), axis ``"dp"``: data parallelism shards the batch over
  it, and (in ParameterServer strategy) embedding tables are row-sharded
  over the *same* axis — on TPU the "parameter server" is simply the HBM of
  the same chips that compute, and lookups ride ICI collectives instead of
  gRPC.
- **2-D hierarchical** (``dcn_parallelism > 1``), axes ``("dp", "ep")``:
  the outer ``dp`` axis strides across HOSTS (slices) — its only collective
  is the gradient psum, which tolerates DCN latency — while embedding
  tables shard over the inner ``ep`` axis, keeping the latency-sensitive
  ragged all-to-all entirely on ICI within a slice.  Device order from
  ``jax.devices()`` groups each process's devices contiguously, so
  ``reshape(dcn, -1)`` puts one process (or group of processes) per ``dp``
  row by construction.
- **2-D hybrid-parallel** (``tensor_parallelism > 1``), axes
  ``("dp", "tp")``: the INNER ``tp`` axis shards a tensor-parallel model's
  weight matrices (models declaring ``ModelSpec.tensor_sharding``) and
  carries the per-block activation all-reduces, so it lives on the cheap
  hop (consecutive devices — within a host for real multi-host worlds);
  the outer ``dp`` axis shards the batch.  Elastic reform picks a legal
  shape via :func:`resolve_2d_shape`: ``tp`` is a MODEL-FIT constraint
  (the weight shards must keep fitting one device), so a shrinking world
  loses ``dp`` replicas first and touches ``tp`` only when fewer than
  ``tp`` devices remain — 8 = tp4 x dp2 -> lose a host -> 4 = tp4 x dp1.
  ``tp == 1`` degrades to the plain 1-D mesh (the 2D->1D re-partition).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from elasticdl_tpu.common.log_utils import get_logger

DATA_AXIS = "dp"
EMBED_AXIS = "ep"
MODEL_AXIS = "tp"

logger = get_logger("mesh")


def create_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    num_devices: Optional[int] = None,
    axis_name: str = DATA_AXIS,
    dcn_parallelism: int = 1,
    tensor_parallelism: int = 1,
) -> Mesh:
    """Build a mesh over ``devices`` (default: all local devices).

    ``num_devices`` takes a prefix of the available devices — used by the
    elastic path to form smaller meshes after a worker leaves, and by tests to
    emulate 4->8->4 scaling on a fixed pool of fake CPU devices.

    ``dcn_parallelism > 1`` builds the 2-D hierarchical ``(dp, ep)`` mesh
    (see module docstring); it must divide the device count.

    ``tensor_parallelism > 1`` builds the 2-D hybrid ``(dp, tp)`` mesh:
    consecutive devices group into ``tp``-sized model shards (the inner
    axis), replicated ``n/tp`` ways over the outer ``dp`` axis.  It must
    divide the device count — elastic callers resolve a legal shape first
    (:func:`resolve_2d_shape`).  Mutually exclusive with
    ``dcn_parallelism`` (a 3-D ``(dcn, dp, tp)`` mesh is out of scope).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} available"
            )
        devices = devices[:num_devices]
    if tensor_parallelism > 1:
        if dcn_parallelism > 1:
            raise ValueError(
                "tensor_parallelism and dcn_parallelism are mutually "
                "exclusive (no 3-D mesh)"
            )
        if len(devices) % tensor_parallelism:
            raise ValueError(
                f"tensor_parallelism {tensor_parallelism} does not divide "
                f"{len(devices)} devices (resolve_2d_shape picks legal shapes)"
            )
        arr = np.asarray(devices).reshape(-1, tensor_parallelism)
        return Mesh(arr, (axis_name, MODEL_AXIS))
    if dcn_parallelism <= 1:
        return Mesh(np.asarray(devices), (axis_name,))
    if len(devices) % dcn_parallelism:
        raise ValueError(
            f"dcn_parallelism {dcn_parallelism} does not divide "
            f"{len(devices)} devices"
        )
    arr = np.asarray(devices).reshape(dcn_parallelism, -1)
    return Mesh(arr, (axis_name, EMBED_AXIS))


def resolve_2d_shape(n_devices: int, tensor_parallelism: int) -> Tuple[int, int]:
    """Legal ``(dp, tp)`` shape for ``n_devices`` live devices under a
    configured tensor-parallel degree.

    ``tp`` is a model-fit constraint — each device holds ``1/tp`` of the
    sharded weights, so reform PRESERVES it and shrinks ``dp`` instead
    (``dp = n // tp``): 8 devices at tp=4 -> (dp=2, tp=4); lose a host ->
    4 devices -> (dp=1, tp=4).  Only when fewer than ``tp`` devices remain
    does ``tp`` shrink — to the largest DIVISOR of the configured degree
    that fits, so head counts and hidden dims divisible by the configured
    ``tp`` stay divisible by the shrunken one.  ``dp * tp`` may be less
    than ``n_devices`` (7 devices at tp=2 use 6); the remainder idles
    until the next reform rather than forcing a ragged axis.
    """
    n = int(n_devices)
    if n < 1:
        raise ValueError(f"need at least one device, got {n}")
    tp = max(1, int(tensor_parallelism))
    while tp > n:
        tp -= 1
        while tp > 1 and tensor_parallelism % tp:
            tp -= 1
    return n // tp, tp


def mesh_shape(mesh: Mesh) -> Tuple[int, int]:
    """The ``(dp, tp)`` view of any mesh: a 1-D mesh is ``(n, 1)``; a
    hierarchical ``(dp, ep)`` mesh reports its full device count as dp
    (no model axis).  One definition shared by gauges, reform trace
    instants and watch_job so the rendered shape cannot drift."""
    shape = dict(mesh.shape)
    tp = int(shape.get(MODEL_AXIS, 1))
    return int(mesh.devices.size) // tp, tp


def dp_factorization(
    mesh: Mesh, axis_name: str = DATA_AXIS, local_size: int = 0
) -> tuple:
    """Factor ``axis_name``'s positions into ``(n_host, n_local)`` for
    the hierarchical collective route (parallel/collectives.py).

    ``local_size > 0`` pins the local fan-in explicitly — the CPU
    harness's way to emulate a multi-host grouping on fake devices, and
    an operator override for exotic device orders.  It must divide the
    axis size.

    ``local_size == 0`` derives the grouping from the mesh itself: the
    devices along the axis group by OWNER PROCESSES, and the
    factorization is real exactly when those groups are contiguous,
    equal-sized and disjoint (how ``jax.devices()`` orders every
    multi-process world — each process's devices are contiguous).  On a
    multi-axis mesh one axis position spans a whole inner-axis row and
    may legitimately span processes — the dp axis of a ``(dp, tp)`` mesh
    with dp=2, tp=4 over 4 two-device processes has each position owned
    by a distinct PAIR of processes, and factors by those pairs.  A
    single host, a 1-device-per-process world, or ragged groups return
    the trivial ``(1, n)``: no hierarchy to exploit, callers fall back
    to flat collectives.  Orders where owner groups interleave or
    overlap along the axis (a tp-major device order threading every
    process through every dp position) also demote to flat — LOUDLY,
    since a real multi-host world is then paying flat-collective bytes
    over a layout a reshape would fix.
    """
    axis_dim = list(mesh.axis_names).index(axis_name)
    devs = np.moveaxis(mesh.devices, axis_dim, 0)
    n = devs.shape[0]
    if local_size:
        if n % local_size:
            raise ValueError(
                f"collective_local_size {local_size} does not divide the "
                f"{axis_name!r} axis size {n}"
            )
        return n // local_size, local_size
    # Owner-process SET per axis position (singleton on 1-D meshes; a
    # whole inner row's owners on multi-axis meshes).
    owners = [
        frozenset(d.process_index for d in np.atleast_1d(devs[i]).flat)
        for i in range(n)
    ]
    multi_owner = any(len(o) > 1 for o in owners)
    runs = []  # contiguous (owner_set, length) runs along the axis
    for o in owners:
        if runs and runs[-1][0] == o:
            runs[-1][1] += 1
        else:
            runs.append([o, 1])

    def flat(reason: str):
        if multi_owner and len(frozenset().union(*owners)) > 1:
            # Positions span processes in a genuinely multi-process world
            # (multi-axis mesh territory), yet no clean grouping exists:
            # a real host hierarchy is being hidden by the device order —
            # say so instead of silently paying flat-collective bytes.
            logger.warning(
                "%s axis of this mesh has %s owner groups; demoting to "
                "flat collectives (no contiguous equal host grouping)",
                axis_name, reason,
            )
        return 1, n

    lengths = {length for _, length in runs}
    if len(runs) <= 1 or len(lengths) != 1:
        return flat("ragged")
    sets = [o for o, _ in runs]
    if len(set(sets)) != len(sets) or len(frozenset().union(*sets)) != sum(
        len(s) for s in sets
    ):
        # A process re-appears non-contiguously, or two groups overlap
        # (tp-major / interleaved orders).
        return flat("interleaved")
    return len(runs), lengths.pop()


class MeshManager:
    """Owns the current mesh and re-forms it on membership changes.

    This is the worker-side half of elastic re-rendezvous: the master bumps a
    membership version (see ``elasticdl_tpu.master.rendezvous``); when a worker
    observes a new version it calls ``reform`` with the new world size, and the
    trainer recompiles its step for the new mesh (compile caches make repeat
    sizes cheap).
    """

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        dcn_parallelism: int = 1,
    ):
        self._pool = list(devices) if devices is not None else list(jax.devices())
        self._dcn = dcn_parallelism
        self._mesh: Optional[Mesh] = None
        self._version = -1

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self.reform(len(self._pool), version=0)
        assert self._mesh is not None
        return self._mesh

    @property
    def version(self) -> int:
        return self._version

    def reform(self, num_devices: int, version: int) -> Mesh:
        self._mesh = create_mesh(
            self._pool, num_devices=num_devices, dcn_parallelism=self._dcn
        )
        self._version = version
        return self._mesh

    def num_devices(self) -> int:
        return self.mesh.devices.size
