"""Multi-host distributed backend — DCN control plane + ICI/DCN data plane.

Reference parity (SURVEY.md §5 "Distributed comm backend" [D]): the
reference's data plane is Horovod->NCCL/Gloo rings between worker pods and
its control plane is gRPC.  The TPU rebuild splits the same way:

- **Control plane**: the master's gRPC service (task dispatch, rendezvous
  versioning) — unchanged across single/multi host — plus JAX's built-in
  distributed coordination service (``jax.distributed``), which PJRT needs
  so every host sees the whole TPU slice as one device set.
- **Data plane**: XLA collectives compiled into the jitted step.  Inside a
  pod slice they ride ICI; across slices (multislice) XLA routes them over
  DCN.  No NCCL/MPI analogue exists or is needed — ``psum`` over the mesh
  IS the allreduce.

Process model: one worker process per TPU host (the reference's one worker
pod per GPU host).  The master assigns each worker a stable ``slot``
(ELASTICDL_WORKER_SLOT); slot 0's address (or an explicit coordinator flag)
seeds ``jax.distributed.initialize``.  After initialization,
``jax.devices()`` returns every chip of every live host, and the mesh spans
them; ``create_mesh`` then works unchanged.

Elasticity: a membership change means the JAX distributed runtime must be
re-initialized with the new host set (XLA's world is fixed per
initialization).  That is exactly the checkpoint-restore re-join the worker
already implements (worker.py ``_replace_state``): shutdown -> initialize
with new topology -> rebuild mesh -> restore.  ``reinitialize`` packages
that sequence.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax

from elasticdl_tpu.common.jax_compat import (
    distributed_initialize,
    enable_cpu_multiprocess_collectives,
)
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.platform import free_port  # noqa: F401 — re-export
# (free_port lives in the jax-free common.platform: bench/test master
# processes that allocate coordinator ports must not pay this module's
# jax import for a socket probe)

logger = get_logger("parallel.distributed")


@dataclasses.dataclass(frozen=True)
class DistributedSpec:
    """Topology of one jax.distributed world."""

    coordinator_address: str  # host:port of process 0's coordination service
    num_processes: int
    process_id: int
    # Coordination-service peer-death detection.  JAX's default (100 s)
    # dominates elastic recovery: a survivor blocked inside a collective on
    # a dead peer sits there until THIS timeout aborts it (measured 83 s of
    # a 99 s total re-rendezvous — tools/rendezvous_bench.py).  30 s is a
    # 3.3x faster default that still tolerates heartbeat-thread starvation
    # on oversubscribed hosts (a 10 s bound produced FALSE peer-death under
    # 1-core CPU contention during XLA compiles: the coordinator declared a
    # live, compiling peer dead).  Dedicated TPU hosts can set
    # --distributed_heartbeat_timeout_s=10 for the measured 25.7 s total
    # re-rendezvous (docs/perf.md).
    heartbeat_timeout_s: float = 30.0

    @property
    def enabled(self) -> bool:
        return self.num_processes > 1


_ACTIVE: Optional[DistributedSpec] = None


def initialize(spec: DistributedSpec) -> None:
    """Bring this process into the JAX distributed world.

    MUST run before the first JAX computation: ``jax.distributed.initialize``
    refuses once the PJRT backend exists, and the backend cannot be re-formed
    in-process.  ``worker.main`` therefore derives the spec from master
    membership over plain gRPC and calls this before constructing the Worker
    (whose first ``jax.devices()`` initializes the backend).  An elastic
    topology change requires a PROCESS RESTART — see
    ``worker.WorkerRestartRequired`` and the pod manager's budget-free
    RESTART relaunch path.

    Single-process specs are a no-op (local jax.devices() is already
    correct), so the same worker code runs one-host and multi-host.
    """
    global _ACTIVE
    if not spec.enabled:
        return
    if _ACTIVE == spec:
        return
    if _ACTIVE is not None:  # pragma: no cover - defensive; see docstring
        raise RuntimeError(
            "jax.distributed world already initialized with a different "
            "topology; an elastic change requires a worker process restart"
        )
    logger.info(
        "jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
        spec.coordinator_address, spec.num_processes, spec.process_id,
    )
    # Both via the compat shims: older jax (this image's 0.4.37) predates
    # the heartbeat_timeout_seconds kwarg (kept at the runtime default
    # instead of failing initialization) and defaults the CPU harness's
    # cross-process collectives to "none" (every cross-process psum would
    # fail) where newer jax defaults to gloo.
    enable_cpu_multiprocess_collectives()
    distributed_initialize(
        coordinator_address=spec.coordinator_address,
        num_processes=spec.num_processes,
        process_id=spec.process_id,
        heartbeat_timeout_seconds=max(int(spec.heartbeat_timeout_s), 1),
    )
    _ACTIVE = spec


def shutdown() -> None:
    global _ACTIVE
    if _ACTIVE is None:
        return
    try:
        jax.distributed.shutdown()
    except Exception:  # pragma: no cover - runtime may already be gone
        logger.exception("jax.distributed.shutdown failed")
    _ACTIVE = None


def advertised_address() -> str:
    """The host other workers can dial: pod IP (downward API) or FQDN."""
    import os
    import socket

    return os.environ.get("MY_POD_IP") or socket.getfqdn()


def active_spec() -> Optional[DistributedSpec]:
    return _ACTIVE


def spec_from_membership(
    membership: dict,
    worker_id: str,
    coordinator_port: int = 8476,
    heartbeat_timeout_s: float = 30.0,
) -> DistributedSpec:
    """Derive this worker's DistributedSpec from master membership.

    The membership dict carries ``ranks`` (worker_id -> rank) and
    ``addresses`` (worker_id -> host) when the pod backend populates them;
    rank 0's host seeds the coordinator.  Single-host deployments (no
    addresses) yield a disabled spec.
    """
    ranks = membership.get("ranks", {})
    addresses = membership.get("addresses", {})
    if not addresses or len(ranks) <= 1:
        return DistributedSpec("", 1, 0)
    rank0 = next((w for w, r in ranks.items() if r == 0), None)
    host0 = addresses.get(rank0)
    if host0 is None:
        return DistributedSpec("", 1, 0)
    return DistributedSpec(
        coordinator_address=f"{host0}:{coordinator_port}",
        num_processes=len(ranks),
        process_id=ranks.get(worker_id, 0),
        heartbeat_timeout_s=heartbeat_timeout_s,
    )
