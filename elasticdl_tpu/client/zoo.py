"""``elasticdl zoo init/build/push`` — model-zoo scaffolding and packaging.

Reference parity (SURVEY.md §2 #1 [U]): the reference's zoo verbs bake the
user's model directory into a docker image (init writes a template +
Dockerfile, build runs docker build, push pushes to a registry).  Same verbs
here; ``build`` additionally *validates* the zoo — imports every module and
checks each ``*model_spec*`` function returns a well-formed ``ModelSpec``
(cheap shape-level init check) — because on TPU the expensive artifact is a
correct jittable spec, not the image.  Docker steps degrade gracefully when
docker is unavailable (validation still runs).
"""

from __future__ import annotations

import importlib
import os
import shutil
import subprocess
import sys
from typing import Callable, Dict, List, Tuple

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.models.spec import ModelSpec

logger = get_logger("client.zoo")

_TEMPLATE_MODEL = '''\
"""Template ElasticDL-TPU model-zoo entry.

Train with:
    elasticdl train --model_zoo={zoo_pkg} --model_def=template.model_spec \\
        --training_data=... --minibatch_size=64
"""

import jax
import jax.numpy as jnp
import optax

from elasticdl_tpu.models.spec import ModelSpec


def model_spec(hidden: int = 64, num_classes: int = 10, lr: float = 1e-3):
    def init(rng):
        k1, k2 = jax.random.split(rng)
        return {{
            "dense1": {{
                "w": jax.random.normal(k1, (28 * 28, hidden)) * 0.05,
                "b": jnp.zeros((hidden,)),
            }},
            "dense2": {{
                "w": jax.random.normal(k2, (hidden, num_classes)) * 0.05,
                "b": jnp.zeros((num_classes,)),
            }},
        }}

    def apply(params, batch, train=False, ctx=None):
        x = batch["images"].reshape(batch["images"].shape[0], -1)
        x = jax.nn.relu(x @ params["dense1"]["w"] + params["dense1"]["b"])
        return x @ params["dense2"]["w"] + params["dense2"]["b"]

    def loss(logits, batch):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]
        ).mean()

    def metrics(logits, batch):
        return {{"accuracy": (logits.argmax(-1) == batch["labels"]).mean()}}

    def example_batch(n):
        return {{
            "images": jnp.zeros((n, 28, 28), jnp.float32),
            "labels": jnp.zeros((n,), jnp.int32),
        }}

    return ModelSpec(
        name="template",
        init=init,
        apply=apply,
        loss=loss,
        metrics=metrics,
        optimizer=optax.adam(lr),
        example_batch=example_batch,
    )
'''

_TEMPLATE_DOCKERFILE = """\
# Model-zoo image: framework + user models, run on GKE TPU node pools.
FROM {base_image}
COPY . /model_zoo
ENV PYTHONPATH=/model_zoo:$PYTHONPATH
"""

_TEMPLATE_REQUIREMENTS = """\
# Extra python deps for your models (installed into the zoo image).
"""


def zoo_init(directory: str, base_image: str = "elasticdl-tpu:latest") -> None:
    """Scaffold a model-zoo directory: template model, Dockerfile, requirements."""
    os.makedirs(directory, exist_ok=True)
    pkg = os.path.basename(os.path.abspath(directory))
    wrote = []
    for name, content in (
        ("__init__.py", ""),
        ("template.py", _TEMPLATE_MODEL.format(zoo_pkg=pkg)),
        ("Dockerfile", _TEMPLATE_DOCKERFILE.format(base_image=base_image)),
        ("requirements.txt", _TEMPLATE_REQUIREMENTS),
    ):
        path = os.path.join(directory, name)
        if os.path.exists(path):
            logger.info("keeping existing %s", path)
            continue
        with open(path, "w") as f:
            f.write(content)
        wrote.append(name)
    logger.info("initialized model zoo %s (wrote %s)", directory, wrote)


def discover_model_specs(
    directory: str,
) -> Tuple[Dict[str, Callable[..., ModelSpec]], List[Tuple[str, str]]]:
    """Import every module in the zoo dir; collect ``*model_spec*`` callables.

    Returns (specs, import_failures) — a broken module (syntax error, missing
    dependency) is reported per-module instead of aborting discovery.
    """
    directory = os.path.abspath(directory)
    parent, pkg = os.path.split(directory)
    specs: Dict[str, Callable[..., ModelSpec]] = {}
    failures: List[Tuple[str, str]] = []
    sys.path.insert(0, parent)
    try:
        for fname in sorted(os.listdir(directory)):
            if not fname.endswith(".py") or fname.startswith("_"):
                continue
            try:
                module = importlib.import_module(f"{pkg}.{fname[:-3]}")
            except Exception as e:  # noqa: BLE001 - report, keep discovering
                failures.append((fname, f"import failed: {e}"))
                continue
            for attr in dir(module):
                if "model_spec" in attr and callable(getattr(module, attr)):
                    specs[f"{fname[:-3]}.{attr}"] = getattr(module, attr)
    finally:
        sys.path.remove(parent)
    return specs, failures


def validate_zoo(directory: str) -> List[Tuple[str, str]]:
    """Build every spec and run a cheap abstract init; returns (name, error)s."""
    import jax

    specs, failures = discover_model_specs(directory)
    if not specs and not failures:
        return [(directory, "no *model_spec* functions found")]
    for name, fn in specs.items():
        try:
            spec = fn()
            if not isinstance(spec, ModelSpec):
                raise TypeError(f"returned {type(spec).__name__}, not ModelSpec")
            # Shape-level init: catches most wiring bugs without device work.
            jax.eval_shape(spec.init, jax.random.key(0))
            logger.info("validated %s (%s)", name, spec.name)
        except Exception as e:  # noqa: BLE001 - report all validation errors
            failures.append((name, str(e)))
    return failures


def zoo_build(
    directory: str, image: str = "", validate_only: bool = False
) -> int:
    """Validate the zoo; then (if requested and possible) docker-build it."""
    failures = validate_zoo(directory)
    for name, err in failures:
        logger.error("zoo validation failed: %s: %s", name, err)
    if failures:
        return 1
    if validate_only or not image:
        return 0
    if shutil.which("docker") is None:
        logger.error("docker not found; ran validation only")
        return 1
    return subprocess.call(["docker", "build", "-t", image, directory])


def zoo_push(image: str) -> int:
    """``docker push`` the built zoo image to its registry."""
    if shutil.which("docker") is None:
        logger.error("docker not found; cannot push %s", image)
        return 1
    return subprocess.call(["docker", "push", image])
