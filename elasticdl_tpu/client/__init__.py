"""Client layer — the ``elasticdl`` command.

Reference parity (SURVEY.md §2 #1 [U — mount empty at survey time; the
``elasticdl`` CLI name and its train/evaluate/predict + zoo verbs are [D]
via BASELINE.json): the reference's ``elasticdl_client`` package is the
user-facing console command that bakes model-zoo docker images
(``zoo init/build/push``) and submits jobs (``train/evaluate/predict``) by
rendering a master pod spec and creating it through the Kubernetes API.

TPU rebuild: same verbs, two deployment modes:

- **local** (default when no cluster flags given): run the master
  in-process; workers are subprocesses via ``ProcessPodBackend``.  This is
  also the single-host TPU mode — one v5e host drives all its chips.
- **cluster**: render the master pod manifest (GKE TPU node-pool selectors,
  ``google.com/tpu`` resources) and submit it with the kubernetes client if
  installed, else write the manifest for ``kubectl apply``.
"""

from elasticdl_tpu.client.api import (
    evaluate,
    predict,
    render_master_pod_manifest,
    submit,
    train,
)
from elasticdl_tpu.client.zoo import zoo_build, zoo_init, zoo_push

__all__ = [
    "train",
    "evaluate",
    "predict",
    "submit",
    "render_master_pod_manifest",
    "zoo_init",
    "zoo_build",
    "zoo_push",
]
