"""``elasticdl`` console entry point.

Reference parity (SURVEY.md §2 #1, §3.1): the reference CLI's verb surface —
``zoo init|build|push`` and ``train|evaluate|predict`` — with the job flags
shared with master/worker through the one ``JobConfig`` flag set
(``common.config.build_arg_parser``), exactly the reference's
client-validates/master-re-parses layering.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from elasticdl_tpu.client import api, zoo
from elasticdl_tpu.common.config import JobConfig, build_arg_parser


def _add_cluster_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--local",
        action="store_true",
        default=None,
        help="run master+workers on this host (default unless --image given)",
    )
    parser.add_argument("--image", default="", help="framework+zoo image for pods")
    parser.add_argument(
        "--manifest_out",
        default="",
        help="write the master pod manifest here instead of submitting",
    )


def _job_parser(prog: str) -> argparse.ArgumentParser:
    # Job flags come from the shared JobConfig parser; cluster flags are
    # client-only and stripped before the config is built.
    parser = build_arg_parser()
    parser.prog = prog
    _add_cluster_flags(parser)
    return parser


def _run_job(verb: str, argv: List[str]) -> int:
    ns = vars(_job_parser(f"elasticdl {verb}").parse_args(argv))
    cluster = {
        "local": ns.pop("local"),
        "image": ns.pop("image"),
        "manifest_out": ns.pop("manifest_out"),
    }
    if cluster["local"] is None:
        cluster["local"] = not (cluster["image"] or cluster["manifest_out"])
    config = JobConfig(**ns)
    if cluster["image"]:
        config.worker_image = cluster["image"]
    cluster["namespace"] = config.namespace
    if not cluster["local"]:
        config.pod_backend = "kubernetes"
    return {"train": api.train, "evaluate": api.evaluate, "predict": api.predict}[
        verb
    ](config, **cluster)


def _run_zoo(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(prog="elasticdl zoo")
    sub = parser.add_subparsers(dest="verb", required=True)
    p_init = sub.add_parser("init", help="scaffold a model-zoo directory")
    p_init.add_argument("directory", nargs="?", default=".")
    p_init.add_argument("--base_image", default="elasticdl-tpu:latest")
    p_build = sub.add_parser("build", help="validate (and docker-build) a zoo")
    p_build.add_argument("directory", nargs="?", default=".")
    p_build.add_argument("--image", default="")
    p_build.add_argument("--validate_only", action="store_true")
    p_push = sub.add_parser("push", help="push a built zoo image")
    p_push.add_argument("image")
    ns = parser.parse_args(argv)
    if ns.verb == "init":
        zoo.zoo_init(ns.directory, base_image=ns.base_image)
        return 0
    if ns.verb == "build":
        return zoo.zoo_build(ns.directory, image=ns.image, validate_only=ns.validate_only)
    return zoo.zoo_push(ns.image)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    verbs = ("train", "evaluate", "predict", "zoo")
    if not argv or argv[0] in ("-h", "--help") or argv[0] not in verbs:
        print(
            "usage: elasticdl {train|evaluate|predict|zoo} [flags]\n"
            "  train/evaluate/predict: submit or locally run a job "
            "(see --help of each)\n"
            "  zoo {init|build|push}: scaffold/validate/package a model zoo",
            file=sys.stderr,
        )
        return 0 if argv and argv[0] in ("-h", "--help") else 2
    if argv[0] == "zoo":
        return _run_zoo(argv[1:])
    return _run_job(argv[0], argv[1:])


if __name__ == "__main__":
    sys.exit(main())
