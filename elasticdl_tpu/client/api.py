"""Job submission API behind the ``elasticdl`` CLI.

Reference parity (SURVEY.md §3.1 [U]): the reference client validates args,
renders a master pod spec (image, command = master main, job config in
args/env), and creates the pod via the Kubernetes API; everything after that
(worker/PS fleet) is the master's job.  Here the config bus is
``ELASTICDL_JOB_CONFIG`` (see ``common.config``), so the master manifest just
carries that one env var.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("client.api")


def render_master_pod_manifest(
    config: JobConfig,
    image: str = "elasticdl-tpu:latest",
    extra_env: Optional[Dict[str, str]] = None,
) -> dict:
    """A Kubernetes V1Pod-shaped dict for the job's master.

    The master is control-plane only (task dispatch, rendezvous, pod
    management) — it requests no TPU and can land on any CPU node.  It
    creates the TPU worker pods itself (see
    ``master.pod_manager.render_worker_pod_manifest``).
    """
    from elasticdl_tpu.master.pod_manager import render_base_pod_manifest

    env = dict(config.to_env())
    env.update(extra_env or {})
    manifest = render_base_pod_manifest(
        config.job_name,
        f"{config.job_name}-master",
        "master",
        image,
        ["python", "-m", "elasticdl_tpu.master.main"],
        env,
    )
    # Control-plane only: no TPU, any CPU node; needs pod create/watch RBAC.
    manifest["spec"]["serviceAccountName"] = "elasticdl-master"
    manifest["spec"]["containers"][0]["resources"] = {
        "requests": {"cpu": "1", "memory": "2Gi"},
    }
    return manifest


def submit(
    config: JobConfig,
    image: str = "elasticdl-tpu:latest",
    namespace: str = "default",
    manifest_out: str = "",
) -> dict:
    """Submit the master pod to a cluster (or emit its manifest).

    Returns the rendered manifest.  With the ``kubernetes`` package
    installed the pod is created; otherwise the manifest is written to
    ``manifest_out`` (or logged) for ``kubectl apply -f``.
    """
    config.validate()
    manifest = render_master_pod_manifest(config, image=image)
    if manifest_out:
        with open(manifest_out, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
        logger.info("wrote master pod manifest to %s", manifest_out)
        return manifest
    try:
        import kubernetes  # type: ignore
    except ImportError:
        raise SystemExit(
            "the 'kubernetes' package is not installed; re-run with "
            "--manifest_out=master.json and `kubectl apply -f` it, or use "
            "--local to run on this host"
        )
    kubernetes.config.load_kube_config()  # pragma: no cover - needs cluster
    core = kubernetes.client.CoreV1Api()  # pragma: no cover
    core.create_namespaced_pod(namespace, manifest)  # pragma: no cover
    logger.info(  # pragma: no cover
        "submitted master pod %s", manifest["metadata"]["name"]
    )
    return manifest  # pragma: no cover


def _run_local(config: JobConfig) -> int:
    """Run the whole job on this host: in-process master, subprocess workers.

    Single-host TPU deployment (one v5e host drives all local chips) and the
    default when no cluster flags are given — the reference has no strict
    equivalent (its Local strategy skips the master entirely); keeping the
    master in the loop preserves dynamic sharding + elasticity locally.
    """
    from elasticdl_tpu.master.main import Master

    status = Master(config).run()
    return 0 if not status.get("abandoned") else 1


def _run(config: JobConfig, job_type: str, **cluster) -> int:
    config.job_type = job_type
    config.validate()
    if cluster.get("local", True):
        return _run_local(config)
    submit(
        config,
        image=cluster.get("image") or "elasticdl-tpu:latest",
        namespace=cluster.get("namespace") or "default",
        manifest_out=cluster.get("manifest_out") or "",
    )
    return 0


def train(config: JobConfig, **cluster) -> int:
    return _run(config, "training", **cluster)


def evaluate(config: JobConfig, **cluster) -> int:
    return _run(config, "evaluation", **cluster)


def predict(config: JobConfig, **cluster) -> int:
    return _run(config, "prediction", **cluster)
