"""graftchaos — scheduled fault injection, one injector per process.

The repo can MEASURE a straggler (grafttrace, r12) but could not CREATE
one on demand: every tail-tolerance claim (deadline-bounded gang boundary,
warm-standby splice-in, recovery time) was only testable by hoping real
hardware misbehaved on cue.  This module is the supply side — a
stdlib-only fault injector cheap enough to ride in every process, whose
scheduled faults (kill a rank at a step, stall a prep, drop an RPC, delay
a PS pull) turn "the gang survives churn" into a benchable, CI-checkable
property (tools/chaos_bench.py; docs/robustness.md).

Design constraints, in order (grafttrace's, deliberately):

- **Hot-path safe when disabled.**  The hook points live inside
  ``# hot-path`` functions (the worker task loop, ``JsonRpcClient.call``,
  the PS pull).  Disabled (the default), the module-level ``hook()`` is
  one attribute check and a return — the ``chaos-discipline`` lint rule
  enforces that call sites use exactly this no-op-when-disabled API, the
  ring-API twin of trace-discipline.
- **Stdlib only.**  The injector rides in the master control plane and
  the jax-free bench tools (graftlint import-hygiene covers the package:
  ``common/rpc.py`` imports it, and the master imports rpc).
- **Attributable.**  Every fired fault emits a ``chaos:*`` trace instant
  (common/trace.py) so injected faults are first-class events in the
  merged cross-process trace — a recovery timeline where the FAULT is
  invisible cannot be decomposed.  (A ``kill`` dies before its buffer
  ships; the master-side pod-failure ``elastic:splice`` detect instant is
  the measured t0 for kills — see docs/robustness.md.)

Plan syntax (``GRAFT_CHAOS`` env var / ``--chaos`` JobConfig flag;
semicolon-separated faults, comma-separated ``key=value`` args)::

    kill:rank=1,step=4
    kill:worker=job-worker-1,step=4      # exact id: relaunched
                                         # incarnations (-rN names) do
                                         # NOT re-match, so a kill cannot
                                         # crash-loop its own relaunch
    kill:target=master,step=3            # r18: kill the MASTER once its
                                         # dispatcher has counted step=N
                                         # done tasks (the master:report
                                         # hook in the servicer) — the
                                         # masterfail bench's crash.
                                         # Default target is the worker.
    stall:rank=0,point=prep,step=2,ms=500,count=2
    stall:rank=0,point=collective,shard=1,ms=2000   # stall ONE dp
                                         # shard's contribution at the
                                         # r15 in-step gate (shard= only
                                         # applies here)
    delay_rpc:method=GetTask,ms=100,count=3
    drop_rpc:method=Heartbeat,count=2,skip=5
    delay_ps:ms=50,count=4
    torn_write:file=master_journal.wal,op=3  # r21: crash THIS process at
                                         # its 4th durable op on that
                                         # file (op= is the per-file
                                         # 0-based index, exact match),
                                         # leaving the on-disk state a
                                         # real mid-op death leaves
                                         # (common/crashsan.py produces
                                         # it; mode= picks which —
                                         # default torn_append for
                                         # appends, tmp_torn for
                                         # publishes), then os._exit.

Fault kinds -> hook points (the wire contract with the call sites):

    kill       worker:task            os._exit(CHAOS_KILL_EXIT_CODE)
               master:report          (target=master only; fires in the
                                      servicer after a report is applied
                                      AND journaled — the hardest crash
                                      point for exactly-once)
    stall      worker:{task,prep,step,collective}  time.sleep(ms)
    delay_rpc  rpc:client             time.sleep(ms) before the send
    drop_rpc   rpc:client             raise ChaosRpcDropped (the caller
                                      sees a failed RPC, exactly as a
                                      lossy network would present one)
    delay_ps   ps:pull                time.sleep(ms) in the PS handler
    torn_write durable:write          crashsan produces the exact on-disk
                                      crash state, then
                                      os._exit(CHAOS_KILL_EXIT_CODE).
                                      NOT a hook() crossing: synced into
                                      crashsan at configure time and
                                      matched at the durable op itself —
                                      durable ops fire under leaf
                                      subsystem locks, where the
                                      injector's lock may not be taken

Match conditions: ``rank=``/``worker=`` against the process context
(``set_context``, updated by the worker on every membership apply),
``step=`` fires once the context step reaches it, ``method=``/``point=``
select call sites, ``skip=`` ignores the first N matching occurrences and
``count=`` bounds total fires (0 = unlimited).  The worker hooks refresh
a per-process step mirror as they cross, so ``step=`` gates rpc faults
too: ``drop_rpc:worker=job-worker-0,step=5,count=0`` blacks out that
rank's RPCs from step 5 on while leaving its join path untouched.  A key
a kind could never match (``method=`` on a stall, ``rank=``/``step=`` on
``delay_ps`` — the PS shard has neither) is a parse error, not a fault
that silently never fires (see ``_KIND_KEYS``).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, List, Optional

from elasticdl_tpu.common import locksan, trace

#: Exit code of a chaos ``kill``: anything other than 0 and the worker's
#: RESTART code (3) maps to a FAILED pod event, so an injected kill charges
#: the slot's relaunch budget exactly as a real crash would — chaos must
#: exercise the REAL failure path, not a polite imitation of it.
CHAOS_KILL_EXIT_CODE = 9


class ChaosError(ValueError):
    """A malformed chaos plan (fail at configure time, not mid-job)."""


class ChaosRpcDropped(RuntimeError):
    """An injected RPC drop: the call site sees a failed RPC."""


#: kind -> hook points it may fire at.
_KIND_POINTS = {
    "kill": ("worker:task", "master:report"),
    "stall": (
        "worker:task", "worker:prep", "worker:step", "worker:collective",
    ),
    "delay_rpc": ("rpc:client",),
    "drop_rpc": ("rpc:client",),
    "delay_ps": ("ps:pull",),
    # torn_write's "point" is the durable-op crossing in common/durable.py,
    # reached via crashsan.set_torn_plan at configure time — hook() never
    # carries it (see _sync_torn_plan), so matches() never sees this kind.
    "torn_write": ("durable:write",),
}

#: Keys each fault KIND accepts (typo'd plans must fail loud at parse —
#: and so must a key the kind would silently ignore: ``method=`` on a
#: stall or ``point=`` on an rpc fault parses into a match condition no
#: hook context can ever satisfy, i.e. a fault that never fires).
#: ``delay_ps`` takes no identity/step keys: the PS shard process has no
#: worker rank and no step mirror, so those conditions could never match.
_KIND_KEYS = {
    "kill": {"rank", "worker", "step", "count", "skip", "target"},
    "stall": {
        "rank", "worker", "step", "point", "shard", "ms", "count", "skip",
    },
    "delay_rpc": {"rank", "worker", "step", "method", "ms", "count", "skip"},
    "drop_rpc": {"rank", "worker", "step", "method", "count", "skip"},
    "delay_ps": {"ms", "count", "skip"},
    # torn_write addresses a durable FILE and its per-file op index, not a
    # worker identity: durable ops fire in whichever process owns the file
    # (master WAL/registry, worker checkpoint manifests), and rank/step
    # conditions could never match the master's crossings.
    "torn_write": {"file", "op", "mode", "count", "skip"},
}


@dataclasses.dataclass
class ChaosFault:
    """One scheduled fault plus its firing state."""

    kind: str
    rank: Optional[int] = None
    worker: str = ""
    step: int = 0
    point: str = ""
    shard: Optional[int] = None
    method: str = ""
    ms: float = 0.0
    count: int = 1
    skip: int = 0
    # kill only: which PROCESS dies.  "" / "worker" = the worker task
    # boundary (pre-r18 behavior); "master" = the servicer's report hook.
    target: str = ""
    # torn_write only: which durable file (basename), which of its ops
    # (per-file 0-based index, EXACT match — unlike step=, a crash point
    # is one op, not "from op N on"), and which crash mode
    # (crashsan.ALL_MODES; "" picks the kind's torn default).
    file: str = ""
    op: Optional[int] = None
    mode: str = ""
    # firing state — guarded by the injector's lock
    seen: int = 0
    fired: int = 0

    def matches(self, point: str, ctx: Dict[str, Any]) -> bool:
        if point not in _KIND_POINTS[self.kind]:
            return False
        if self.kind == "kill":
            # A kill binds to ONE process family: target=master fires
            # only at the servicer's report hook, the default only at the
            # worker task boundary — a plan must never kill both.
            wanted = (
                "master:report" if self.target == "master" else "worker:task"
            )
            if point != wanted:
                return False
        if self.kind == "stall":
            # A stall binds to ONE worker hook point (default: the step
            # dispatch) — "stall the prep" and "stall the step" are
            # different experiments.
            if point != f"worker:{self.point or 'step'}":
                return False
        if self.method and ctx.get("method") != self.method:
            return False
        if self.file and ctx.get("file") != self.file:
            return False
        if self.op is not None and ctx.get("op") != self.op:
            return False
        if self.shard is not None and ctx.get("shard") != self.shard:
            return False
        if self.rank is not None and ctx.get("rank") != self.rank:
            return False
        if self.worker and ctx.get("worker_id") != self.worker:
            return False
        if self.step and int(ctx.get("step") or 0) < self.step:
            return False
        return True


def parse_plan(spec: str) -> List[ChaosFault]:
    """Parse a ``GRAFT_CHAOS`` plan string; raises ChaosError naming the
    offending entry (a typo'd fault silently never firing would make a
    chaos run report tolerance that was never exercised)."""
    faults: List[ChaosFault] = []
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        kind, _, argstr = entry.partition(":")
        kind = kind.strip()
        if kind not in _KIND_POINTS:
            raise ChaosError(
                f"unknown chaos fault kind {kind!r} in {entry!r} "
                f"(known: {sorted(_KIND_POINTS)})"
            )
        kwargs: Dict[str, Any] = {}
        for item in filter(None, (a.strip() for a in argstr.split(","))):
            if "=" not in item:
                raise ChaosError(f"malformed chaos arg {item!r} in {entry!r}")
            key, value = (s.strip() for s in item.split("=", 1))
            if key not in _KIND_KEYS[kind]:
                raise ChaosError(
                    f"chaos arg {key!r} does not apply to {kind!r} in "
                    f"{entry!r} (accepted: {sorted(_KIND_KEYS[kind])})"
                )
            if key in ("rank", "step", "count", "skip", "shard", "op"):
                kwargs[key] = int(value)
            elif key == "ms":
                kwargs[key] = float(value)
            else:
                kwargs[key] = value
        fault = ChaosFault(kind=kind, **kwargs)
        if fault.kind in ("stall", "delay_rpc", "delay_ps") and fault.ms <= 0:
            raise ChaosError(f"{entry!r} needs ms=<positive duration>")
        if fault.point and fault.point not in (
            "task", "prep", "step", "collective"
        ):
            raise ChaosError(
                f"{entry!r}: point must be task|prep|step|collective, got "
                f"{fault.point!r}"
            )
        if fault.target and fault.target not in ("worker", "master"):
            raise ChaosError(
                f"{entry!r}: target must be worker|master, got "
                f"{fault.target!r}"
            )
        if fault.target == "master" and (fault.rank is not None or fault.worker):
            # The master has neither rank nor worker id: such a condition
            # could never match — a fault that silently never fires (the
            # parse-error stance).
            raise ChaosError(
                f"{entry!r}: rank=/worker= do not apply to target=master"
            )
        if fault.kind == "torn_write":
            from elasticdl_tpu.common import crashsan

            if fault.mode and fault.mode not in crashsan.ALL_MODES:
                # A typo'd mode would fall back to the default and report
                # tolerance for a crash shape that was never produced.
                raise ChaosError(
                    f"{entry!r}: mode must be one of "
                    f"{', '.join(crashsan.ALL_MODES)}, got {fault.mode!r}"
                )
            if os.sep in fault.file:
                # Matching is by basename (the hook's ctx); a path could
                # never match — a fault that silently never fires.
                raise ChaosError(
                    f"{entry!r}: file= takes the durable file's basename, "
                    f"not a path"
                )
            if fault.op is not None and fault.op < 0:
                raise ChaosError(f"{entry!r}: op= must be >= 0")
        if fault.shard is not None and fault.point != "collective":
            # shard= addresses one dp contributor crossing the r15
            # collective gate; no other hook point carries a shard, so
            # the condition could never match — a fault that silently
            # never fires (the parse-error stance above).
            raise ChaosError(
                f"{entry!r}: shard= applies only to point=collective"
            )
        faults.append(fault)
    return faults


def _sync_torn_plan(plan: List[ChaosFault]) -> None:
    """Hand the plan's torn_write faults to crashsan, which owns their
    matching and firing at the durable-op crossing.  torn_write is the
    one fault kind that does NOT route through ``hook``/``fire``: durable
    ops cross under leaf-declared subsystem locks (the master journal
    appends under TaskDispatcher._lock), where acquiring the injector's
    locksan-wrapped lock would be a lock-order violation — crashsan's
    plain leaf lock is the only one that crossing may take."""
    from elasticdl_tpu.common import crashsan

    crashsan.set_torn_plan([
        {
            "file": f.file, "op": f.op, "mode": f.mode,
            "count": f.count, "skip": f.skip,
        }
        for f in plan
        if f.kind == "torn_write"
    ])


class ChaosInjector:
    """The per-process fault schedule plus its firing state.

    ``fire`` is only reached when the module-level ``hook`` saw
    ``enabled`` — the disabled hot path never enters this class.  Firing
    state mutates under a leaf lock (hooks run on task-loop, prep-pool,
    gRPC-handler and PS threads at once); the fault ACTIONS (sleep, raise,
    exit) run outside it.
    """

    def __init__(self, plan: Optional[List[ChaosFault]] = None):
        self.enabled = bool(plan)
        self._plan: List[ChaosFault] = list(plan or [])
        self._lock = locksan.lock("ChaosInjector._lock", leaf=True)  # lock-order: leaf
        self._ctx: Dict[str, Any] = {}  # guarded-by: _lock
        _sync_torn_plan(self._plan)

    # test seam: a kill must be observable without killing the test runner
    _exit = staticmethod(os._exit)

    def set_context(self, **ctx: Any) -> None:
        """Merge process identity (rank, worker_id) into the match context.
        The worker calls this on every membership apply — ranks shift
        across reforms, and a rank-addressed fault must follow them."""
        with self._lock:
            self._ctx.update(ctx)

    def configure(self, spec: str = "", plan: Optional[List[ChaosFault]] = None) -> None:
        """(Re)arm the injector from a plan string or a parsed plan;
        empty disables.  Firing state resets — reconfiguring IS a new
        experiment."""
        if plan is None:
            plan = parse_plan(spec) if spec else []
        with self._lock:
            self._plan = list(plan)
            self.enabled = bool(self._plan)
        # Outside the lock: crashsan's plain lock orders below nothing.
        _sync_torn_plan(plan)

    def stats(self) -> List[dict]:
        """Per-fault seen/fired counters (the bench's injection audit)."""
        with self._lock:
            return [dataclasses.asdict(f) for f in self._plan]

    def fire(self, point: str, ctx: Dict[str, Any]) -> None:
        """Match + fire every armed fault for this hook crossing.  The
        decision runs under the lock; the ACTION (sleep/raise/exit) runs
        outside it so a long stall never serializes other threads' hooks."""
        due: List[ChaosFault] = []
        with self._lock:
            # Persist the worker's step mirror: task/prep/step hooks carry
            # ``step`` per crossing, the rpc hooks do not — remembering
            # the last seen value lets ``step=`` gate the worker-process
            # fault kinds ("black out this rank's RPCs once it reaches
            # step N"), which is how the chaos bench severs a skipped
            # straggler without touching its join path.
            if ctx.get("step") is not None:
                self._ctx["step"] = ctx["step"]
            merged = dict(self._ctx)
            merged.update(ctx)
            for f in self._plan:
                if not f.matches(point, merged):
                    continue
                f.seen += 1
                if f.seen <= f.skip:
                    continue
                if f.count > 0 and f.fired >= f.count:
                    continue
                f.fired += 1
                due.append(f)
        for f in due:
            self._apply(f, point, merged)

    def _apply(self, fault: ChaosFault, point: str, ctx: Dict[str, Any]) -> None:
        # The instant FIRST: a fault that raises or exits must still be
        # attributable in whatever trace window survives.  The stderr
        # line is the audit of last resort: a kill's ring dies with its
        # process and a blacked-out (drop_rpc) process can never ship
        # its ring over a heartbeat — the pod LOG is the one channel a
        # severed process still writes, and chaos_bench counts these
        # lines as its injection audit.
        trace.instant(
            f"chaos:{fault.kind}", cat="chaos", point=point,
            ms=fault.ms, rank=ctx.get("rank"), method=ctx.get("method"),
            step=ctx.get("step"), shard=ctx.get("shard"), fired=fault.fired,
            file=ctx.get("file"), op=ctx.get("op"),
        )
        import sys

        print(
            f"[graftchaos] {fault.kind} at {point} (ctx={ctx})",
            file=sys.stderr, flush=True,
        )
        if fault.kind == "kill":
            # os._exit, not sys.exit: a real crash skips interpreter
            # teardown, and the whole point is to exercise the REAL
            # failure path (pod watcher -> FAILED -> relaunch/splice).
            self._exit(CHAOS_KILL_EXIT_CODE)
        elif fault.kind in ("stall", "delay_rpc", "delay_ps"):
            # The injected stall IS the fault under test — hot-path
            # discipline is owned by the disabled-mode no-op, not here.
            # graftlint: allow[hot-path-sync] the injected stall IS the fault; disabled mode never reaches this
            time.sleep(fault.ms / 1e3)
        elif fault.kind == "drop_rpc":
            raise ChaosRpcDropped(
                f"chaos: dropped RPC {ctx.get('method')!r} "
                f"(fault fired {fault.fired}/{fault.count or 'inf'})"
            )
        # torn_write never reaches fire: it is synced into crashsan at
        # configure time (_sync_torn_plan) and fires at the durable-op
        # crossing itself, under crashsan's plain leaf lock.


# -- the process-global injector -------------------------------------------

#: One injector per process.  GRAFT_CHAOS arms it at import (subprocess
#: workers/PS pods inherit the env); ``configure()`` arms it
#: programmatically (the --chaos job flag via the config bus, tests).
_INJ = ChaosInjector(
    parse_plan(os.environ.get("GRAFT_CHAOS", ""))
    if os.environ.get("GRAFT_CHAOS")
    else None
)


def default() -> ChaosInjector:
    return _INJ


def enabled() -> bool:
    return _INJ.enabled


def configure(spec: str = "", plan: Optional[List[ChaosFault]] = None) -> None:
    _INJ.configure(spec, plan)


def set_context(**ctx: Any) -> None:
    _INJ.set_context(**ctx)


def hook(point: str, **ctx: Any) -> None:
    """The one hot-path-legal entry point (chaos-discipline): a single
    attribute check when disabled, the full match/fire only when a plan
    is armed."""
    if not _INJ.enabled:
        return
    _INJ.fire(point, ctx)
