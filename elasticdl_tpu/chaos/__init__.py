"""graftchaos — repo-native fault injection (see chaos/inject.py).

Import as ``from elasticdl_tpu import chaos`` and call the module helpers;
hot-path call sites use ``chaos.hook(...)`` only (the no-op-when-disabled
API the ``chaos-discipline`` lint rule enforces).
"""

from elasticdl_tpu.chaos.inject import (  # noqa: F401
    CHAOS_KILL_EXIT_CODE,
    ChaosError,
    ChaosFault,
    ChaosInjector,
    ChaosRpcDropped,
    configure,
    default,
    enabled,
    hook,
    parse_plan,
    set_context,
)
