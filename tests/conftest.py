"""Test harness: 8 fake CPU devices, mirroring the reference's no-cluster test
strategy (mock k8s + in-process master/worker — SURVEY.md §4) with JAX's
equivalent: XLA host-platform device multiplexing.

Must set the env vars BEFORE jax initializes its backends, hence this module
does it at import time (conftest is imported before any test module).
"""

import os

# Force-override: the image's sitecustomize registers the tunneled real-TPU
# "axon" PJRT plugin at interpreter start and sets jax_platforms="axon,cpu",
# which overrides the JAX_PLATFORMS env var.  Tests must run on fake CPU
# devices (fast, 8-wide), so set XLA flags before backend init AND push the
# config back to cpu after jax import.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

# Runtime lock-order sanitizer (common/locksan.py) ON for the whole tier-1
# suite: every threaded path (worker task loop, servicer gRPC pool, PS
# handlers, pod-manager watchers — and their subprocess workers, which
# inherit the env) runs with acquisition-order assertions against the
# static '# lock-order:' declarations graftlint checks.  setdefault so a
# developer can force it off with GRAFT_LOCKSAN=0.
os.environ.setdefault("GRAFT_LOCKSAN", "1")

# Runtime shared-state sanitizer (common/racesan.py) ON for the whole
# tier-1 suite, the locksan pattern: opted-in control-plane classes record
# per-attribute (thread-role, held-locks) observations and raise on a
# cross-role unguarded write — the dynamic twin of graftlint's v5
# shared-state pass.  Must be set before the opted-in classes are
# imported (the decorator reads it at class-creation time).
os.environ.setdefault("GRAFT_RACESAN", "1")

# Runtime jit-compile sanitizer (common/jitsan.py) ON for the whole
# tier-1 suite — the dynamic twin of graftlint's v6 jit-discipline
# passes: every jax_compat.jit_compiled/jit_donating callable counts its
# XLA lowerings and raises deterministically past its declared
# expected_variants budget, so the entire suite PROVES the train step
# compiles exactly once after warmup (mask flips and elastic reforms add
# zero recompiles).  setdefault so GRAFT_JITSAN=0 forces it off; the
# stricter GRAFT_JITSAN_TRANSFER_GUARD stays opt-in (compilation itself
# may move constants).
os.environ.setdefault("GRAFT_JITSAN", "1")

# Runtime durability sanitizer (common/crashsan.py) ON for the whole
# tier-1 suite — the dynamic twin of graftlint's v7 durability passes:
# every durable-write crossing (common/durable.py append/publish/replace)
# is counted and indexed per file, so crash_at(op, mode) matrices and the
# chaos grammar's torn_write faults can target exact crossings.  Recording
# is one locked counter bump per durable op — noise next to the fsync the
# op itself pays.  setdefault so GRAFT_CRASHSAN=0 forces it off.
os.environ.setdefault("GRAFT_CRASHSAN", "1")

# Runtime wire-schema sanitizer (common/wiresan.py) ON for the whole
# tier-1 suite — the dynamic twin of graftlint's v8 wire passes: every
# request AND response crossing JsonRpcClient.call / make_generic_handler
# is validated against its MessageSchema (missing/mistyped fields raise
# deterministically; unknown fields are counted per method — the
# additive-compat stance).  The armed cost is one dict scan per message,
# noise next to the JSON serialization the call already pays.  setdefault
# so GRAFT_WIRESAN=0 forces it off; the version mask
# (GRAFT_WIRESAN_MASK / wiresan.set_mask) stays opt-in per test.
os.environ.setdefault("GRAFT_WIRESAN", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 fake devices, got {len(devs)}"
    return devs
