"""Elasticity: the BASELINE.json config #5 scenario ("worker preemption +
scale 4->8->4 during DeepFM training") on 8 fake devices, plus topology-
crossing checkpoint restore — the reference's chaos-style integration tests
(SURVEY.md §4) in-process."""

import os

import jax
import numpy as np
import pytest

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.data.synthetic import generate
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.trainer import Trainer
from elasticdl_tpu.worker.worker import DirectMasterProxy, Worker

DEEPFM_TINY = dict(
    compute_dtype="float32", buckets_per_feature=64, hidden=(16,)
)


def _deepfm_job(tmp_path, n_records=192, records_per_task=32, **cfg):
    data = str(tmp_path / "criteo.txt")
    generate("criteo", data, n_records)
    config = JobConfig(
        model_def="deepfm.model_spec",
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        training_data=data,
        minibatch_size=16,
        **cfg,
    )
    reader = create_data_reader(data)
    servicer = MasterServicer(
        TaskDispatcher(reader.create_shards(records_per_task))
    )
    spec = load_model_spec("elasticdl_tpu.models", "deepfm.model_spec", **DEEPFM_TINY)
    return config, servicer, reader, spec


def test_scale_4_8_4_mid_training(tmp_path, devices):
    """Phantom workers join then leave mid-job; the surviving worker re-forms
    its mesh 4 -> 8 -> 4 and training completes with every task done.
    lease_batch=1 keeps the GetTask-call counter a per-task schedule (the
    orchestration below injects membership events by call count); the
    unbatched wire shape stays a supported config."""
    config, servicer, reader, spec = _deepfm_job(tmp_path, lease_batch=1)
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices, devices_per_worker=4,
    )

    # Orchestrate membership changes from inside the task loop: after task 2
    # a phantom worker joins (4->8 devices); after task 4 it leaves (8->4).
    orig_get_task = servicer.GetTask
    counter = {"n": 0}

    def get_task_with_events(req):
        counter["n"] += 1
        if counter["n"] == 3:
            servicer.rendezvous.register("phantom")
        elif counter["n"] == 5:
            servicer.rendezvous.remove("phantom")
        return orig_get_task(req)

    servicer.GetTask = get_task_with_events

    result = worker.run()
    assert result["reforms"] == 2
    assert servicer.dispatcher.finished()
    assert servicer.JobStatus({})["done"] == 6
    assert result["step"] == 12  # 192 records / 16: no step lost or repeated


def test_worker_death_loses_no_data(tmp_path, devices):
    """A worker dies holding an in-flight task; after the master evicts it,
    a replacement worker completes every shard."""
    config, servicer, reader, spec = _deepfm_job(tmp_path, n_records=128)

    class DyingWorker(Worker):
        def _dispatch_training_task(self, task, prep=None):
            if self.worker_id == "w-doomed" and task.task_id >= 1:
                raise KeyboardInterrupt("preempted")  # dies mid-task
            return super()._dispatch_training_task(task, prep=prep)

    doomed = DyingWorker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w-doomed", spec=spec, devices=devices, devices_per_worker=4,
    )
    with pytest.raises(KeyboardInterrupt):
        doomed.run()
    status = servicer.JobStatus({})
    # Four tasks in flight at death under the prep-ahead pipeline with
    # batched leases (lease_batch default covers all 4 shards in one RPC):
    # task 0 (dispatched, died before its deferred report), task 1 (died
    # during dispatch), task 2 (prepped on the background pool, never
    # started), task 3 (leased, still buffered).  ALL requeue on eviction —
    # the lease entered `doing` at hand-out, so worker loss invalidates it
    # through the same recover_tasks path as in-flight work.  At-least-once
    # semantics, nothing lost.
    assert status["doing"] == 4

    # Master notices the death (here: pod event / heartbeat timeout path).
    servicer.rendezvous.remove("w-doomed")
    assert servicer.JobStatus({})["doing"] == 0  # requeued

    survivor = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w-live", spec=spec, devices=devices, devices_per_worker=4,
    )
    survivor.run()
    status = servicer.JobStatus({})
    assert status["finished"] and status["done"] == 4
    # 128 records / 16 per batch = 8 steps of work observable on the
    # survivor side alone is < 8 only because the doomed worker did task 0;
    # the requeued shard was re-run — at-least-once, nothing lost.
    assert status["todo"] == 0 and status["doing"] == 0


def test_checkpoint_restores_across_mesh_sizes(tmp_path, devices):
    """Save sharded state from an 8-device mesh, restore into a 4-device
    mesh: the elastic resize path for PS-sharded embedding tables."""
    from elasticdl_tpu.common.checkpoint import CheckpointManager

    spec = load_model_spec("elasticdl_tpu.models", "deepfm.model_spec", **DEEPFM_TINY)
    config = JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER)

    mesh8 = create_mesh(devices, num_devices=8)
    t8 = Trainer(spec, config, mesh8)
    state8 = t8.init_state(jax.random.key(0))
    batch = spec.example_batch(32)
    batch["cat"] = np.arange(32 * 26, dtype=np.int32).reshape(32, 26) % 1000
    state8, _ = t8.train_step(state8, t8.shard_batch(batch))

    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(1, jax.device_get(state8), wait=True)

    mesh4 = create_mesh(devices, num_devices=4)
    t4 = Trainer(spec, config, mesh4)
    template = t4.init_state(jax.random.key(1))  # different init, target shardings
    restored = ckpt.restore(template)
    assert int(restored.step) == 1
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state8)),
        jax.tree.leaves(jax.device_get(restored)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-6)

    # And the restored state trains on the smaller mesh.
    state4, metrics = t4.train_step(restored, t4.shard_batch(batch))
    assert int(state4.step) == 2
    assert np.isfinite(float(metrics["loss"]))
    ckpt.close()


def test_elastic_reform_resumes_from_checkpoint(tmp_path, devices):
    """With checkpointing on, a membership change makes the worker reload the
    snapshot (the reference's elastic-Horovod restore path, SURVEY.md §3.5)."""
    config, servicer, reader, spec = _deepfm_job(
        tmp_path,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_steps=2,
        lease_batch=1,  # the GetTask counter below is a per-task schedule
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices, devices_per_worker=4,
    )
    orig_get_task = servicer.GetTask
    counter = {"n": 0}

    def get_task_with_join(req):
        counter["n"] += 1
        if counter["n"] == 4:
            servicer.rendezvous.register("phantom")
        return orig_get_task(req)

    servicer.GetTask = get_task_with_join
    result = worker.run()
    assert result["reforms"] == 1
    # The job's final step count reflects a rewind to the last snapshot:
    # work since the checkpoint was re-done, never skipped.
    assert result["step"] >= 12
    assert servicer.dispatcher.finished()


def test_sharded_moments_survive_2_4_2_reform(devices):
    """The elastic twist of the r11 sharded optimizer: an in-process
    2->4->2 resize must REDISTRIBUTE the existing Adam moments across the
    new shard layout — bit-exactly, since the canonical bridge is pure
    data movement — never re-initialize them (a silent convergence
    regression on every join/leave).

    Compile accounting rides jitsan's lowering counters (v6, armed
    suite-wide by conftest): each topology's step lowers exactly ONCE —
    on its first dispatch after the reform — and repeat steps at a
    topology add ZERO recompiles, so a reform costs one deliberate
    re-lower and nothing else."""
    from elasticdl_tpu.common import jitsan

    spec = load_model_spec("elasticdl_tpu.models", "deepfm.model_spec", **DEEPFM_TINY)
    config = JobConfig(
        distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
        optimizer_sharding="sharded",
    )
    batch = spec.example_batch(32)
    batch["cat"] = np.arange(32 * 26, dtype=np.int32).reshape(32, 26) % 1000

    def train_compiles():
        return jitsan.compiles("trainer.train_step")

    t = Trainer(spec, config, create_mesh(devices, num_devices=2))
    state = t.init_state(jax.random.key(0))
    c0 = train_compiles()
    for _ in range(2):
        state, _ = t.train_step(state, t.shard_batch(batch))
    if jitsan.enabled():
        # One lowering for the 2-way build; the second step adds zero.
        assert train_compiles() == c0 + 1
    before = t.host_state(state)  # canonical: param-shaped moments

    # 2 -> 4: the worker reform path (set_mesh + canonical re-placement).
    t.set_mesh(create_mesh(devices, num_devices=4))
    state = t.shard_state(before)
    mid = t.host_state(state)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(mid)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    state, m4 = t.train_step(state, t.shard_batch(batch))
    assert np.isfinite(float(m4["loss"]))
    state, m4b = t.train_step(state, t.shard_batch(batch))
    assert np.isfinite(float(m4b["loss"]))
    if jitsan.enabled():
        # The reform re-lowered exactly once for the 4-way topology; the
        # repeat step at 4-way added zero.
        assert train_compiles() == c0 + 2

    # 4 -> 2, carrying the steps trained at 4-way.
    after4 = t.host_state(state)
    t.set_mesh(create_mesh(devices, num_devices=2))
    state = t.shard_state(t.host_state(state))
    back = t.host_state(state)
    for a, b in zip(jax.tree.leaves(after4), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    state, m2 = t.train_step(state, t.shard_batch(batch))
    assert int(state.step) == 5 and np.isfinite(float(m2["loss"]))
    if jitsan.enabled():
        assert train_compiles() == c0 + 3  # one re-lower back at 2-way


def test_sharded_checkpoint_restores_across_world_sizes(tmp_path, devices):
    """Checkpoints hold the CANONICAL optimizer layout in every mode, so a
    save from a 4-way sharded trainer restores into a 2-way sharded
    trainer AND into a replicated one — dense state and moments equal."""
    from elasticdl_tpu.common.checkpoint import CheckpointManager

    spec = load_model_spec("elasticdl_tpu.models", "deepfm.model_spec", **DEEPFM_TINY)

    def cfg(mode):
        return JobConfig(
            distribution_strategy=DistributionStrategy.PARAMETER_SERVER,
            optimizer_sharding=mode,
        )

    batch = spec.example_batch(32)
    batch["cat"] = np.arange(32 * 26, dtype=np.int32).reshape(32, 26) % 1000
    t4 = Trainer(spec, cfg("sharded"), create_mesh(devices, num_devices=4))
    state4 = t4.init_state(jax.random.key(0))
    for _ in range(2):
        state4, _ = t4.train_step(state4, t4.shard_batch(batch))
    canonical = t4.host_state(state4)

    ckpt = CheckpointManager(str(tmp_path / "ckpt"))
    ckpt.save(2, canonical, wait=True)  # the worker's save layout

    for n_dev, mode in ((2, "sharded"), (8, "sharded"), (4, "replicated")):
        t = Trainer(spec, cfg(mode), create_mesh(devices, num_devices=n_dev))
        template = t.init_state(jax.random.key(1))  # different init
        restored = t.adopt_restored(
            ckpt.restore(t.restore_template(template))
        )
        assert int(restored.step) == 2
        got = t.host_state(restored)
        for a, b in zip(jax.tree.leaves(canonical), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # And it trains on the target topology.
        state, metrics = t.train_step(restored, t.shard_batch(batch))
        assert int(state.step) == 3
        assert np.isfinite(float(metrics["loss"]))
    ckpt.close()


def test_scale_4_8_4_with_sharded_optimizer(tmp_path, devices):
    """The full worker elastic scenario (phantom join + leave) with the
    ZeRO-sharded optimizer on: reforms reshard the optimizer state through
    the canonical bridge and the job still completes every task exactly
    once."""
    config, servicer, reader, spec = _deepfm_job(
        tmp_path, lease_batch=1, optimizer_sharding="sharded"
    )
    worker = Worker(
        config, DirectMasterProxy(servicer), reader,
        worker_id="w0", spec=spec, devices=devices, devices_per_worker=4,
    )
    orig_get_task = servicer.GetTask
    counter = {"n": 0}

    def get_task_with_events(req):
        counter["n"] += 1
        if counter["n"] == 3:
            servicer.rendezvous.register("phantom")
        elif counter["n"] == 5:
            servicer.rendezvous.remove("phantom")
        return orig_get_task(req)

    servicer.GetTask = get_task_with_events
    result = worker.run()
    assert result["reforms"] == 2
    assert servicer.dispatcher.finished()
    assert result["step"] == 12  # no step lost or repeated
