"""jitsan (v6): deterministic retrace detection, disabled-mode identity,
variant budgets, the gauge/artifact bridges, and the transfer-guard
window — the runtime twin of graftlint's jit-discipline passes."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.common import gauge, jitsan
from elasticdl_tpu.common.jax_compat import jit_compiled, jit_donating


# Registry names are process-global: each test below uses its own
# distinct "test.<x>" literal and asserts DELTAS, never absolute counts.

# ---- counting + budgets ----------------------------------------------------

def test_same_shape_never_relowers():
    f = jit_compiled(lambda x: x * 2, name="test.stable", expected_variants=1)
    base = jitsan.compiles("test.stable")
    f(jnp.ones((4,)))
    assert jitsan.compiles("test.stable") == base + 1
    for _ in range(3):
        f(jnp.ones((4,)))
    # Steady state: zero further lowerings — the contract every
    # recompile-free test in the suite asserts through this counter.
    assert jitsan.compiles("test.stable") == base + 1


def test_shape_drift_raises_deterministically():
    f = jit_compiled(lambda x: x + 1, name="test.drift", expected_variants=1)
    f(jnp.ones((4,)))
    with pytest.raises(jitsan.JitSanViolation) as e:
        f(jnp.ones((8,)))  # second shape: one lowering past the budget
    assert "test.drift" in str(e.value) and "expected_variants=1" in str(e.value)
    # Deterministic, not flaky: the SAME drifting call raises again (a
    # third distinct shape), while the original shape stays served from
    # the compile cache.
    assert float(f(jnp.ones((4,)))[0]) == 2.0
    with pytest.raises(jitsan.JitSanViolation):
        f(jnp.ones((16,)))


def test_variant_budget_allows_declared_shapes():
    # expected_variants=2 is the serving bucket story: two padded shapes
    # are the declared contract, the third is the violation.
    f = jit_compiled(lambda x: x.sum(), name="test.buckets", expected_variants=2)
    f(jnp.ones((4,)))
    f(jnp.ones((8,)))
    with pytest.raises(jitsan.JitSanViolation):
        f(jnp.ones((16,)))


def test_instances_carry_their_own_budget():
    # Two structural builds under ONE name (the trainer's mask/no-mask
    # variants): each instance may lower its own budget's worth.
    a = jit_compiled(lambda x: x * 1, name="test.twin", expected_variants=1)
    b = jit_compiled(lambda x: x * 3, name="test.twin", expected_variants=1)
    base = jitsan.compiles("test.twin")
    a(jnp.ones((4,)))
    b(jnp.ones((4,)))
    assert jitsan.compiles("test.twin") == base + 2
    rec = jitsan.stats()["test.twin"]
    assert rec["instances"] >= 2 and rec["budget"] == 1


def test_jit_donating_counts_and_still_donates():
    f = jit_donating(
        lambda s, b: s + b, name="test.donate", expected_variants=1
    )
    s = jnp.ones((4,))
    base = jitsan.compiles("test.donate")
    out = f(s, jnp.ones((4,)))
    assert jitsan.compiles("test.donate") == base + 1
    assert s.is_deleted()  # donation survived the counting wrapper
    assert float(out[0]) == 2.0


# ---- disabled mode ---------------------------------------------------------

def test_disabled_mode_returns_plain_jit(monkeypatch):
    monkeypatch.setenv("GRAFT_JITSAN", "0")
    assert not jitsan.enabled()
    before = dict(jitsan.stats())
    f = jit_compiled(lambda x: x * 2, name="test.disabled")
    g = jit_donating(lambda s, b: s + b, name="test.disabled")
    # Nothing registered: the declaration costs nothing when disabled.
    assert jitsan.stats() == before
    # And the callables are the PLAIN jitted functions — the wrapped
    # (counting) spelling would expose the shim, not the user function.
    assert float(f(jnp.ones(()))) == 2.0
    s = jnp.ones(())
    g(s, jnp.ones(()))
    assert s.is_deleted()


# ---- gauge + artifact bridges ----------------------------------------------

def test_gauge_bridge_publishes_per_fn_counts():
    f = jit_compiled(lambda x: x - 1, name="test.gaugefn", expected_variants=1)
    f(jnp.ones((4,)))
    reg = gauge.Registry()
    collector = gauge.install_jit_collector(reg)
    try:
        fam = reg.snapshot()["edl_jit_compiles_total"]
        by_fn = {
            s["labels"]["fn"]: s["value"] for s in fam["samples"]
        }
        assert by_fn.get("test.gaugefn", 0) >= 1
    finally:
        reg.remove_collector(collector)


def test_dump_stats_writes_json(tmp_path):
    f = jit_compiled(lambda x: x * 5, name="test.dump", expected_variants=1)
    f(jnp.ones((2,)))
    path = str(tmp_path / "jitsan_stats.json")
    assert jitsan.dump_stats(path) == path
    with open(path) as fh:
        payload = json.load(fh)
    assert payload["test.dump"]["compiles"] >= 1
    assert payload["test.dump"]["budget"] == 1


def test_dump_stats_without_target_is_noop(monkeypatch):
    monkeypatch.delenv("GRAFT_JITSAN_DUMP", raising=False)
    assert jitsan.dump_stats() is None


# ---- transfer guard --------------------------------------------------------

def test_transfer_guard_disarmed_is_nullcontext(monkeypatch):
    monkeypatch.delenv("GRAFT_JITSAN_TRANSFER_GUARD", raising=False)
    with jitsan.transfer_guard():
        # Implicit transfers stay legal: the guard is opt-in.
        assert jax.config.jax_transfer_guard is None
        np.asarray(jax.device_put(np.ones(2)))


def test_transfer_guard_armed_sets_disallow(monkeypatch):
    monkeypatch.setenv("GRAFT_JITSAN_TRANSFER_GUARD", "1")
    assert jitsan.transfer_guard_armed()
    with jitsan.transfer_guard():
        # Introspect the armed level rather than provoking a transfer:
        # XLA's host platform serves arrays zero-copy, so an actual
        # implicit-D2H repro is backend-dependent; the config flip is
        # the deterministic, backend-free half of the contract.
        assert jax.config.jax_transfer_guard == "disallow"
        # Explicit spellings stay legal under "disallow" — the worker's
        # dispatch window relies on exactly this split.
        jax.device_get(jax.device_put(np.ones(2)))
    assert jax.config.jax_transfer_guard is None


def test_transfer_guard_needs_jitsan_enabled(monkeypatch):
    monkeypatch.setenv("GRAFT_JITSAN", "0")
    monkeypatch.setenv("GRAFT_JITSAN_TRANSFER_GUARD", "1")
    assert not jitsan.transfer_guard_armed()


# ---- reset -----------------------------------------------------------------

def test_reset_clears_aggregates_not_budgets():
    f = jit_compiled(lambda x: x / 2, name="test.reset", expected_variants=1)
    f(jnp.ones((4,)))
    assert jitsan.compiles("test.reset") >= 1
    jitsan.reset()
    assert jitsan.compiles("test.reset") == 0
    # The per-instance budget survives the aggregate reset: the violation
    # contract is an instance property.
    with pytest.raises(jitsan.JitSanViolation):
        f(jnp.ones((8,)))
