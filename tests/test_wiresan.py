"""wiresan: both wire directions validated at the rpc boundary, unknown
fields counted (never raised — the additive-compat stance), violations
deterministic, the version mask faithful, and the v1-masked skew fleet
completing a real gRPC job clean (graftlint v8's runtime twin)."""

import os
from concurrent import futures

import grpc
import pytest

from elasticdl_tpu.common import gauge, wiresan
from elasticdl_tpu.common.rpc import (
    JsonRpcClient,
    MessageSchema,
    make_generic_handler,
)

_STR = (str,)
_INT = (int,)
_BOOL = (bool,)

PING_REQ = {
    "Ping": MessageSchema(
        required={"worker_id": _STR}, optional={"lease": _INT},
        since={"lease": 9},
    ),
}
PING_RESP = {
    "Ping": MessageSchema(
        required={"ok": _BOOL}, optional={"eta": _INT}, since={"eta": 12},
    ),
}


@pytest.fixture(autouse=True)
def _isolated():
    wiresan.reset()
    yield
    wiresan.reset()


# ---- check(): the violation grammar ----

def test_missing_required_raises_deterministically():
    msg = {"lease": 2}
    with pytest.raises(wiresan.WireSanViolation) as e1:
        wiresan.check("Ping", msg, PING_REQ, "request")
    # Same message, same violation, same text — a schema bug must repro,
    # not flake.
    with pytest.raises(wiresan.WireSanViolation) as e2:
        wiresan.check("Ping", msg, PING_REQ, "request")
    assert str(e1.value) == str(e2.value)
    assert "request Ping" in str(e1.value)
    assert "worker_id" in str(e1.value)
    assert wiresan.stats()["violations"] == 2


def test_wrong_type_raises_and_bool_is_not_int():
    with pytest.raises(wiresan.WireSanViolation):
        wiresan.check(
            "Ping", {"worker_id": "w", "lease": "4"}, PING_REQ, "request"
        )
    # bool subclasses int; {"lease": True} must not read as lease 1.
    with pytest.raises(wiresan.WireSanViolation):
        wiresan.check(
            "Ping", {"worker_id": "w", "lease": True}, PING_REQ, "request"
        )


def test_unknown_fields_counted_never_raised():
    wiresan.check(
        "Ping", {"worker_id": "w", "new_field": 1, "newer": 2},
        PING_REQ, "request",
    )
    wiresan.check("Ping", {"worker_id": "w", "new_field": 3}, PING_REQ,
                  "request")
    stats = wiresan.stats()
    assert stats["unknown_fields"] == {"Ping": 3}
    assert stats["violations"] == 0


def test_undeclared_method_and_absent_table_pass_unjudged():
    # The PS tier's binary frames and schema-less services: no contract
    # declared, nothing enforced.
    wiresan.check("PullParams", {"anything": object()}, PING_REQ, "request")
    wiresan.check("Ping", {"anything": 1}, None, "request")
    assert wiresan.stats()["unknown_fields"] == {}


def test_gauge_collector_exports_unknown_counts():
    wiresan.check("Ping", {"worker_id": "w", "x": 1}, PING_REQ, "request")
    reg = gauge.Registry()
    collector = gauge.install_wire_collector(reg)
    try:
        fam = reg.snapshot()["edl_wire_unknown_fields_total"]
        by_method = {
            s["labels"]["method"]: s["value"] for s in fam["samples"]
        }
        assert by_method == {"Ping": 1.0}
    finally:
        reg.remove_collector(collector)


# ---- the version mask ----

def test_mask_strips_newer_fields_both_shapes():
    masked = wiresan.mask(
        "Ping", {"worker_id": "w", "lease": 4}, PING_REQ, rev=1
    )
    assert masked == {"worker_id": "w"}
    resp = wiresan.mask("Ping", {"ok": True, "eta": 9}, PING_RESP, rev=1)
    assert resp == {"ok": True}
    # At or past the field's revision nothing strips.
    assert wiresan.mask(
        "Ping", {"ok": True, "eta": 9}, PING_RESP, rev=12
    ) == {"ok": True, "eta": 9}


def test_mask_identity_when_nothing_strips():
    # No copy on the fast path: the SAME object comes back.
    msg = {"worker_id": "w"}
    assert wiresan.mask("Ping", msg, PING_REQ, rev=1) is msg
    assert wiresan.mask("NoSchema", msg, PING_REQ, rev=1) is msg


def test_mask_requires_armed_sanitizer(monkeypatch):
    monkeypatch.setenv("GRAFT_WIRESAN", "0")
    # A mask with the sanitizer off would strip nothing and "pass" by
    # testing the current protocol — fail loud instead.
    with pytest.raises(wiresan.WireSanError):
        wiresan.set_mask(1)
    monkeypatch.setenv("GRAFT_WIRESAN_MASK", "1")
    with pytest.raises(wiresan.WireSanError):
        wiresan.mask_rev()


def test_set_mask_overrides_env(monkeypatch):
    monkeypatch.setenv("GRAFT_WIRESAN_MASK", "9")
    wiresan.set_mask(1)
    assert wiresan.mask_rev() == 1
    wiresan.set_mask(None)
    assert wiresan.mask_rev() == 9


# ---- both ends over real gRPC ----

def _serve(methods, schemas=None, response_schemas=None):
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers((
        make_generic_handler(
            "test.WireSvc", methods, schemas=schemas,
            response_schemas=response_schemas,
        ),
    ))
    port = server.add_insecure_port("localhost:0")
    server.start()
    return server, f"localhost:{port}"


def test_server_side_response_validation():
    # The handler returns a response missing its required field: the
    # violation must surface in the SERVER's frame (the client sees a
    # remote error, not a silent malformed dict).
    server, addr = _serve(
        {"Ping": lambda req: {}},
        schemas=PING_REQ, response_schemas=PING_RESP,
    )
    try:
        client = JsonRpcClient(
            addr, service_name="test.WireSvc",
            schemas=PING_REQ, response_schemas={},
        )
        client.wait_ready(10.0)
        with pytest.raises(grpc.RpcError):
            client.call("Ping", {"worker_id": "w"}, timeout_s=10.0)
        assert wiresan.stats()["violations"] >= 1
    finally:
        server.stop(grace=0)


def test_client_side_response_validation_and_clean_path():
    server, addr = _serve(
        {"Ping": lambda req: {"ok": True, "eta": 3}},
        schemas=PING_REQ, response_schemas=PING_RESP,
    )
    try:
        good = JsonRpcClient(
            addr, service_name="test.WireSvc",
            schemas=PING_REQ, response_schemas=PING_RESP,
        )
        good.wait_ready(10.0)
        assert good.call(
            "Ping", {"worker_id": "w"}, timeout_s=10.0
        ) == {"ok": True, "eta": 3}
        # A client whose schema demands a field this server never sends:
        # the violation lands in the CALLER's frame, field named.
        strict = JsonRpcClient(
            addr, service_name="test.WireSvc",
            schemas=PING_REQ,
            response_schemas={
                "Ping": MessageSchema(required={"bogus": _INT}),
            },
        )
        with pytest.raises(wiresan.WireSanViolation, match="bogus"):
            strict.call("Ping", {"worker_id": "w"}, timeout_s=10.0)
    finally:
        server.stop(grace=0)


def test_client_masks_request_and_response():
    seen = {}

    def ping(req):
        seen.update(req)
        return {"ok": True, "eta": 3}

    server, addr = _serve(
        {"Ping": ping}, schemas=PING_REQ, response_schemas=PING_RESP,
    )
    try:
        client = JsonRpcClient(
            addr, service_name="test.WireSvc",
            schemas=PING_REQ, response_schemas=PING_RESP,
        )
        client.wait_ready(10.0)
        wiresan.set_mask(1)
        try:
            resp = client.call(
                "Ping", {"worker_id": "w", "lease": 4}, timeout_s=10.0
            )
        finally:
            wiresan.set_mask(None)
        assert "lease" not in seen          # request masked on the way out
        assert resp == {"ok": True}         # response masked on the way in
    finally:
        server.stop(grace=0)


def test_disabled_mode_is_identity(monkeypatch):
    # GRAFT_WIRESAN off: no validation, no counting, no masking — the
    # call path must behave exactly as before r22.
    monkeypatch.delenv("GRAFT_WIRESAN", raising=False)
    server, addr = _serve(
        {"Ping": lambda req: {}},  # malformed response
        schemas=PING_REQ, response_schemas=PING_RESP,
    )
    try:
        client = JsonRpcClient(
            addr, service_name="test.WireSvc",
            schemas=PING_REQ, response_schemas=PING_RESP,
        )
        client.wait_ready(10.0)
        assert client.call("Ping", {"worker_id": "w"}, timeout_s=10.0) == {}
        assert wiresan.stats()["violations"] == 0
        assert wiresan.stats()["unknown_fields"] == {}
    finally:
        server.stop(grace=0)


def test_version_skew_roundtrip_real_grpc():
    # The additive-compat proof: a v1-masked worker (no lease batching,
    # no seq ledger, no envelopes) completes a real gRPC job against a
    # current master — zero violations, zero double-trains.  Same driver
    # that stamps artifacts/wire_skew.json into the LINT artifact.
    from tools.wire_skew import run_skew

    assert os.environ.get("GRAFT_WIRESAN") == "1"  # conftest arms it
    verdict = run_skew(4, log=lambda m: None)
    assert verdict["ok"], verdict["errors"]
    assert verdict["tasks_done"] == 4
    assert verdict["wire_violations"] == 0
    assert verdict["job_status"]["duplicate_done"] == 0
    assert verdict["job_status"]["stale_reports"] == 0
    assert verdict["job_status"]["finished"] is True
