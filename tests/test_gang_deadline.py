"""Deadline-bounded gang boundary (r13): dispatcher skip accounting and
the servicer's straggler-skip protocol, driven with a fake clock so the
deadline mechanics are deterministic.  The subprocess-gang twin lives in
tools/chaos_bench.py's stall fleet."""

import pytest

from elasticdl_tpu.common import trace
from elasticdl_tpu.data.reader import Shard
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher


def _shards(n, size=10):
    return [Shard("f", i * size, (i + 1) * size) for i in range(n)]


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


# ---------------------------------------------------------------------------
# dispatcher: bounded skip accounting
# ---------------------------------------------------------------------------

class TestSkipAccounting:
    def test_skip_requeues_without_charging_retry_budget(self):
        d = TaskDispatcher(_shards(2), task_skip_budget=2)
        t = d.get_task("gang")
        lost = d.skip_tasks("gang")
        assert [x.task_id for x in lost] == [t.task_id]
        c = d.counts()
        assert c["skipped"] == 1 and c["skip_counts"] == {t.task_id: 1}
        # Requeued at the FRONT, retry budget untouched: the same shard
        # hands out again and can still fail max_retries times.
        t2 = d.get_task("w0")
        assert t2.shard == t.shard
        assert d._failed_counts == {}

    def test_skips_beyond_budget_charge_like_failures(self):
        d = TaskDispatcher(_shards(1), task_skip_budget=1, max_task_retries=1)
        t = d.get_task("gang")
        d.skip_tasks("gang")                    # skip 1: free
        d.get_task("gang")
        d.skip_tasks("gang")                    # skip 2: charged (fail 1/1)
        assert d._failed_counts == {t.task_id: 1}
        d.get_task("gang")
        d.skip_tasks("gang")                    # skip 3: fail 2 > budget
        c = d.counts()
        assert c["abandoned"] == 1 and c["skipped"] == 3
        assert d.finished()  # the poison shard cannot wedge the job

    def test_skip_after_stop_drops(self):
        d = TaskDispatcher(_shards(1), task_skip_budget=2)
        d.get_task("gang")
        d.stop()
        d.skip_tasks("gang")
        assert d.counts()["todo"] == 0 and d.finished()

    def test_skipped_task_still_trains_exactly_once(self):
        d = TaskDispatcher(_shards(1), task_skip_budget=2)
        t = d.get_task("gang")
        d.skip_tasks("gang")
        t2 = d.get_task("w1")
        assert t2.task_id == t.task_id
        assert d.report(t2.task_id, True)
        c = d.counts()
        assert c["done"] == 1 and c["duplicate_done"] == 0 and d.finished()

    def test_duplicate_done_counter(self):
        d = TaskDispatcher(_shards(1))
        t = d.get_task("w0")
        assert d.report(t.task_id, True)
        assert not d.report(t.task_id, True)  # late duplicate: rejected
        assert not d.report(t.task_id, False)  # late failure: benign
        assert d.counts()["duplicate_done"] == 1


# ---------------------------------------------------------------------------
# servicer: the deadline protocol over GetGroupTask/Heartbeat
# ---------------------------------------------------------------------------

def _gang(n_shards=6, deadline_ms=200.0, budget=2):
    clock = FakeClock()
    dispatcher = TaskDispatcher(
        _shards(n_shards), task_skip_budget=budget, clock=clock
    )
    rendezvous = RendezvousServer(heartbeat_timeout_s=1e9, clock=clock)
    servicer = MasterServicer(
        dispatcher, rendezvous=rendezvous,
        gang_deadline_ms=deadline_ms, clock=clock,
    )
    return servicer, clock


def _join(servicer, *workers):
    for w in workers:
        servicer.RegisterWorker({"worker_id": w})
    version = servicer.rendezvous.version()
    for w in workers:
        servicer.Heartbeat({"worker_id": w, "version": version})
    return version


def _pull(servicer, worker, seq, version):
    return servicer.GetGroupTask(
        {"worker_id": worker, "seq": seq, "version": version}
    )


def test_gang_deadline_skips_straggler_and_preserves_exactly_once():
    trace.configure(enabled=True)
    trace.default().clear()
    try:
        servicer, clock = _gang()
        d = servicer.dispatcher
        v = _join(servicer, "w0", "w1")

        # Both ranks cross boundary 0 together; the gang trains task 0.
        e0 = _pull(servicer, "w0", 0, v)
        assert _pull(servicer, "w1", 0, v) == e0 and e0["task"] is not None
        servicer.ReportTaskResult({
            "worker_id": "w1", "task_id": e0["task"]["task_id"],
            "task_type": "training", "success": True,
        })

        # w1 begins dispatching entry 1 (arrival counter 2) and blocks in
        # the collective; w0 stalls before arriving (counter frozen at
        # 1).  The beats carry the divergence.  Within the deadline
        # nothing happens; past it the heartbeat-driven check skips w0.
        e1 = _pull(servicer, "w1", 1, v)
        assert e1["task"] is not None
        in_flight = e1["task"]["task_id"]
        servicer.Heartbeat({"worker_id": "w0", "version": v, "gang_seq": 1})
        servicer.Heartbeat({"worker_id": "w1", "version": v, "gang_seq": 2})
        clock.advance(0.1)
        assert servicer.Heartbeat(
            {"worker_id": "w1", "version": v, "gang_seq": 2}
        )["version"] == v
        clock.advance(0.15)  # now 0.25s past the front's arrival at 2
        resp = servicer.Heartbeat(
            {"worker_id": "w1", "version": v, "gang_seq": 2}
        )
        assert resp["version"] != v  # membership bumped: w0 was skipped

        status = servicer.JobStatus({})
        assert status["skipped_ranks"] == {"w0": 1}
        assert status["skip_counts"] == {in_flight: 1}
        assert status["skipped"] == 1
        names = [e["name"] for e in trace.default().export()]
        assert "gang:skip" in names and "lease:skip" in names

        # The straggler's poll of the dead world reads stale -> restart.
        assert _pull(servicer, "w0", 1, v)["stale"]

        # Both restart and re-register; the reformed gang drains the log
        # from seq 0 — the skipped task requeued exactly once, so done
        # lands exactly on the shard count with zero duplicates.
        v2 = _join(servicer, "w0", "w1")
        seq = 0
        while True:
            ea = _pull(servicer, "w0", seq, v2)
            eb = _pull(servicer, "w1", seq, v2)
            assert ea == eb
            if ea["finished"]:
                break
            if ea["task"] is None:
                pytest.fail("gang starved: no entry and not finished")
            servicer.ReportTaskResult({
                "worker_id": "w0", "task_id": ea["task"]["task_id"],
                "task_type": "training", "success": True,
            })
            seq += 1
        final = d.counts()
        assert final["done"] == 6 and final["duplicate_done"] == 0
        assert final["abandoned"] == 0 and final["skipped"] == 1
    finally:
        trace.configure(enabled=False)
        trace.default().clear()


def test_gang_deadline_disabled_never_skips():
    servicer, clock = _gang(deadline_ms=0.0)
    v = _join(servicer, "w0", "w1")
    _pull(servicer, "w0", 0, v)
    _pull(servicer, "w1", 0, v)
    servicer.Heartbeat({"worker_id": "w0", "version": v, "gang_seq": 1})
    servicer.Heartbeat({"worker_id": "w1", "version": v, "gang_seq": 2})
    clock.advance(3600.0)
    resp = servicer.Heartbeat(
        {"worker_id": "w1", "version": v, "gang_seq": 2}
    )
    assert resp["version"] == v  # nobody evicted, however long the lag
    assert servicer.JobStatus({})["skipped_ranks"] == {}


def test_gang_deadline_waits_inside_window():
    servicer, clock = _gang(deadline_ms=500.0)
    v = _join(servicer, "w0", "w1")
    _pull(servicer, "w0", 0, v)
    _pull(servicer, "w1", 0, v)
    servicer.Heartbeat({"worker_id": "w0", "version": v, "gang_seq": 1})
    servicer.Heartbeat({"worker_id": "w1", "version": v, "gang_seq": 2})
    clock.advance(0.4)  # inside the window: a slow-but-alive rank is fine
    assert servicer.Heartbeat(
        {"worker_id": "w1", "version": v, "gang_seq": 2}
    )["version"] == v


def test_gang_deadline_heartbeat_progress_sees_wedged_batch():
    """Lease batching leaves every rank's LAST boundary ask at the same
    seq — from asks alone a mid-batch straggler is invisible (its healthy
    peers are wedged in the collective ON it and never reach the next
    boundary either; consumption freezes at the same value gang-wide).
    The heartbeat's ``gang_seq`` ARRIVAL counter is the signal that
    diverges: a healthy rank counts an entry when it BEGINS dispatching
    it — it arrived at the collective, then blocked inside — while the
    straggler that never reached the boundary never counts it.  The skip
    must fire on that signal alone."""
    servicer, clock = _gang()
    v = _join(servicer, "w0", "w1")
    servicer.GetGroupTask(
        {"worker_id": "w0", "seq": 0, "version": v, "lease": 4}
    )
    servicer.GetGroupTask(
        {"worker_id": "w1", "seq": 0, "version": v, "lease": 4}
    )
    servicer.Heartbeat({"worker_id": "w1", "version": v, "gang_seq": 3})
    servicer.Heartbeat({"worker_id": "w0", "version": v, "gang_seq": 2})
    clock.advance(0.25)
    resp = servicer.Heartbeat({"worker_id": "w1", "version": v, "gang_seq": 3})
    assert resp["version"] != v  # w0 skipped on heartbeat progress alone
    assert servicer.JobStatus({})["skipped_ranks"] == {"w0": 1}


def test_gang_progress_is_version_gated_and_monotonic():
    """A beat from a stale world must not seed the current world's
    deadline clock, and a late lower-seq signal must not regress a rank's
    recorded progress (which would fabricate a straggler)."""
    servicer, clock = _gang()
    v = _join(servicer, "w0", "w1")
    _pull(servicer, "w0", 0, v)
    _pull(servicer, "w1", 0, v)
    # Stale-version beat: ignored — the head must not advance.
    servicer.Heartbeat({"worker_id": "w1", "version": v - 1, "gang_seq": 5})
    clock.advance(0.25)
    assert servicer.maybe_skip_straggler() is None
    # Monotonic: a late gang_seq=0 beat cannot drag w1 behind w0.
    servicer.Heartbeat({"worker_id": "w1", "version": v, "gang_seq": 2})
    servicer.Heartbeat({"worker_id": "w1", "version": v, "gang_seq": 0})
    servicer.Heartbeat({"worker_id": "w0", "version": v, "gang_seq": 2})
    clock.advance(0.25)
    assert servicer.maybe_skip_straggler() is None  # nobody actually lags


def test_deadline_evicted_rank_beats_cannot_revive_membership():
    """The straggler's process is often still ALIVE after the skip (a
    stall, not a crash) — its background liveness beat keeps arriving,
    and the rendezvous heartbeat's unknown-worker path would re-register
    it unconfirmed, undoing the eviction and wedging the reform on a
    rank that cannot confirm the new version.  The servicer must refuse
    the revival (and the rank's stale gang progress) until the rank
    deliberately re-registers — its restart path."""
    servicer, clock = _gang()
    rv = servicer.rendezvous
    v = _join(servicer, "w0", "w1")
    _pull(servicer, "w0", 0, v)
    _pull(servicer, "w1", 0, v)
    servicer.Heartbeat({"worker_id": "w0", "version": v, "gang_seq": 1})
    servicer.Heartbeat({"worker_id": "w1", "version": v, "gang_seq": 2})
    clock.advance(0.25)
    # The straggler's OWN beat trips the deadline: the skip fires inside
    # this very Heartbeat call, and the response must already refuse the
    # revival (the eviction re-check runs after the skip).
    resp = servicer.Heartbeat({"worker_id": "w0", "version": v, "gang_seq": 1})
    assert servicer.JobStatus({})["skipped_ranks"] == {"w0": 1}
    v_evicted = rv.version()
    assert resp["version"] == v_evicted and resp["version"] != v
    assert "w0" not in rv.membership()["workers"]
    # The wedged rank's beat thread keeps beating: no revival, no version
    # churn — the response's version mismatch is what drives its restart.
    for _ in range(3):
        resp = servicer.Heartbeat(
            {"worker_id": "w0", "version": v, "gang_seq": 1}
        )
        assert resp["version"] == v_evicted and resp["version"] != v
    assert "w0" not in rv.membership()["workers"]
    assert rv.version() == v_evicted
    # Its stale gang_seq stayed out of the deadline accounting: only w1
    # remains at the boundary, and nobody lags anyone.
    clock.advance(0.25)
    assert servicer.maybe_skip_straggler() is None
    # A stale arrival re-seeded by a beat that lost the check-then-act
    # race against the eviction (interleaving: first evicted-check passes,
    # the skip lands, note_gang_progress re-inserts) is dropped by the
    # next refused beat — left behind, it would fake a SECOND eviction of
    # the same stall one deadline later, double-charging the skip budget.
    with servicer._group_lock:
        servicer._gang_arrivals["w0"] = (1, clock())
    servicer.Heartbeat({"worker_id": "w0", "version": v, "gang_seq": 1})
    with servicer._group_lock:
        assert "w0" not in servicer._gang_arrivals
    clock.advance(0.25)
    assert servicer.maybe_skip_straggler() is None
    assert servicer.JobStatus({})["skipped_ranks"] == {"w0": 1}
    # Deliberate re-registration (the restart path) lifts the block.
    v2 = _join(servicer, "w0", "w1")
    assert "w0" in rv.membership()["workers"]
    assert servicer.Heartbeat(
        {"worker_id": "w0", "version": v2, "gang_seq": 0}
    )["version"] == v2


def test_gang_deadline_skips_one_rank_per_window():
    """Three ranks, two stragglers: one eviction per deadline window —
    skips stay attributable one rank at a time, and the second laggard
    gets a fresh deadline against the re-formed gang."""
    servicer, clock = _gang()
    v = _join(servicer, "w0", "w1", "w2")
    for w in ("w0", "w1", "w2"):
        _pull(servicer, w, 0, v)  # establishes the lockstep world
    for w in ("w0", "w1"):
        servicer.Heartbeat({"worker_id": w, "version": v, "gang_seq": 1})
    servicer.Heartbeat({"worker_id": "w2", "version": v, "gang_seq": 2})
    clock.advance(0.25)
    assert servicer.maybe_skip_straggler() in ("w0", "w1")
    assert servicer.maybe_skip_straggler() is None  # clock restarted
    assert sum(servicer.JobStatus({})["skipped_ranks"].values()) == 1
