"""Model-zoo parity tests (BASELINE.json configs #2-#4): every model runs a
step on the 8-device mesh under its intended strategy, trains, and — the key
hybrid check — the ParameterServer (mesh-sharded tables) step matches the
AllReduce (replicated tables) step numerically on the same global batch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.common.config import DistributionStrategy, JobConfig
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.trainer import Trainer

BATCH = 64


def _tabular_batch(rng, n, n_dense, n_cat, max_id=5000):
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "dense": jax.random.uniform(k1, (n, n_dense), jnp.float32, 0, 100),
        "cat": jax.random.randint(k2, (n, n_cat), 0, max_id),
        "labels": jax.random.bernoulli(k3, 0.3, (n,)).astype(jnp.int32),
    }


def _cifar_batch(rng, n):
    k1, k2 = jax.random.split(rng)
    return {
        "images": jax.random.normal(k1, (n, 32, 32, 3), jnp.float32),
        "labels": jax.random.randint(k2, (n,), 0, 10),
    }


def _deepfm_spec():
    return load_model_spec(
        "elasticdl_tpu.models",
        "deepfm.model_spec",
        compute_dtype="float32",
        buckets_per_feature=64,
        hidden=(32, 32),
    )


def _widedeep_spec():
    return load_model_spec(
        "elasticdl_tpu.models",
        "wide_deep.model_spec",
        compute_dtype="float32",
        buckets=32,
        hidden=(32,),
    )


def _resnet_spec():
    return load_model_spec(
        "elasticdl_tpu.models",
        "cifar10_resnet.model_spec",
        compute_dtype="float32",
        depth=14,
        width=8,
    )


@pytest.mark.parametrize(
    "spec_fn,batch_fn",
    [
        (_deepfm_spec, lambda r, n: _tabular_batch(r, n, 13, 26)),
        (_widedeep_spec, lambda r, n: _tabular_batch(r, n, 5, 9)),
    ],
    ids=["deepfm", "wide_deep"],
)
def test_ps_strategy_step_and_convergence(devices, spec_fn, batch_fn):
    spec = spec_fn()
    mesh = create_mesh(devices)
    cfg = JobConfig(distribution_strategy=DistributionStrategy.PARAMETER_SERVER)
    trainer = Trainer(spec, cfg, mesh)
    assert trainer.sharded_embeddings
    state = trainer.init_state(jax.random.key(0))
    batch = trainer.shard_batch(batch_fn(jax.random.key(1), BATCH))
    first = None
    for _ in range(8):
        state, metrics = trainer.train_step(state, batch)
        if first is None:
            first = float(metrics["loss"])
        assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < first


@pytest.mark.parametrize("impl", ["dense", "ragged_emulated"])
@pytest.mark.parametrize(
    "spec_fn,batch_fn",
    [
        (_deepfm_spec, lambda r, n: _tabular_batch(r, n, 13, 26)),
        (_widedeep_spec, lambda r, n: _tabular_batch(r, n, 5, 9)),
    ],
    ids=["deepfm", "wide_deep"],
)
def test_ps_matches_allreduce(devices, spec_fn, batch_fn, impl):
    """The hybrid's sharded-table path must produce the same update as plain
    replicated-table allreduce — the decisive numerics check for the
    collective embedding transpose (both lookup routes)."""
    batch = batch_fn(jax.random.key(2), BATCH)
    results = {}
    for strategy in (
        DistributionStrategy.ALLREDUCE,
        DistributionStrategy.PARAMETER_SERVER,
    ):
        spec = spec_fn()
        mesh = create_mesh(devices)
        trainer = Trainer(
            spec,
            JobConfig(
                distribution_strategy=strategy, embedding_lookup_impl=impl
            ),
            mesh,
        )
        state = trainer.init_state(jax.random.key(0))
        state, metrics = trainer.train_step(state, trainer.shard_batch(batch))
        results[strategy] = (
            jax.device_get(state.params),
            float(metrics["loss"]),
        )

    p_ar, loss_ar = results[DistributionStrategy.ALLREDUCE]
    p_ps, loss_ps = results[DistributionStrategy.PARAMETER_SERVER]
    assert abs(loss_ar - loss_ps) < 1e-5
    for a, b in zip(jax.tree.leaves(p_ar), jax.tree.leaves(p_ps)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_resnet_allreduce_step(devices):
    spec = _resnet_spec()
    mesh = create_mesh(devices)
    trainer = Trainer(spec, JobConfig(), mesh)
    state = trainer.init_state(jax.random.key(0))
    batch = trainer.shard_batch(_cifar_batch(jax.random.key(1), 32))
    first = None
    for _ in range(5):
        state, metrics = trainer.train_step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < first


def test_resnet50_builds():
    """Full-size ResNet-50 param shapes build without error (no step — slow on
    fake CPU devices; the real-chip bench covers execution)."""
    spec = load_model_spec(
        "elasticdl_tpu.models", "cifar10_resnet.model_spec", depth=50
    )
    shapes = jax.eval_shape(spec.init, jax.random.key(0))
    n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
    assert n_params > 20_000_000  # ResNet-50 class size


def test_resnet_imagenet_stem_variant(devices):
    """The ImageNet-shaped configuration (224x224 input, 1000-class head,
    7x7/s2 stem + maxpool — tools/bench_all.py 'resnet50_imagenet') trains
    a step at a reduced size: the stride-2 stem halves the spatial dims
    twice before the stages, and the head width follows num_classes."""
    spec = load_model_spec(
        "elasticdl_tpu.models",
        "cifar10_resnet.model_spec",
        compute_dtype="float32",
        depth=14,
        width=8,
        image_size=64,
        num_classes=7,
        imagenet_stem=True,
    )
    mesh = create_mesh(devices)
    cfg = JobConfig(distribution_strategy=DistributionStrategy.ALLREDUCE)
    trainer = Trainer(spec, cfg, mesh)
    state = trainer.init_state(jax.random.key(0))
    rng = np.random.RandomState(3)
    batch = {
        "images": rng.rand(16, 64, 64, 3).astype(np.float32),
        "labels": rng.randint(0, 7, (16,)).astype(np.int32),
    }
    logits = spec.apply(jax.device_get(state).params, batch, train=False)
    assert logits.shape == (16, 7)
    state, metrics = trainer.train_step(state, trainer.shard_batch(batch))
    assert np.isfinite(float(metrics["loss"]))
    # Full-size shapes build: 1000-class ImageNet head + 7x7 stem kernel.
    full = load_model_spec(
        "elasticdl_tpu.models", "cifar10_resnet.model_spec",
        depth=50, image_size=224, num_classes=1000, imagenet_stem=True,
    )
    shapes = jax.eval_shape(full.init, jax.random.key(0))
    assert shapes["stem"]["conv"].shape == (7, 7, 3, 64)
    assert shapes["head"]["w"].shape[-1] == 1000
    n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
    assert 24_000_000 < n_params < 27_000_000  # ImageNet ResNet-50 ~25.6M
